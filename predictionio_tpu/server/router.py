"""Serving-fleet router tier: one front door over N query-server replicas.

``pio deploy`` serves one process on one host; this module is the thin
tier that turns N of those processes into ONE serving surface
(ROADMAP item 2's replication axis):

* **Spread** — queries fan out over the replicas through
  :class:`WeightedSplitter`, the canary ``TrafficSplitter``'s
  error-diffusion discipline generalized to N arms: every arm
  accumulates ``weight/total`` credit per query and the largest
  accumulator wins, so over any window each replica serves exactly
  ``round(N * share)`` (±1) queries — no RNG, deterministic tests, and
  a restarted router resumes the EXACT mid-stream split because the
  accumulators persist through the durable telemetry store
  (``pio_router_splitter_acc``).
* **Health** — every replica is probed at ``/slo.json`` +
  ``/deploy/status.json`` (the readiness surfaces a deployed query
  server already exposes); ``health_fail_after`` consecutive failures
  eject it from rotation, the first healthy probe re-admits it. A
  failed proxy attempt retries on OTHER replicas (``proxy_retries``)
  before surfacing — a replica mid-restart must not fail user queries.
* **Fleet cutovers** — ``POST /deploy.json`` / ``/rollback.json`` on
  the router sequence the release-registry cutover one replica at a
  time in rank order, aborting (and rolling back the already-cut
  replicas) on the first failure: the router is the ONE place a fleet
  deploy is ordered, so replicas can never diverge past one rank.
* **One trace id** — the proxy forwards the request's trace context in
  ``X-Pio-Trace`` (obs/middleware.py adopts it on the replica), so
  router → replica → device is one lineage in the flight recorder; the
  replicas the router spawns inherit it via
  ``parallel/distributed.worker_env``.
* **Autoscaling** — when a ``deploy/fleet.FleetController`` is
  attached, the router feeds it burn/QPS signals off the health probes
  and executes its scale decisions: grow spawns + waits healthy,
  shrink DRAINS the victim (weight zero, in-flight runs to completion)
  before stopping it — zero dropped queries across a scale-down is the
  contract, tested.

Every knob is ``PIO_ROUTER_*`` / server.json ``router`` (see
``utils.server_config.RouterConfig``); metrics are the ``pio_router_*``
family (OBSERVABILITY.md).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Callable, Dict, Optional

import aiohttp
from aiohttp import web

from predictionio_tpu.obs.middleware import (
    add_metrics_routes, observability_middleware,
)
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.trace_context import TRACE_HEADER, record_event
from predictionio_tpu.obs.tracing import capture_context, carried
from predictionio_tpu.utils.server_config import RouterConfig

logger = logging.getLogger("pio.router")

#: default port a router listens on (replicas live at base_port + rank)
DEFAULT_ROUTER_PORT = 8100

#: how long a scale-up waits for the new replica's first healthy probe
SPAWN_HEALTHY_TIMEOUT_S = 60.0

#: per-probe and per-proxy HTTP timeouts — probes must be fast enough
#: that a hung replica cannot stall the whole health sweep
PROBE_TIMEOUT_S = 5.0
PROXY_TIMEOUT_S = 30.0


class WeightedSplitter:
    """The canary error-diffusion splitter generalized to N arms.

    Each :meth:`route` call adds ``weight/total`` credit to every arm
    and picks the arm with the most accumulated credit (ties break on
    the lowest arm id), then debits the winner by 1 — stride
    scheduling, so over any window of N routes each arm serves within
    ±1 of its exact share, deterministically. Arms with zero weight
    (draining or ejected replicas) accrue nothing and can never win.

    The accumulators are the ONLY state; :meth:`state` / :meth:`restore`
    round-trip them through the telemetry store so a restarted router
    resumes the split mid-stream instead of re-seeding at zero (the
    process-local-counter skew the single-arm ``TrafficSplitter`` had).
    """

    def __init__(self, weights: Optional[Dict[int, float]] = None):
        self._weights: Dict[int, float] = {}
        self._acc: Dict[int, float] = {}
        if weights:
            self.set_weights(weights)

    def set_weights(self, weights: Dict[int, float]) -> None:
        """Replace the arm set; surviving arms keep their accumulated
        credit (a scale event must not reshuffle the in-progress
        diffusion of the arms that stay)."""
        self._weights = {int(a): max(0.0, float(w))
                         for a, w in weights.items()}
        self._acc = {a: self._acc.get(a, 0.0) for a in self._weights}

    def route(self, eligible=None) -> Optional[int]:
        """The arm this query goes to, or None when no arm is routable.
        ``eligible`` restricts the draw (retry excluding the arm that
        just failed) without disturbing the other arms' credit."""
        arms = [a for a, w in self._weights.items()
                if w > 0 and (eligible is None or a in eligible)]
        if not arms:
            return None
        total = sum(self._weights[a] for a in arms)
        best = None
        for arm in sorted(arms):
            self._acc[arm] += self._weights[arm] / total
            if best is None or self._acc[arm] > self._acc[best]:
                best = arm
        self._acc[best] -= 1.0
        return best

    def state(self) -> Dict[int, float]:
        return dict(self._acc)

    def restore(self, accs: Dict[int, float]) -> None:
        """Re-seed surviving arms' accumulators from a persisted
        :meth:`state`; junk values (non-numeric, |acc| >= arm count + 1)
        are ignored — a corrupt snapshot must not be worse than the
        cold start it replaces."""
        bound = len(self._acc) + 1.0
        for arm, acc in accs.items():
            try:
                arm = int(arm)
                acc = float(acc)
            except (TypeError, ValueError):
                continue
            if arm in self._acc and abs(acc) < bound:
                self._acc[arm] = acc


@dataclasses.dataclass
class ReplicaHandle:
    """One replica's liveness state as the router sees it."""

    rank: int
    url: str
    proc: object = None             # Popen when the router spawned it
    healthy: bool = False
    fails: int = 0
    draining: bool = False
    inflight: int = 0
    slo: Optional[dict] = None
    deploy_status: Optional[dict] = None
    #: monotonic instant before which the health loop skips this
    #: replica — exponential probe backoff for a dead port (a killed
    #: replica must not be hammered at health_interval_s forever)
    next_probe_at: float = 0.0

    def to_json(self) -> dict:
        active = (self.deploy_status or {}).get("active") or {}
        return {
            "rank": self.rank,
            "url": self.url,
            "healthy": self.healthy,
            "draining": self.draining,
            "inflight": self.inflight,
            "consecutiveFailures": self.fails,
            "sloBreached": bool((self.slo or {}).get("breached")),
            "releaseVersion": active.get("releaseVersion"),
        }


class Router:
    """The router tier (module docstring). ``spawn(rank) -> url |
    ReplicaHandle`` and ``stop(handle)`` are the replica lifecycle
    seams: ``pio router`` injects a ``pio deploy`` subprocess spawner
    (cli/main.py), tests inject in-process stub servers, and a router
    can also front pre-existing replicas via ``replica_urls``."""

    def __init__(self, config: Optional[RouterConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 telemetry=None,
                 spawn: Optional[Callable] = None,
                 stop: Optional[Callable] = None,
                 fleet=None,
                 replica_urls=()):
        self.cfg = config or RouterConfig.from_env()
        self.registry = registry or MetricsRegistry()
        self._telemetry = telemetry
        self._spawn = spawn
        self._stop = stop
        self.fleet = fleet
        self.replicas: Dict[int, ReplicaHandle] = {}
        self.splitter = WeightedSplitter()
        self._session: Optional[aiohttp.ClientSession] = None
        self._health_task: Optional[asyncio.Task] = None
        self._fleet_task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._qps_sample = (time.monotonic(), 0.0)

        r = self.registry
        self._requests = r.counter(
            "pio_router_requests_total",
            "Queries proxied by replica and upstream HTTP status",
            labelnames=("replica", "status"))
        self._proxy_hist = r.histogram(
            "pio_router_proxy_duration_seconds",
            "Router-to-replica proxy wall time (queue + replica + wire)",
            labelnames=("replica",))
        self._retries = r.counter(
            "pio_router_retries_total",
            "Proxy attempts retried on another replica after a failure")
        self._dropped = r.counter(
            "pio_router_dropped_total",
            "Queries failed with no routable replica left to try")
        self._healthy_g = r.gauge(
            "pio_router_replica_healthy",
            "1 while the replica is in rotation, 0 while ejected",
            labelnames=("replica",))
        self._replicas_g = r.gauge(
            "pio_router_replicas",
            "Replicas currently attached (healthy or not)")
        self._acc_g = r.gauge(
            "pio_router_splitter_acc",
            "Error-diffusion accumulator per replica — persisted "
            "through the telemetry store so a restarted router resumes "
            "the exact mid-stream split",
            labelnames=("replica",))
        self._health_total = r.counter(
            "pio_router_health_checks_total",
            "Replica health probes by outcome",
            labelnames=("replica", "outcome"))
        self._deploys = r.counter(
            "pio_router_deploys_total",
            "Fleet-sequenced cutovers by action and outcome",
            labelnames=("action", "outcome"))

        for i, url in enumerate(replica_urls):
            self._attach(ReplicaHandle(rank=i, url=str(url).rstrip("/")))

        self.app = web.Application(middlewares=[
            observability_middleware(self.registry, "router")])
        self.app.on_startup.append(self._on_startup)
        self.app.on_cleanup.append(self._on_cleanup)
        self._routes()

    # -- membership ----------------------------------------------------------
    def _attach(self, handle: ReplicaHandle) -> ReplicaHandle:
        self.replicas[handle.rank] = handle
        self._rebuild_weights()
        return handle

    def _rebuild_weights(self) -> None:
        self.splitter.set_weights({
            rank: 0.0 if h.draining else 1.0
            for rank, h in self.replicas.items()})
        self._replicas_g.set(float(len(self.replicas)))
        for rank, h in self.replicas.items():
            self._healthy_g.set(
                1.0 if h.healthy and not h.draining else 0.0,
                replica=str(rank))
        self._publish_acc()

    def _publish_acc(self) -> None:
        for rank, acc in self.splitter.state().items():
            self._acc_g.set(acc, replica=str(rank))

    def active_count(self) -> int:
        return sum(1 for h in self.replicas.values() if not h.draining)

    def _restore_splitter(self) -> None:
        """Re-seed the diffusion accumulators from the durable
        telemetry store (the restart-skew fix): last persisted
        ``pio_router_splitter_acc`` point per replica wins."""
        if not self.cfg.persist_splitter or self._telemetry is None:
            return
        try:
            accs: Dict[int, float] = {}
            for info in self._telemetry.reader().series(
                    "pio_router_splitter_acc"):
                rep = info.labels.get("replica")
                if rep is None or not info.points:
                    continue
                accs[int(rep)] = float(info.points[-1][1])
            if accs:
                self.splitter.restore(accs)
                self._publish_acc()
                logger.info("splitter state restored for %d replica(s)",
                            len(accs))
        except Exception:
            logger.exception("splitter state restore failed; "
                             "starting from zero accumulators")

    # -- lifecycle -----------------------------------------------------------
    async def _on_startup(self, app) -> None:
        self._loop = asyncio.get_running_loop()
        self._session = aiohttp.ClientSession()
        self._restore_splitter()
        if self._spawn is not None and not self.replicas:
            for rank in range(self.cfg.replicas):
                await self.grow(wait_healthy=False)
        self._health_task = self._loop.create_task(self._health_loop())
        if self.fleet is not None:
            self._fleet_task = self._loop.create_task(self._fleet_loop())

    async def _on_cleanup(self, app) -> None:
        for task in (self._health_task, self._fleet_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        for handle in list(self.replicas.values()):
            if handle.proc is not None:
                await self._terminate(handle)
        if self._session is not None:
            await self._session.close()
        if self._telemetry is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._telemetry.stop)

    def _routes(self) -> None:
        r = self.app.router
        r.add_get("/", self.handle_root)
        r.add_post("/queries.json", self.handle_query)
        # multi-tenant replicas (server/multitenant.py): the tenant
        # path segment rides through to the replica's own gate, so
        # admission/residency decisions stay at the replica where the
        # tenant's SLO engine and budgeter live
        r.add_post("/t/{tenant}/queries.json", self.handle_tenant_query)
        r.add_get("/slo.json", self.handle_slo)
        r.add_get("/fleet/status.json", self.handle_fleet_status)
        r.add_post("/deploy.json", self.handle_deploy)
        r.add_post("/rollback.json", self.handle_rollback)
        add_metrics_routes(self.app, self.registry, default_registry())
        from predictionio_tpu.obs.telemetry import (
            add_history_routes, history_reader_factory,
        )

        add_history_routes(self.app,
                           history_reader_factory(self._telemetry))

    # -- spawn / drain (the fleet controller's actuation surface) ------------
    async def grow(self, wait_healthy: bool = True) -> int:
        """Attach one more replica via the spawner; returns its rank.
        ``wait_healthy`` blocks until its first healthy probe (the
        scale-up contract: capacity exists before the action commits)."""
        if self._spawn is None:
            raise RuntimeError("router has no replica spawner")
        rank = max(self.replicas) + 1 if self.replicas else 0
        spawned = self._spawn(rank)
        if isinstance(spawned, ReplicaHandle):
            spawned.rank = rank
            handle = spawned
        else:
            handle = ReplicaHandle(rank=rank, url=str(spawned).rstrip("/"))
        handle.url = handle.url.rstrip("/")
        self._attach(handle)
        logger.info("replica %d attached at %s", rank, handle.url)
        if wait_healthy:
            ok = await self.wait_replica_healthy(rank)
            if not ok:
                raise RuntimeError(
                    f"replica {rank} ({handle.url}) never became healthy "
                    f"within {SPAWN_HEALTHY_TIMEOUT_S:g}s")
        return rank

    async def wait_replica_healthy(
            self, rank: int,
            timeout_s: float = SPAWN_HEALTHY_TIMEOUT_S) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            handle = self.replicas.get(rank)
            if handle is None:
                return False
            if await self._probe(handle):
                return True
            await asyncio.sleep(
                min(0.1, max(0.01, self.cfg.health_interval_s / 4)))
        return False

    async def drain(self, rank: int,
                    timeout_s: Optional[float] = None) -> bool:
        """Scale-down one replica with the zero-drop discipline: weight
        to zero FIRST (no new queries), in-flight queries run to
        completion (bounded by ``drain_timeout_s``), then stop. Returns
        True when the drain completed with nothing in flight."""
        handle = self.replicas.get(rank)
        if handle is None:
            return True
        handle.draining = True
        self._rebuild_weights()
        deadline = time.monotonic() + (
            self.cfg.drain_timeout_s if timeout_s is None else timeout_s)
        while handle.inflight > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        drained = handle.inflight == 0
        if not drained:
            logger.warning("replica %d drain timed out with %d in flight",
                           rank, handle.inflight)
        await self._terminate(handle)
        self.replicas.pop(rank, None)
        self._healthy_g.set(0.0, replica=str(rank))
        self._rebuild_weights()
        logger.info("replica %d drained and detached (%s)", rank,
                    "clean" if drained else "timeout")
        return drained

    async def _terminate(self, handle: ReplicaHandle) -> None:
        if self._stop is not None:
            try:
                out = self._stop(handle)
                if asyncio.iscoroutine(out):
                    await out
            except Exception:
                logger.exception("replica %d stop hook failed",
                                 handle.rank)
        elif handle.proc is not None:
            try:
                handle.proc.terminate()
                await asyncio.get_running_loop().run_in_executor(
                    None, handle.proc.wait, 10)
            except Exception:
                logger.exception("replica %d terminate failed",
                                 handle.rank)
        handle.proc = None

    # -- health --------------------------------------------------------------
    async def _probe(self, handle: ReplicaHandle) -> bool:
        """One readiness probe: both surfaces a deployed query server
        exposes must answer — /slo.json (burn state feeds the fleet
        controller) and /deploy/status.json (a replica mid-cutover is
        not ready)."""
        try:
            timeout = aiohttp.ClientTimeout(total=PROBE_TIMEOUT_S)
            async with self._session.get(f"{handle.url}/slo.json",
                                         timeout=timeout) as resp:
                if resp.status != 200:
                    raise aiohttp.ClientError(f"slo {resp.status}")
                slo = await resp.json()
            async with self._session.get(
                    f"{handle.url}/deploy/status.json",
                    timeout=timeout) as resp:
                if resp.status != 200:
                    raise aiohttp.ClientError(f"status {resp.status}")
                status = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError,
                OSError):
            handle.fails += 1
            self._health_total.inc(replica=str(handle.rank),
                                   outcome="fail")
            # exponential backoff: a replica that keeps failing gets
            # probed at interval, 2x, 4x ... capped — a dead port is
            # not hammered at health_interval_s, and one successful
            # probe resets the schedule (re-admission stays bounded by
            # the cap, not by how long the replica was down)
            backoff = min(
                self.cfg.health_backoff_cap_s,
                self.cfg.health_interval_s * (2 ** max(0, handle.fails - 1)))
            handle.next_probe_at = time.monotonic() + backoff
            if handle.healthy \
                    and handle.fails >= self.cfg.health_fail_after:
                handle.healthy = False
                self._rebuild_weights()
                logger.warning("replica %d ejected after %d failed "
                               "probes (probe backoff up to %.1fs)",
                               handle.rank, handle.fails,
                               self.cfg.health_backoff_cap_s)
            return False
        handle.slo = slo if isinstance(slo, dict) else None
        handle.deploy_status = status if isinstance(status, dict) else None
        handle.fails = 0
        handle.next_probe_at = 0.0
        self._health_total.inc(replica=str(handle.rank), outcome="ok")
        if not handle.healthy:
            handle.healthy = True
            self._rebuild_weights()
        return True

    async def _health_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.health_interval_s)
            now = time.monotonic()
            for handle in list(self.replicas.values()):
                if handle.draining or now < handle.next_probe_at:
                    continue
                try:
                    await self._probe(handle)
                except Exception:
                    logger.exception("health probe for replica %d blew "
                                     "up", handle.rank)

    # -- the fleet controller loop -------------------------------------------
    def fleet_signals(self):
        """One observation for the autoscaler: fleet QPS from the
        router's own request counter delta, burn from the replicas'
        last /slo.json probes."""
        from predictionio_tpu.deploy.fleet import FleetSignals

        now = time.monotonic()
        total = sum(v for _, v in self._requests.samples())
        last_t, last_total = self._qps_sample
        self._qps_sample = (now, total)
        dt = max(1e-6, now - last_t)
        burning = any(bool((h.slo or {}).get("breached"))
                      for h in self.replicas.values()
                      if h.healthy and not h.draining)
        return FleetSignals(
            burning=burning,
            qps=max(0.0, total - last_total) / dt,
            healthy=sum(1 for h in self.replicas.values()
                        if h.healthy and not h.draining))

    async def _fleet_loop(self) -> None:
        self.fleet.bind(FleetRouterActuator(self, self._loop))
        await self._loop.run_in_executor(None, self.fleet.recover)
        while True:
            await asyncio.sleep(self.cfg.health_interval_s)
            signals = self.fleet_signals()
            ctx = capture_context()
            try:
                # the tick blocks on spawn/drain — keep it off the loop
                # (the proxy hot path must keep serving THROUGH a scale
                # action; that concurrency is the zero-drop test)
                await self._loop.run_in_executor(
                    None, lambda: self._fleet_tick(ctx, signals))
            except Exception:
                logger.exception("fleet controller tick failed")

    def _fleet_tick(self, ctx, signals) -> None:
        with carried(ctx, "fleet_tick", record=False):
            self.fleet.tick(signals)

    # -- handlers ------------------------------------------------------------
    async def handle_root(self, request) -> web.Response:
        return web.json_response({
            "service": "router",
            "replicas": [h.to_json()
                         for _, h in sorted(self.replicas.items())],
        })

    async def handle_query(self, request) -> web.Response:
        return await self._proxy_query(request, "/queries.json")

    async def handle_tenant_query(self, request) -> web.Response:
        tenant = request.match_info["tenant"]
        return await self._proxy_query(
            request, f"/t/{tenant}/queries.json")

    async def _proxy_query(self, request, path: str) -> web.Response:
        body = await request.read()
        headers = {"Content-Type": "application/json"}
        ctx = capture_context()
        if ctx is not None:
            # one trace id spans router -> replica -> device: the
            # replica's middleware adopts this as its parent
            headers[TRACE_HEADER] = ctx.encode()
        tried: set = set()
        attempts = 1 + self.cfg.proxy_retries
        last_error = "no routable replica"
        for attempt in range(attempts):
            eligible = {rank for rank, h in self.replicas.items()
                        if h.healthy and not h.draining
                        and rank not in tried}
            rank = self.splitter.route(eligible=eligible)
            if rank is None:
                break
            if attempt > 0:
                self._retries.inc()
            self._publish_acc()
            handle = self.replicas[rank]
            tried.add(rank)
            handle.inflight += 1
            t0 = time.perf_counter()
            try:
                timeout = aiohttp.ClientTimeout(total=PROXY_TIMEOUT_S)
                async with self._session.post(
                        f"{handle.url}{path}", data=body,
                        headers=headers, params=request.query,
                        timeout=timeout) as resp:
                    payload = await resp.read()
                    if resp.status >= 500:
                        raise aiohttp.ClientError(
                            f"replica {rank} answered {resp.status}")
                    self._requests.inc(replica=str(rank),
                                       status=str(resp.status))
                    return web.Response(
                        body=payload, status=resp.status,
                        content_type="application/json")
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as e:
                last_error = str(e) or type(e).__name__
                self._requests.inc(replica=str(rank), status="error")
                handle.fails += 1
                if handle.healthy \
                        and handle.fails >= self.cfg.health_fail_after:
                    handle.healthy = False
                    self._rebuild_weights()
                    logger.warning("replica %d ejected on proxy "
                                   "failures: %s", rank, last_error)
            finally:
                handle.inflight -= 1
                self._proxy_hist.observe(time.perf_counter() - t0,
                                         replica=str(rank))
        self._dropped.inc()
        return web.json_response(
            {"message": f"no replica could serve the query: {last_error}"},
            status=503)

    async def handle_slo(self, request) -> web.Response:
        """The fleet burn view: breached when ANY in-rotation replica
        reports a burn (the scale-up trigger reads the same signal)."""
        docs = {str(rank): h.slo
                for rank, h in sorted(self.replicas.items())
                if h.slo is not None}
        breached = any(bool((d or {}).get("breached"))
                       for d in docs.values())
        return web.json_response({"breached": breached,
                                  "replicas": docs})

    async def handle_fleet_status(self, request) -> web.Response:
        doc = {
            "replicas": [h.to_json()
                         for _, h in sorted(self.replicas.items())],
            "splitter": {str(a): acc
                         for a, acc in self.splitter.state().items()},
            "config": {
                "replicas": self.cfg.replicas,
                "healthIntervalS": self.cfg.health_interval_s,
                "healthFailAfter": self.cfg.health_fail_after,
                "proxyRetries": self.cfg.proxy_retries,
                "drainTimeoutS": self.cfg.drain_timeout_s,
            },
        }
        if self.fleet is not None:
            doc["autoscaler"] = self.fleet.status()
        return web.json_response(doc)

    async def handle_deploy(self, request) -> web.Response:
        try:
            body = await request.json()
        except ValueError:
            body = {}
        return await self._sequenced("/deploy.json", body, "deploy",
                                     request)

    async def handle_rollback(self, request) -> web.Response:
        try:
            body = await request.json()
        except ValueError:
            body = {}
        return await self._sequenced("/rollback.json", body, "rollback",
                                     request)

    async def _sequenced(self, path: str, body: dict, action: str,
                         request) -> web.Response:
        """The fleet-consistent cutover: one replica at a time in rank
        order; the first failure aborts the remainder and rolls the
        already-cut replicas back, so the fleet can never diverge past
        one rank. Recorded as a flight-recorder event under the
        request's trace id."""
        ranks = [rank for rank, h in sorted(self.replicas.items())
                 if not h.draining]
        results = []
        done = []
        for rank in ranks:
            handle = self.replicas.get(rank)
            if handle is None:
                continue
            try:
                timeout = aiohttp.ClientTimeout(total=PROXY_TIMEOUT_S)
                async with self._session.post(
                        f"{handle.url}{path}", json=body,
                        params=request.query, timeout=timeout) as resp:
                    doc = await resp.json()
                    results.append({"replica": rank,
                                    "status": resp.status,
                                    "response": doc})
                    if resp.status >= 400:
                        raise aiohttp.ClientError(
                            f"replica {rank} answered {resp.status}")
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError,
                    OSError) as e:
                if not results or results[-1].get("replica") != rank:
                    results.append({"replica": rank, "status": "error",
                                    "error": str(e) or type(e).__name__})
                unwound = []
                if action == "deploy" and done:
                    unwound = await self._unwind(done, request)
                self._deploys.inc(action=action, outcome="aborted")
                record_event("router_cutover", {
                    "action": action, "outcome": "aborted",
                    "failedReplica": rank, "completed": done,
                    "unwound": unwound})
                return web.json_response(
                    {"action": action, "aborted": True,
                     "failedReplica": rank, "results": results,
                     "unwound": unwound}, status=502)
            done.append(rank)
        self._deploys.inc(action=action, outcome="ok")
        record_event("router_cutover", {"action": action,
                                        "outcome": "ok",
                                        "replicas": done})
        return web.json_response({"action": action, "aborted": False,
                                  "results": results})

    async def _unwind(self, ranks, request) -> list:
        """Best-effort rollback of replicas a failed sequenced deploy
        already cut over — convergence, not a guarantee (a replica that
        cannot answer its rollback stays divergent and its health probe
        keeps it visible)."""
        unwound = []
        for rank in ranks:
            handle = self.replicas.get(rank)
            if handle is None:
                continue
            try:
                timeout = aiohttp.ClientTimeout(total=PROXY_TIMEOUT_S)
                async with self._session.post(
                        f"{handle.url}/rollback.json", json={},
                        params=request.query, timeout=timeout) as resp:
                    if resp.status < 400:
                        unwound.append(rank)
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError):
                logger.exception("unwind rollback failed for replica "
                                 "%d", rank)
        return unwound


class FleetRouterActuator:
    """The fleet controller's synchronous view of the router: the
    controller ticks on an executor thread (scale actions block on
    spawn/drain), so each actuation round-trips into the router's
    event loop and waits for the result."""

    def __init__(self, router: Router, loop: asyncio.AbstractEventLoop):
        self._router = router
        self._loop = loop

    def count(self) -> int:
        return self._router.active_count()

    def scale_up(self) -> int:
        fut = asyncio.run_coroutine_threadsafe(
            self._router.grow(wait_healthy=True), self._loop)
        return fut.result(timeout=SPAWN_HEALTHY_TIMEOUT_S + 30)

    def scale_down(self) -> bool:
        active = sorted(rank for rank, h in self._router.replicas.items()
                        if not h.draining)
        if not active:
            return True
        victim = active[-1]     # newest replica drains first (LIFO)
        fut = asyncio.run_coroutine_threadsafe(
            self._router.drain(victim), self._loop)
        return fut.result(
            timeout=self._router.cfg.drain_timeout_s + 30)


def run_router(config: Optional[RouterConfig] = None,
               ip: str = "localhost",
               spawn: Optional[Callable] = None,
               stop: Optional[Callable] = None,
               replica_urls=(),
               registry: Optional[MetricsRegistry] = None,
               fleet=None) -> None:
    """Serve the router until stopped (the ``pio router`` entry):
    resolves the knob chain, arms durable telemetry (service
    ``router``) so the splitter accumulators and ``pio_router_*``
    history survive restarts, and attaches the autoscaler when
    server.json/env enable it."""
    from predictionio_tpu.utils.server_config import (
        fleet_config, router_config,
    )

    cfg = config or router_config()
    registry = registry or MetricsRegistry()
    from predictionio_tpu.obs.telemetry import build_recorder
    from predictionio_tpu.utils.server_config import telemetry_config

    telemetry = build_recorder(
        "router", telemetry_config(), instance=str(cfg.port),
        registries=[registry, default_registry()])
    if fleet is None:
        fcfg = fleet_config()
        if fcfg.enabled:
            from predictionio_tpu.deploy.fleet import FleetController

            fleet = FleetController(fcfg, registry=registry)
    router = Router(cfg, registry=registry, telemetry=telemetry,
                    spawn=spawn, stop=stop, fleet=fleet,
                    replica_urls=replica_urls)
    logger.info("Router listening on %s:%s over %d replica(s)", ip,
                cfg.port, max(len(router.replicas), cfg.replicas))
    web.run_app(router.app, host=ip, port=cfg.port, print=None)
