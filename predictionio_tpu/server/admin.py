"""Admin REST API — port 7071.

Parity with the reference AdminAPI (tools/.../admin/AdminAPI.scala:45-129)
and its CommandClient (tools/.../admin/CommandClient.scala):

  GET    /                      -> {"status": "alive"}
  GET    /cmd/app               -> list apps
  POST   /cmd/app               -> create app (body {"name": ..., "id"?, "description"?})
  DELETE /cmd/app/<name>        -> delete app + data
  DELETE /cmd/app/<name>/data   -> wipe app event data

Deploy-lifecycle extension (no reference counterpart):

  GET    /cmd/releases          -> all release manifests (deploy/ registry);
                                   ?engineId=&engineVariant= filters
  GET    /cmd/slo               -> the operator's SLO fleet view: this
                                   host's configured spec plus, with
                                   ?targets=host:port[,host:port...],
                                   each query server's live /slo.json
"""

from __future__ import annotations

import asyncio
import logging

from aiohttp import web

from predictionio_tpu.obs.middleware import add_metrics_routes, observability_middleware
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.storage.base import AccessKey, App
from predictionio_tpu.storage.registry import Storage

logger = logging.getLogger("pio.admin")

DEFAULT_PORT = 7071


async def _run(fn, *args):
    return await asyncio.get_running_loop().run_in_executor(None, fn, *args)


async def handle_root(request):
    return web.json_response({"status": "alive"})


async def handle_app_list(request):
    def _list():
        apps = Storage.get_meta_data_apps().get_all()
        keys = Storage.get_meta_data_access_keys()
        return [{"name": a.name, "id": a.id,
                 "accessKeys": [k.key for k in keys.get_by_appid(a.id)]}
                for a in apps]
    return web.json_response({"status": 1, "apps": await _run(_list)})


async def handle_app_new(request):
    try:
        body = await request.json()
        name = body["name"]
    except Exception:
        return web.json_response(
            {"status": 0, "message": "body must be JSON with a name"},
            status=400)

    def _create():
        apps = Storage.get_meta_data_apps()
        if apps.get_by_name(name):
            return None
        app_id = apps.insert(App(id=int(body.get("id") or 0), name=name,
                                 description=body.get("description")))
        if app_id is None:
            return None
        Storage.get_events().init_channel(app_id)
        key = Storage.get_meta_data_access_keys().insert(
            AccessKey(key="", appid=app_id, events=()))
        return app_id, key

    out = await _run(_create)
    if out is None:
        return web.json_response(
            {"status": 0, "message": f"App {name} already exists."}, status=409)
    app_id, key = out
    return web.json_response(
        {"status": 1, "id": app_id, "name": name, "accessKey": key},
        status=201)


async def handle_app_delete(request):
    name = request.match_info["name"]

    def _delete():
        apps = Storage.get_meta_data_apps()
        app = apps.get_by_name(name)
        if app is None:
            return False
        events = Storage.get_events()
        channels = Storage.get_meta_data_channels()
        for c in channels.get_by_appid(app.id):
            events.remove_channel(app.id, c.id)
            channels.delete(c.id)
        events.remove_channel(app.id)
        for k in Storage.get_meta_data_access_keys().get_by_appid(app.id):
            Storage.get_meta_data_access_keys().delete(k.key)
        apps.delete(app.id)
        return True

    if await _run(_delete):
        return web.json_response(
            {"status": 1, "message": f"App {name} deleted."})
    return web.json_response(
        {"status": 0, "message": f"App {name} does not exist."}, status=404)


async def handle_app_data_delete(request):
    name = request.match_info["name"]

    def _wipe():
        app = Storage.get_meta_data_apps().get_by_name(name)
        if app is None:
            return False
        events = Storage.get_events()
        events.remove_channel(app.id)
        events.init_channel(app.id)
        return True

    if await _run(_wipe):
        return web.json_response(
            {"status": 1, "message": f"Data of app {name} deleted."})
    return web.json_response(
        {"status": 0, "message": f"App {name} does not exist."}, status=404)


async def handle_releases(request):
    """Release manifests across every engine variant (the operator's
    fleet view; the query server's /releases.json is per-variant)."""
    from predictionio_tpu.deploy.releases import release_to_json

    engine_id = request.query.get("engineId")
    variant = request.query.get("engineVariant")

    def _list():
        return [release_to_json(r)
                for r in Storage.get_meta_data_releases().get_all()
                if (not engine_id or r.engine_id == engine_id)
                and (not variant or r.engine_variant == variant)]

    return web.json_response({"status": 1, "releases": await _run(_list)})


async def handle_slo(request):
    """The SLO fleet view: the host's configured objectives, and — when
    ``?targets=host:port,...`` names live query servers — each target's
    current /slo.json evaluation, so one admin call answers "is any
    release burning its budget" across the fleet."""
    import aiohttp

    from predictionio_tpu.obs.slo import slo_spec_from_server_json

    spec = slo_spec_from_server_json()
    out = {
        "status": 1,
        "spec": ({
            "objectives": [{
                "name": o.name, "kind": o.kind,
                "thresholdS": o.threshold_s, "budget": o.budget}
                for o in spec.objectives],
            "windows": [{"seconds": w.seconds,
                         "burnThreshold": w.burn_threshold}
                        for w in spec.windows],
            "evalIntervalS": spec.eval_interval_s,
        } if spec is not None else None),
    }
    raw_targets = request.query.get("targets", "")
    targets = [t.strip() for t in raw_targets.split(",") if t.strip()][:32]
    if targets:
        from predictionio_tpu.utils.retry import RetryPolicy, \
            retry_call_async

        # one transient-fault retry with full jitter (the shared
        # utils/retry policy): a query server mid-restart answers the
        # fleet view on the second try instead of smearing an "error"
        # row across the operator's dashboard
        policy = RetryPolicy(retries=1, backoff_s=0.1, backoff_cap_s=0.5)
        timeout = aiohttp.ClientTimeout(total=5)
        async with aiohttp.ClientSession(timeout=timeout) as session:

            async def _get(target):
                async with session.get(
                        f"http://{target}/slo.json") as resp:
                    return await resp.json()

            async def _fetch(target):
                try:
                    return target, await retry_call_async(
                        _get, (target,), policy=policy)
                except Exception as e:
                    return target, {"error": str(e)}

            # concurrent: the view answers in one slowest-target timeout,
            # not the sum over dead targets
            results = await asyncio.gather(*[_fetch(t) for t in targets])
        fleet = dict(results)
        out["fleet"] = fleet
        out["breached"] = [t for t, s in fleet.items()
                           if isinstance(s, dict) and s.get("breached")]
    return web.json_response(out)


def create_admin_server(registry: MetricsRegistry = None,
                        telemetry=None,
                        history_root: str = None) -> web.Application:
    from predictionio_tpu.obs.telemetry import (
        add_history_routes, history_reader_factory,
    )

    registry = registry or MetricsRegistry()
    app = web.Application(middlewares=[
        observability_middleware(registry, "admin")])
    app.router.add_get("/", handle_root)
    app.router.add_get("/cmd/app", handle_app_list)
    app.router.add_post("/cmd/app", handle_app_new)
    app.router.add_delete("/cmd/app/{name}", handle_app_delete)
    app.router.add_delete("/cmd/app/{name}/data", handle_app_data_delete)
    app.router.add_get("/cmd/releases", handle_releases)
    app.router.add_get("/cmd/slo", handle_slo)
    from predictionio_tpu.obs.capacity import (
        add_capacity_route, register_capacity_metrics,
    )

    register_capacity_metrics(registry)
    add_capacity_route(app)
    add_metrics_routes(app, registry, default_registry())
    # fleet-wide history: the admin answers /history/*.json over the
    # MERGED per-process telemetry stores (obs/fleet.history_reader) —
    # the operator's one endpoint for longitudinal questions
    add_history_routes(app, history_reader_factory(telemetry,
                                                   root=history_root))
    if telemetry is not None:
        async def _stop_telemetry(app):
            import asyncio

            await asyncio.get_running_loop().run_in_executor(
                None, telemetry.stop)
        app.on_shutdown.append(_stop_telemetry)
    return app


def run_admin_server(ip: str = "localhost", port: int = DEFAULT_PORT) -> None:
    from predictionio_tpu.obs.telemetry import build_recorder
    from predictionio_tpu.utils.server_config import ServerConfig

    cfg = ServerConfig.load()
    registry = MetricsRegistry()
    telemetry = build_recorder("admin", cfg.telemetry,
                               instance=str(port),
                               registries=[registry, default_registry()])
    ssl_ctx = cfg.ssl_context()
    logger.info("Admin API listening on %s:%s%s", ip, port,
                " (TLS)" if ssl_ctx else "")
    web.run_app(create_admin_server(registry, telemetry=telemetry,
                                    history_root=cfg.telemetry.root_dir()),
                host=ip, port=port, ssl_context=ssl_ctx, print=None)
