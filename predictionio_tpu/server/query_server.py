"""Query server — deployed engine REST serving on port 8000.

Parity with the reference CreateServer/PredictionServer
(core/.../workflow/CreateServer.scala:104-706):

  GET  /               -> engine/instance info + serving stats   (:460-482)
  POST /queries.json   -> the prediction hot path                (:484-605)
  GET  /reload         -> reload latest COMPLETED instance       (:642-652)
  POST /stop           -> graceful shutdown (key auth)           (:635-641)
  GET  /plugins.json   -> engine server plugin registry

The hot path (:508 runs algorithms serially and says "TODO: Parallelize";
SURVEY.md P7): here the model's factor matrices stay resident as device
arrays inside the model objects, queries run through jitted scoring, and the
serial per-algorithm loop remains only as Python orchestration around
device-resident compute.

Feedback loop (:527-589): when feedback=True, each query/prediction pair is
written back to the event store as a `predict` event with prId tagging.
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime as _dt
import json
import logging
import time
from typing import Any, Optional

from aiohttp import web

from predictionio_tpu.core.engine import Engine, TrainResult
from predictionio_tpu.core.params import params_from_json
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event, UTC
from predictionio_tpu.obs.jax_stats import register_jax_metrics
from predictionio_tpu.obs.middleware import add_metrics_routes, observability_middleware
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.tracing import span
from predictionio_tpu.server.plugins import PluginContext
from predictionio_tpu.storage.base import EngineInstance, generate_id
from predictionio_tpu.storage.registry import Storage

logger = logging.getLogger("pio.queryserver")

DEFAULT_PORT = 8000


def _to_jsonable(obj: Any) -> Any:
    if hasattr(obj, "to_dict"):
        return obj.to_dict()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    return obj


def _query_class(train_result: TrainResult) -> Optional[type]:
    """Runtime query class resolution (BaseAlgorithm.queryClass:122 analog):
    an explicit `query_class` on the algorithm, else the annotation of
    predict's query parameter."""
    for algo in train_result.algorithms:
        qc = getattr(algo, "query_class", None)
        if qc is not None:
            return qc
        try:
            import typing

            hints = typing.get_type_hints(type(algo).predict)
            qc = hints.get("query")
            if isinstance(qc, type) and dataclasses.is_dataclass(qc):
                return qc
        except Exception:
            pass
    return None


class MicroBatcher:
    """Cross-request micro-batching onto the resident device model.

    The reference answers queries in a serial per-request loop
    (CreateServer.scala:508, marked "TODO: Parallelize"). Here every request
    queued while the previous batch was on the device is drained into ONE
    `Algorithm.batch_predict` call per algorithm — for vectorized algorithms
    (e.g. ALS recommend_batch) B concurrent queries cost one [B,K]@[K,N]
    matmul instead of B matvecs.
    """

    def __init__(self, predict_batch, max_batch: int = 64,
                 linger_s: float = 0.0):
        self._predict_batch = predict_batch
        self.max_batch = max_batch
        self.linger_s = linger_s
        self._queue: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None

    async def submit(self, query):
        loop = asyncio.get_running_loop()
        if self._task is None or self._task.done():
            self._queue = asyncio.Queue()
            self._task = loop.create_task(self._worker())
        fut = loop.create_future()
        self._queue.put_nowait((query, fut))
        return await fut

    async def _worker(self):
        loop = asyncio.get_running_loop()
        batch = []
        try:
            while True:
                batch = [await self._queue.get()]
                if self.linger_s:
                    await asyncio.sleep(self.linger_s)
                while len(batch) < self.max_batch and not self._queue.empty():
                    batch.append(self._queue.get_nowait())
                queries = [q for q, _ in batch]
                try:
                    results = await loop.run_in_executor(
                        None, self._predict_batch, queries)
                except Exception as e:
                    results = [e] * len(batch)
                for (_, fut), res in zip(batch, results):
                    if fut.done():
                        continue
                    if isinstance(res, Exception):
                        fut.set_exception(res)
                    else:
                        fut.set_result(res)
                batch = []
        finally:
            # worker died (cancellation at shutdown, BaseException): fail
            # everything in flight so no HTTP handler hangs on `await fut`
            while not self._queue.empty():
                batch.append(self._queue.get_nowait())
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("query micro-batch worker stopped"))


class QueryServer:
    def __init__(self, engine: Engine, train_result: TrainResult,
                 instance: EngineInstance, ctx,
                 feedback: bool = False,
                 feedback_app_name: Optional[str] = None,
                 access_key: Optional[str] = None,
                 plugin_context: Optional[PluginContext] = None,
                 log_url: Optional[str] = None,
                 log_prefix: str = "",
                 registry: Optional[MetricsRegistry] = None):
        self.engine = engine
        self.result = train_result
        self.instance = instance
        self.ctx = ctx
        self.feedback = feedback
        self.feedback_app_name = feedback_app_name
        #: remote error sink (CreateServer.scala:435-446 remoteLog): on a
        #: failed query, POST log_prefix + {"engineInstance", "message"}
        self.log_url = log_url
        self.log_prefix = log_prefix
        # resolve the feedback app once; a per-query metadata lookup would
        # sit on the hot path
        self._feedback_target = None
        if feedback and feedback_app_name:
            from predictionio_tpu.data.eventstore import resolve_app

            self._feedback_target = resolve_app(feedback_app_name)
        self.access_key = access_key
        self.plugins = plugin_context or PluginContext(
            "predictionio_tpu.engineserver_plugins")
        self.start_time = _dt.datetime.now(tz=UTC)
        self.last_serving_sec = 0.0
        self._stop_event = asyncio.Event()
        self.batcher = MicroBatcher(self._predict_batch)
        self.registry = registry or MetricsRegistry()
        register_jax_metrics(default_registry())
        self._query_hist = self.registry.histogram(
            "pio_query_duration_seconds",
            "Query hot-path wall time by engine variant",
            labelnames=("engine_variant",))
        self._query_failures = self.registry.counter(
            "pio_query_failures_total",
            "Failed queries by engine variant and cause "
            "(bad_json = client garbage, predict_error = engine failure)",
            labelnames=("engine_variant", "reason"))
        self._feedback_hist = self.registry.histogram(
            "pio_feedback_write_duration_seconds",
            "Feedback-loop event store write wall time")
        self._reload_total = self.registry.counter(
            "pio_reload_total", "Model reload attempts by outcome",
            labelnames=("status",))
        self.app = web.Application(middlewares=[
            observability_middleware(self.registry, "query_server")])
        self._routes()

    def _routes(self):
        r = self.app.router
        r.add_get("/", self.handle_root)
        r.add_post("/queries.json", self.handle_query)
        r.add_get("/reload", self.handle_reload)
        r.add_post("/stop", self.handle_stop)
        r.add_get("/plugins.json", self.handle_plugins)
        add_metrics_routes(self.app, self.registry, default_registry())

    # -- info ---------------------------------------------------------------
    async def handle_root(self, request):
        """Engine/instance info + serving stats (CreateServer.scala:460-482),
        latency figures sourced from the metrics registry."""
        count = self._query_hist.total_count()
        total = self._query_hist.total_sum()
        uptime = (_dt.datetime.now(tz=UTC) - self.start_time).total_seconds()
        return web.json_response({
            "status": "alive",
            "engineInstance": {
                "id": self.instance.id,
                "engineId": self.instance.engine_id,
                "engineVariant": self.instance.engine_variant,
                "startTime": self.instance.start_time.isoformat(),
            },
            "algorithms": [type(a).__name__ for a in self.result.algorithms],
            "startTime": self.start_time.isoformat(),
            "uptimeSeconds": uptime,
            "requestCount": int(count),
            "queryCount": int(count),
            "avgServingSec": (total / count) if count else 0.0,
            "p95ServingSec": self._query_hist.quantile(0.95),
            "lastServingSec": self.last_serving_sec,
        })

    async def _remote_log(self, message: str) -> None:
        """POST a serving failure to the operator's log sink
        (CreateServer.scala:435-446 remoteLog parity: prefix + JSON of
        engine-instance metadata and the message; delivery failures are
        logged locally and never propagate to the client response)."""
        import aiohttp

        payload = self.log_prefix + json.dumps({
            "engineInstance": {"id": self.instance.id,
                               "engineId": self.instance.engine_id,
                               "engineVariant": self.instance.engine_variant},
            "message": message})
        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        self.log_url, data=payload,
                        timeout=aiohttp.ClientTimeout(total=5)):
                    pass
        except Exception as e:
            logger.error("Unable to send remote log: %s", e)

    # -- hot path (CreateServer.scala:484-605) -------------------------------
    async def handle_query(self, request):
        t0 = time.perf_counter()
        variant = self.instance.engine_variant
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            self._query_failures.inc(engine_variant=variant,
                                     reason="bad_json")
            return web.json_response({"message": str(e)}, status=400)
        try:
            # spans resolve through the middleware-installed trace, which
            # carries a pre-resolved histogram handle (no lock on hot path)
            with span("extract_query"):
                query = self._extract_query(body)
            with span("predict"):
                if self._vectorized():
                    prediction = await self.batcher.submit(query)
                else:
                    # no vectorized batch_predict to exploit — per-request
                    # thread-pool parallelism beats serializing into one batch
                    loop = asyncio.get_running_loop()
                    prediction = await loop.run_in_executor(
                        None, self._predict, query)
        except Exception as e:
            logger.exception("query failed")
            self._query_failures.inc(engine_variant=variant,
                                     reason="predict_error")
            if self.log_url:
                await self._remote_log(
                    f"Query:\n{json.dumps(body)}\n\nError:\n{e!r}\n\n")
            return web.json_response({"message": str(e)}, status=400)

        pred_json = _to_jsonable(prediction)
        # feedback loop: tag with prId and record events (:527-589)
        if self.feedback and self.feedback_app_name:
            pr_id = (pred_json.get("prId") if isinstance(pred_json, dict)
                     else None) or generate_id()
            if isinstance(pred_json, dict):
                pred_json = dict(pred_json)
                pred_json["prId"] = pr_id
            asyncio.get_running_loop().run_in_executor(
                None, self._record_feedback, body, pred_json, pr_id)
        # output blockers transform; sniffers observe
        for blocker in self.plugins.output_blockers.values():
            try:
                pred_json = blocker.process(self.instance, body, pred_json)
            except Exception:
                logger.exception("output blocker failed")
        for sniffer in self.plugins.output_sniffers.values():
            try:
                sniffer.process(self.instance, body, pred_json)
            except Exception:
                logger.exception("output sniffer failed")

        dt = time.perf_counter() - t0
        self.last_serving_sec = dt
        self._query_hist.observe(dt, engine_variant=variant)
        return web.json_response(pred_json)

    def _extract_query(self, body: dict):
        qc = _query_class(self.result)
        if qc is None:
            return body
        return params_from_json(body, qc)

    def _vectorized(self) -> bool:
        """Micro-batching only pays when EVERY algorithm overrides
        batch_predict with a device-batched implementation — with a mix,
        the non-vectorized algorithms would run their serial per-query
        loop inside the single batcher worker, which is slower than the
        per-request thread-pool path."""
        from predictionio_tpu.core.base import Algorithm

        return bool(self.result.algorithms) and all(
            type(a).batch_predict is not Algorithm.batch_predict
            for a in self.result.algorithms)

    def _predict(self, query):
        supplemented = self.result.serving.supplement(query)
        predictions = [
            algo.predict(model, supplemented)
            for algo, model in zip(self.result.algorithms, self.result.models)]
        return self.result.serving.serve(query, predictions)

    def _predict_batch(self, queries):
        """Batch path behind MicroBatcher. Per-query errors are isolated:
        a failing query yields its Exception in the result slot, never
        poisoning the rest of the batch."""
        result = self.result      # snapshot: /reload may swap mid-batch
        out = [None] * len(queries)
        ok = []
        for i, q in enumerate(queries):
            try:
                ok.append((i, result.serving.supplement(q)))
            except Exception as e:
                out[i] = e
        if not ok:
            return out
        try:
            per_query = {i: [] for i, _ in ok}
            for algo, model in zip(result.algorithms, result.models):
                for i, p in algo.batch_predict(model, ok):
                    per_query[i].append(p)
            for i, _ in ok:
                try:
                    out[i] = result.serving.serve(queries[i], per_query[i])
                except Exception as e:
                    out[i] = e
        except Exception:
            # batch path failed (poison query inside a vectorized
            # batch_predict) — isolate by falling back to per-query predict
            for i, sq in ok:
                try:
                    preds = [a.predict(m, sq) for a, m in
                             zip(result.algorithms, result.models)]
                    out[i] = result.serving.serve(queries[i], preds)
                except Exception as e:
                    out[i] = e
        return out

    def _record_feedback(self, query_json, pred_json, pr_id):
        """Write predict/actual linkage events (CreateServer.scala:563-589)."""
        t0 = time.perf_counter()
        try:
            app_id, channel_id = self._feedback_target
            event = Event(
                event="predict",
                entity_type="pio_pr",
                entity_id=pr_id,
                properties=DataMap({"query": query_json,
                                    "prediction": pred_json}),
            )
            Storage.get_events().insert(event, app_id, channel_id)
            self._feedback_hist.observe(time.perf_counter() - t0)
        except Exception:
            logger.exception("feedback recording failed")

    # -- management ----------------------------------------------------------
    def _authorized(self, request) -> bool:
        if not self.access_key:
            return True
        return request.query.get("accessKey") == self.access_key

    async def handle_reload(self, request):
        """Re-read the latest COMPLETED instance (:342-371 ReloadServer)."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        from predictionio_tpu.workflow.train import load_for_deploy

        instances = Storage.get_meta_data_engine_instances()
        latest = instances.get_latest_completed(
            self.instance.engine_id, self.instance.engine_version,
            self.instance.engine_variant)
        if latest is None:
            self._reload_total.inc(status="not_found")
            return web.json_response(
                {"message": "No COMPLETED instance found"}, status=404)
        loop = asyncio.get_running_loop()
        result, ctx = await loop.run_in_executor(
            None, load_for_deploy, self.engine, latest)
        # swap under the running loop — double-buffered reload
        self.result = result
        self.ctx = ctx
        self.instance = latest
        self._reload_total.inc(status="reloaded")
        logger.info("reloaded engine instance %s", latest.id)
        return web.json_response({"message": "Reloaded",
                                  "engineInstanceId": latest.id})

    async def handle_stop(self, request):
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        self._stop_event.set()
        asyncio.get_running_loop().call_later(0.2, _raise_shutdown)
        return web.json_response({"message": "Shutting down"})

    async def handle_plugins(self, request):
        return web.json_response({"plugins": self.plugins.describe()})


def _raise_shutdown():
    raise web.GracefulExit()


def create_query_server(engine: Engine, train_result: TrainResult,
                        instance: EngineInstance, ctx,
                        **kwargs) -> QueryServer:
    return QueryServer(engine, train_result, instance, ctx, **kwargs)


def run_query_server(engine: Engine, train_result: TrainResult,
                     instance: EngineInstance, ctx,
                     ip: str = "localhost", port: int = DEFAULT_PORT,
                     **kwargs) -> None:
    from predictionio_tpu.utils.server_config import ServerConfig

    cfg = ServerConfig.load()
    # server.conf key guards /stop and /reload when no explicit key given
    # (CreateServer + KeyAuthentication.scala:33-62)
    kwargs.setdefault("access_key", cfg.key or None)
    server = create_query_server(engine, train_result, instance, ctx, **kwargs)
    ssl_ctx = cfg.ssl_context()
    logger.info("Query server listening on %s:%s%s", ip, port,
                " (TLS)" if ssl_ctx else "")
    web.run_app(server.app, host=ip, port=port,
                ssl_context=ssl_ctx, print=None)
