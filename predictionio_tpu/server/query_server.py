"""Query server — deployed engine REST serving on port 8000.

Parity with the reference CreateServer/PredictionServer
(core/.../workflow/CreateServer.scala:104-706):

  GET  /               -> engine/instance info + serving stats   (:460-482)
  POST /queries.json   -> the prediction hot path                (:484-605)
  GET  /reload         -> reload latest COMPLETED instance       (:642-652)
  POST /stop           -> graceful shutdown (key auth)           (:635-641)
  GET  /plugins.json   -> engine server plugin registry

The hot path (:508 runs algorithms serially and says "TODO: Parallelize";
SURVEY.md P7): here the model's factor matrices stay resident as device
arrays inside the model objects, queries run through jitted scoring, and the
serial per-algorithm loop remains only as Python orchestration around
device-resident compute.

Feedback loop (:527-589): when feedback=True, each query/prediction pair is
written back to the event store as a `predict` event with prId tagging.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import datetime as _dt
import functools
import json
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from aiohttp import web

from predictionio_tpu.core.engine import Engine, TrainResult
from predictionio_tpu.core.params import params_from_json
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event, UTC
from predictionio_tpu.obs.jax_stats import register_jax_metrics
from predictionio_tpu.obs.middleware import add_metrics_routes, observability_middleware
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.tracing import span, span_histogram
from predictionio_tpu.ops.bucketing import bucket_size, padding_waste
from predictionio_tpu.server.plugins import PluginContext
from predictionio_tpu.storage.base import EngineInstance, generate_id
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.server_config import ServingConfig

logger = logging.getLogger("pio.queryserver")

DEFAULT_PORT = 8000

#: ceiling of the ADAPTIVE linger window (`ServingConfig.batch_linger_s
#: = None`): the batcher never waits longer than this for stragglers,
#: and usually waits far less (2x the arrival-interval EWMA)
ADAPTIVE_LINGER_MAX_S = 0.002
#: EWMA smoothing for the arrival-interval estimate
_EWMA_ALPHA = 0.2
#: an arrival gap above this resets the estimator — idle-period gaps
#: describe nothing about burst spacing
_EWMA_RESET_S = 1.0


@contextlib.contextmanager
def _stage(hist, name: str):
    """Stage timing against a PRE-RESOLVED span histogram handle —
    `span(..., registry=...)` would re-resolve the histogram under the
    registry lock on every exit, which has no place on the hot path
    (the tracing.Trace.span_hist rule)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        hist.observe(time.perf_counter() - t0, span=name)


def _to_jsonable(obj: Any) -> Any:
    if hasattr(obj, "to_dict"):
        return obj.to_dict()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    return obj


def _query_class(train_result: TrainResult) -> Optional[type]:
    """Runtime query class resolution (BaseAlgorithm.queryClass:122 analog):
    an explicit `query_class` on the algorithm, else the annotation of
    predict's query parameter."""
    for algo in train_result.algorithms:
        qc = getattr(algo, "query_class", None)
        if qc is not None:
            return qc
        try:
            import typing

            hints = typing.get_type_hints(type(algo).predict)
            qc = hints.get("query")
            if isinstance(qc, type) and dataclasses.is_dataclass(qc):
                return qc
        except Exception:
            pass
    return None


class MicroBatcher:
    """Cross-request micro-batching onto the resident device model.

    The reference answers queries in a serial per-request loop
    (CreateServer.scala:508, marked "TODO: Parallelize"). Here every request
    queued while a batch is on the device is drained into ONE
    `Algorithm.batch_predict` call per algorithm — for vectorized algorithms
    (e.g. ALS recommend_batch) B concurrent queries cost one [B,K]@[K,N]
    matmul instead of B matvecs.

    Three serving-hot-path mechanisms beyond plain coalescing:

    * **pipelining** — up to `inflight` batches run concurrently on a
      dedicated bounded executor, so the worker assembles/supplements
      batch k+1 on the host while batch k is on the device (the classic
      host/device overlap; `inflight=1` restores strict serialization).
    * **adaptive linger** (`linger_s=None`) — the wait-for-stragglers
      window is derived from the arrival-interval EWMA: the worker
      lingers only when another batch is already in flight (the device
      is busy, so waiting is free) AND the EWMA says a second request is
      likely to arrive within ADAPTIVE_LINGER_MAX_S. A lone sequential
      client therefore never pays a linger tax, while a concurrent burst
      coalesces. An explicit `linger_s` number forces a fixed wait
      (0 disables lingering).
    * **shape bucketing** — not here but in the `predict_batch` callable
      (`QueryServer._predict_batch` pads each drained batch up to its
      power-of-two bucket via ops/bucketing before any jitted scorer
      sees it).
    """

    def __init__(self, predict_batch, max_batch: int = 64,
                 linger_s: Optional[float] = None, inflight: int = 2,
                 executor: Optional[ThreadPoolExecutor] = None,
                 registry: Optional[MetricsRegistry] = None):
        self._predict_batch = predict_batch
        self.max_batch = max(1, max_batch)
        #: None = adaptive (EWMA-derived); a number = fixed linger window
        self.linger_s = linger_s
        self.adaptive_linger_max_s = ADAPTIVE_LINGER_MAX_S
        self.inflight = max(1, inflight)
        self._executor = executor
        self._queue: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None
        self._sem: Optional[asyncio.Semaphore] = None
        self._inflight_now = 0
        self._ewma_interval: Optional[float] = None
        self._last_arrival: Optional[float] = None
        self._size_hist = self._inflight_gauge = self._span_hist = None
        if registry is not None:
            self._size_hist = registry.histogram(
                "pio_batch_size",
                "Queries coalesced per micro-batch drain",
                buckets=tuple(float(1 << i) for i in range(11)))
            self._inflight_gauge = registry.gauge(
                "pio_batch_inflight",
                "Micro-batches currently running on the predict executor")
            registry.gauge_callback(
                "pio_batch_queue_depth",
                "Queries waiting in the micro-batch queue",
                lambda: float(self.queue_depth()))
            self._span_hist = span_histogram(registry)

    # -- arrival-rate estimate (adaptive linger input) -----------------------
    def _note_arrival(self) -> None:
        now = time.monotonic()
        last, self._last_arrival = self._last_arrival, now
        if last is None:
            return
        dt = now - last
        if dt > _EWMA_RESET_S:
            # an idle gap says nothing about spacing WITHIN a burst
            self._ewma_interval = None
        elif self._ewma_interval is None:
            self._ewma_interval = dt
        else:
            self._ewma_interval += _EWMA_ALPHA * (dt - self._ewma_interval)

    def _linger_window(self) -> float:
        if self.linger_s is not None:
            return self.linger_s
        if self._inflight_now == 0:
            # device idle: dispatching now beats betting on a straggler
            return 0.0
        ewma = self._ewma_interval
        if ewma is None or ewma > self.adaptive_linger_max_s:
            return 0.0
        return min(self.adaptive_linger_max_s, 2.0 * ewma)

    def _observe_span(self, name: str, seconds: float) -> None:
        if self._span_hist is not None:
            self._span_hist.observe(seconds, span=name)

    def queue_depth(self) -> int:
        return self._queue.qsize() if self._queue is not None else 0

    async def shutdown(self) -> None:
        """Cancel the worker and wait for its drain to fail everything
        still queued — handlers see a fast RuntimeError, never a hang.
        Batches already on the executor resolve through their callbacks."""
        task = self._task
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:      # worker died of its own accord
                pass

    # -- submit/worker -------------------------------------------------------
    async def submit(self, query):
        loop = asyncio.get_running_loop()
        self._note_arrival()
        fut = loop.create_future()
        entry = (query, fut)
        while True:
            if self._task is None or self._task.done():
                self._queue = asyncio.Queue()
                self._sem = asyncio.Semaphore(self.inflight)
                self._task = loop.create_task(
                    self._worker(self._queue, self._sem))
            task, queue = self._task, self._queue
            queue.put_nowait(entry)
            if not task.done() or fut.done():
                return await fut
            # the worker completed between the liveness check and the put
            # — its shutdown drain can have run BEFORE our entry landed,
            # which would orphan `fut` and hang this handler forever on
            # `await fut`. Re-check and requeue onto a fresh worker (the
            # dead queue is abandoned; nothing reads it again).

    async def _worker(self, queue: asyncio.Queue, sem: asyncio.Semaphore):
        loop = asyncio.get_running_loop()
        batch = []
        try:
            while True:
                batch = [await queue.get()]
                # take an in-flight slot BEFORE assembling: while every
                # slot is busy the queue keeps filling, which IS the
                # batching signal — no linger needed under saturation
                await sem.acquire()
                dispatched = False
                try:
                    while len(batch) < self.max_batch and not queue.empty():
                        batch.append(queue.get_nowait())
                    linger = self._linger_window()
                    if linger > 0.0 and len(batch) < self.max_batch:
                        t0 = time.perf_counter()
                        await asyncio.sleep(linger)
                        self._observe_span("batch_linger",
                                           time.perf_counter() - t0)
                        while (len(batch) < self.max_batch
                               and not queue.empty()):
                            batch.append(queue.get_nowait())
                    if self._size_hist is not None:
                        self._size_hist.observe(float(len(batch)))
                    queries = [q for q, _ in batch]
                    ex_fut = loop.run_in_executor(
                        self._executor, self._predict_batch, queries)
                    self._inflight_now += 1
                    if self._inflight_gauge is not None:
                        self._inflight_gauge.set(float(self._inflight_now))
                    ex_fut.add_done_callback(
                        functools.partial(self._finish_batch, batch, sem))
                    dispatched = True
                finally:
                    if not dispatched:
                        sem.release()
                batch = []
        finally:
            # worker died (cancellation at shutdown, BaseException): fail
            # everything not yet dispatched so no HTTP handler hangs on
            # `await fut`; already-dispatched batches resolve through
            # their executor-future callbacks
            while not queue.empty():
                batch.append(queue.get_nowait())
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("query micro-batch worker stopped"))

    def _finish_batch(self, batch, sem: asyncio.Semaphore, ex_fut) -> None:
        """Runs on the event loop when a dispatched batch's executor
        future settles: free the in-flight slot, then route per-query
        results/errors to their awaiting handlers."""
        self._inflight_now -= 1
        if self._inflight_gauge is not None:
            self._inflight_gauge.set(float(self._inflight_now))
        sem.release()
        try:
            results = ex_fut.result()
        except BaseException as e:   # noqa: BLE001 — must never orphan futs
            err = e if isinstance(e, Exception) else \
                RuntimeError(f"micro-batch dispatch failed: {e!r}")
            results = [err] * len(batch)
        for (_, fut), res in zip(batch, results):
            if fut.done():
                continue
            if isinstance(res, Exception):
                fut.set_exception(res)
            else:
                fut.set_result(res)


class QueryServer:
    def __init__(self, engine: Engine, train_result: TrainResult,
                 instance: EngineInstance, ctx,
                 feedback: bool = False,
                 feedback_app_name: Optional[str] = None,
                 access_key: Optional[str] = None,
                 plugin_context: Optional[PluginContext] = None,
                 log_url: Optional[str] = None,
                 log_prefix: str = "",
                 registry: Optional[MetricsRegistry] = None,
                 serving_config: Optional[ServingConfig] = None):
        self.engine = engine
        self.result = train_result
        self.instance = instance
        self.ctx = ctx
        self.feedback = feedback
        self.feedback_app_name = feedback_app_name
        #: remote error sink (CreateServer.scala:435-446 remoteLog): on a
        #: failed query, POST log_prefix + {"engineInstance", "message"}
        self.log_url = log_url
        self.log_prefix = log_prefix
        # resolve the feedback app once; a per-query metadata lookup would
        # sit on the hot path
        self._feedback_target = None
        if feedback and feedback_app_name:
            from predictionio_tpu.data.eventstore import resolve_app

            self._feedback_target = resolve_app(feedback_app_name)
        self.access_key = access_key
        self.plugins = plugin_context or PluginContext(
            "predictionio_tpu.engineserver_plugins")
        self.start_time = _dt.datetime.now(tz=UTC)
        self.last_serving_sec = 0.0
        self._stop_event = asyncio.Event()
        self.registry = registry or MetricsRegistry()
        register_jax_metrics(default_registry())
        self.serving_config = serving_config or ServingConfig.from_env()
        #: dedicated bounded pool for predictions ONLY — feedback writes
        #: and remote logging stay on the loop's default executor, so a
        #: burst of event-store writes can never starve the hot path (and
        #: vice versa). Sized past `batch_inflight` so non-vectorized
        #: engines (per-request path) still get some parallelism.
        self._predict_executor = ThreadPoolExecutor(
            max_workers=max(4, self.serving_config.batch_inflight * 2),
            thread_name_prefix="pio-predict")
        self.batcher = MicroBatcher(
            self._predict_batch,
            max_batch=self.serving_config.batch_max,
            linger_s=self.serving_config.batch_linger_s,
            inflight=self.serving_config.batch_inflight,
            executor=self._predict_executor,
            registry=self.registry)
        #: pre-resolved span-histogram handle for batch-stage timings
        #: (_predict_batch runs per batch on the executor — it must not
        #: take the registry lock to re-resolve the histogram each stage)
        self._span_hist = span_histogram(self.registry)
        self._pad_waste = self.registry.counter(
            "pio_batch_pad_waste_rows_total",
            "Throwaway rows added padding batches up to their shape "
            "bucket (the price of a bounded compile-shape set)")
        #: cached per TrainResult (recomputing re-imported core.base and
        #: re-walked every algorithm on EVERY request); refreshed when
        #: /reload swaps the result
        self._vectorized_cached = self._compute_vectorized(train_result)
        self._query_hist = self.registry.histogram(
            "pio_query_duration_seconds",
            "Query hot-path wall time by engine variant",
            labelnames=("engine_variant",))
        self._query_failures = self.registry.counter(
            "pio_query_failures_total",
            "Failed queries by engine variant and cause "
            "(bad_json = client garbage, predict_error = engine failure)",
            labelnames=("engine_variant", "reason"))
        self._feedback_hist = self.registry.histogram(
            "pio_feedback_write_duration_seconds",
            "Feedback-loop event store write wall time")
        self._reload_total = self.registry.counter(
            "pio_reload_total", "Model reload attempts by outcome",
            labelnames=("status",))
        self.app = web.Application(middlewares=[
            observability_middleware(self.registry, "query_server")])
        self.app.on_cleanup.append(self._on_cleanup)
        self._routes()

    async def _on_cleanup(self, app) -> None:
        # drain the batcher BEFORE the executor goes away: its worker's
        # finally fails queued queries fast instead of leaving a pending
        # task (and a 'Task was destroyed' warning) behind the loop
        await self.batcher.shutdown()
        self._predict_executor.shutdown(wait=False)

    def _routes(self):
        r = self.app.router
        r.add_get("/", self.handle_root)
        r.add_post("/queries.json", self.handle_query)
        r.add_get("/reload", self.handle_reload)
        r.add_post("/stop", self.handle_stop)
        r.add_get("/plugins.json", self.handle_plugins)
        add_metrics_routes(self.app, self.registry, default_registry())

    # -- info ---------------------------------------------------------------
    async def handle_root(self, request):
        """Engine/instance info + serving stats (CreateServer.scala:460-482),
        latency figures sourced from the metrics registry."""
        count = self._query_hist.total_count()
        total = self._query_hist.total_sum()
        uptime = (_dt.datetime.now(tz=UTC) - self.start_time).total_seconds()
        return web.json_response({
            "status": "alive",
            "engineInstance": {
                "id": self.instance.id,
                "engineId": self.instance.engine_id,
                "engineVariant": self.instance.engine_variant,
                "startTime": self.instance.start_time.isoformat(),
            },
            "algorithms": [type(a).__name__ for a in self.result.algorithms],
            "startTime": self.start_time.isoformat(),
            "uptimeSeconds": uptime,
            "requestCount": int(count),
            "queryCount": int(count),
            "avgServingSec": (total / count) if count else 0.0,
            "p95ServingSec": self._query_hist.quantile(0.95),
            "lastServingSec": self.last_serving_sec,
        })

    async def _remote_log(self, message: str) -> None:
        """POST a serving failure to the operator's log sink
        (CreateServer.scala:435-446 remoteLog parity: prefix + JSON of
        engine-instance metadata and the message; delivery failures are
        logged locally and never propagate to the client response)."""
        import aiohttp

        payload = self.log_prefix + json.dumps({
            "engineInstance": {"id": self.instance.id,
                               "engineId": self.instance.engine_id,
                               "engineVariant": self.instance.engine_variant},
            "message": message})
        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        self.log_url, data=payload,
                        timeout=aiohttp.ClientTimeout(total=5)):
                    pass
        except Exception as e:
            logger.error("Unable to send remote log: %s", e)

    # -- hot path (CreateServer.scala:484-605) -------------------------------
    async def handle_query(self, request):
        t0 = time.perf_counter()
        variant = self.instance.engine_variant
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            self._query_failures.inc(engine_variant=variant,
                                     reason="bad_json")
            return web.json_response({"message": str(e)}, status=400)
        try:
            # spans resolve through the middleware-installed trace, which
            # carries a pre-resolved histogram handle (no lock on hot path)
            with span("extract_query"):
                query = self._extract_query(body)
            with span("predict"):
                if self._vectorized():
                    prediction = await self.batcher.submit(query)
                else:
                    # no vectorized batch_predict to exploit — per-request
                    # parallelism on the server's own bounded pool beats
                    # serializing into one batch
                    loop = asyncio.get_running_loop()
                    prediction = await loop.run_in_executor(
                        self._predict_executor, self._predict, query)
        except Exception as e:
            logger.exception("query failed")
            self._query_failures.inc(engine_variant=variant,
                                     reason="predict_error")
            if self.log_url:
                await self._remote_log(
                    f"Query:\n{json.dumps(body)}\n\nError:\n{e!r}\n\n")
            return web.json_response({"message": str(e)}, status=400)

        pred_json = _to_jsonable(prediction)
        # feedback loop: tag with prId and record events (:527-589)
        if self.feedback and self.feedback_app_name:
            pr_id = (pred_json.get("prId") if isinstance(pred_json, dict)
                     else None) or generate_id()
            if isinstance(pred_json, dict):
                pred_json = dict(pred_json)
                pred_json["prId"] = pr_id
            asyncio.get_running_loop().run_in_executor(
                None, self._record_feedback, body, pred_json, pr_id)
        # output blockers transform; sniffers observe
        for blocker in self.plugins.output_blockers.values():
            try:
                pred_json = blocker.process(self.instance, body, pred_json)
            except Exception:
                logger.exception("output blocker failed")
        for sniffer in self.plugins.output_sniffers.values():
            try:
                sniffer.process(self.instance, body, pred_json)
            except Exception:
                logger.exception("output sniffer failed")

        dt = time.perf_counter() - t0
        self.last_serving_sec = dt
        self._query_hist.observe(dt, engine_variant=variant)
        return web.json_response(pred_json)

    def _extract_query(self, body: dict):
        qc = _query_class(self.result)
        if qc is None:
            return body
        return params_from_json(body, qc)

    def _vectorized(self) -> bool:
        """Cached per TrainResult — the walk itself is cheap but it sat
        on EVERY request; recomputed only when /reload swaps models."""
        return self._vectorized_cached

    @staticmethod
    def _compute_vectorized(result: TrainResult) -> bool:
        """Micro-batching only pays when EVERY algorithm overrides
        batch_predict with a batched implementation — with a mix, the
        non-vectorized algorithms would run their serial per-query loop
        inside the single batcher worker, which is slower than the
        per-request thread-pool path."""
        from predictionio_tpu.core.base import Algorithm

        return bool(result.algorithms) and all(
            type(a).batch_predict is not Algorithm.batch_predict
            for a in result.algorithms)

    def _predict(self, query):
        supplemented = self.result.serving.supplement(query)
        predictions = [
            algo.predict(model, supplemented)
            for algo, model in zip(self.result.algorithms, self.result.models)]
        return self.result.serving.serve(query, predictions)

    def _predict_batch(self, queries):
        """Batch path behind MicroBatcher (runs on the predict executor).

        Per-query errors are isolated: a failing query yields its
        Exception in the result slot, never poisoning the rest of the
        batch. Before the scorers run, the batch is padded up to its
        power-of-two shape bucket (ops/bucketing) with clones of the last
        real query under sentinel indices — jitted scorers therefore see
        at most `bucket_count(max_batch)` distinct batch shapes ever, and
        the padded rows are sliced off here so they never reach
        `serving.serve` or a client.

        This server-level pad is what protects engines whose
        batch_predict jits on the RAW batch length (classification's
        `_vector_batch_predict` scores an [B, d] feature matrix through
        a stable jit). ALS additionally re-buckets on its own device
        rows (unknown users shrink B mid-model, so it must); for
        host-BLAS scorers the pad is a few microseconds of duplicated
        matvec — the bounded price of one rule for every engine."""
        result = self.result      # snapshot: /reload may swap mid-batch
        n = len(queries)
        out = [None] * n
        ok = []
        with _stage(self._span_hist, "batch_assemble"):
            for i, q in enumerate(queries):
                try:
                    ok.append((i, result.serving.supplement(q)))
                except Exception as e:
                    out[i] = e
            if not ok:
                return out
            bucket = bucket_size(len(ok), self.batcher.max_batch)
            waste = padding_waste(len(ok), bucket)
            if waste:
                # sentinel indices >= n mark pad rows; their predictions
                # are computed and thrown away — the bounded price of a
                # bounded compile-shape set
                pad_q = ok[-1][1]
                batch = ok + [(n + j, pad_q) for j in range(waste)]
                self._pad_waste.inc(waste)
            else:
                batch = ok
        try:
            per_query = {i: [] for i, _ in ok}
            with _stage(self._span_hist, "batch_device"):
                for algo, model in zip(result.algorithms, result.models):
                    for i, p in algo.batch_predict(model, batch):
                        if i in per_query:      # pad rows sliced off
                            per_query[i].append(p)
            with _stage(self._span_hist, "batch_serve"):
                for i, _ in ok:
                    try:
                        out[i] = result.serving.serve(queries[i],
                                                      per_query[i])
                    except Exception as e:
                        out[i] = e
        except Exception:
            # batch path failed (poison query inside a vectorized
            # batch_predict) — isolate by falling back to per-query predict
            for i, sq in ok:
                try:
                    preds = [a.predict(m, sq) for a, m in
                             zip(result.algorithms, result.models)]
                    out[i] = result.serving.serve(queries[i], preds)
                except Exception as e:
                    out[i] = e
        return out

    def _record_feedback(self, query_json, pred_json, pr_id):
        """Write predict/actual linkage events (CreateServer.scala:563-589)."""
        t0 = time.perf_counter()
        try:
            app_id, channel_id = self._feedback_target
            event = Event(
                event="predict",
                entity_type="pio_pr",
                entity_id=pr_id,
                properties=DataMap({"query": query_json,
                                    "prediction": pred_json}),
            )
            Storage.get_events().insert(event, app_id, channel_id)
            self._feedback_hist.observe(time.perf_counter() - t0)
        except Exception:
            logger.exception("feedback recording failed")

    # -- management ----------------------------------------------------------
    def _authorized(self, request) -> bool:
        if not self.access_key:
            return True
        return request.query.get("accessKey") == self.access_key

    async def handle_reload(self, request):
        """Re-read the latest COMPLETED instance (:342-371 ReloadServer)."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        from predictionio_tpu.workflow.train import load_for_deploy

        instances = Storage.get_meta_data_engine_instances()
        latest = instances.get_latest_completed(
            self.instance.engine_id, self.instance.engine_version,
            self.instance.engine_variant)
        if latest is None:
            self._reload_total.inc(status="not_found")
            return web.json_response(
                {"message": "No COMPLETED instance found"}, status=404)
        loop = asyncio.get_running_loop()
        result, ctx = await loop.run_in_executor(
            None, load_for_deploy, self.engine, latest)
        # swap under the running loop — double-buffered reload; the
        # cached vectorized-capability flag refreshes with the swap
        self.result = result
        self._vectorized_cached = self._compute_vectorized(result)
        self.ctx = ctx
        self.instance = latest
        self._reload_total.inc(status="reloaded")
        logger.info("reloaded engine instance %s", latest.id)
        return web.json_response({"message": "Reloaded",
                                  "engineInstanceId": latest.id})

    async def handle_stop(self, request):
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        self._stop_event.set()
        asyncio.get_running_loop().call_later(0.2, _raise_shutdown)
        return web.json_response({"message": "Shutting down"})

    async def handle_plugins(self, request):
        return web.json_response({"plugins": self.plugins.describe()})


def _raise_shutdown():
    raise web.GracefulExit()


def create_query_server(engine: Engine, train_result: TrainResult,
                        instance: EngineInstance, ctx,
                        **kwargs) -> QueryServer:
    return QueryServer(engine, train_result, instance, ctx, **kwargs)


def run_query_server(engine: Engine, train_result: TrainResult,
                     instance: EngineInstance, ctx,
                     ip: str = "localhost", port: int = DEFAULT_PORT,
                     **kwargs) -> None:
    from predictionio_tpu.utils.server_config import ServerConfig

    cfg = ServerConfig.load()
    # server.conf key guards /stop and /reload when no explicit key given
    # (CreateServer + KeyAuthentication.scala:33-62)
    kwargs.setdefault("access_key", cfg.key or None)
    # micro-batch tuning from server.json "serving" + PIO_BATCH_* env
    kwargs.setdefault("serving_config", cfg.serving)
    server = create_query_server(engine, train_result, instance, ctx, **kwargs)
    ssl_ctx = cfg.ssl_context()
    logger.info("Query server listening on %s:%s%s", ip, port,
                " (TLS)" if ssl_ctx else "")
    web.run_app(server.app, host=ip, port=port,
                ssl_context=ssl_ctx, print=None)
