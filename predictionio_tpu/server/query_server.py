"""Query server — deployed engine REST serving on port 8000.

Parity with the reference CreateServer/PredictionServer
(core/.../workflow/CreateServer.scala:104-706):

  GET  /               -> engine/instance info + serving stats   (:460-482)
  POST /queries.json   -> the prediction hot path                (:484-605)
  GET  /reload         -> WARM-swap to latest COMPLETED instance (:642-652)
  POST /stop           -> graceful shutdown (key auth)           (:635-641)
  GET  /plugins.json   -> engine server plugin registry

Deploy-lifecycle surface (deploy/ subsystem; no reference counterpart —
the reference's /reload is a cold load-latest with no way back):

  GET  /releases.json       -> release manifests for this variant
  GET  /deploy/status.json  -> active release + canary window state
  POST /deploy.json         -> warm deploy a release (key auth); body
                               {"releaseId"|"version"|"engineInstanceId",
                                "canaryFraction"?, "shadow"?, ...}
  POST /rollback.json       -> roll back (key auth): abort an active
                               canary, else restore the standby release

Everything a query touches — TrainResult, the vectorized-capability
flag, the micro-batcher — is bundled into one :class:`deploy.ServingUnit`
and swapped as a single reference assignment, so an in-flight batch keeps
the release it was routed to and no request can observe a half-swapped
(result, vectorized) pair. Before a unit takes traffic it is driven
through the ops/bucketing shape ladder (deploy/warm.py), so the first
post-cutover batch pays zero XLA compiles.

The hot path (:508 runs algorithms serially and says "TODO: Parallelize";
SURVEY.md P7): here the model's factor matrices stay resident as device
arrays inside the model objects, queries run through jitted scoring, and the
serial per-algorithm loop remains only as Python orchestration around
device-resident compute.

Feedback loop (:527-589): when feedback=True, each query/prediction pair is
written back to the event store as a `predict` event with prId tagging.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import datetime as _dt
import functools
import json
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional

from aiohttp import web

from predictionio_tpu.core.engine import Engine, TrainResult
from predictionio_tpu.core.params import params_from_json
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event, UTC
from predictionio_tpu.deploy.canary import (
    ROLE_CANARY, ROLE_INCUMBENT, ROLE_SHADOW, CanaryConfig, CanaryController,
)
from predictionio_tpu.deploy.releases import release_to_json, resolve_release
from predictionio_tpu.deploy.warm import (
    DeployError, FoldinSwapRaced, ServingUnit, WarmupReport, build_unit,
    deploy_metrics, verify_unit, warmup_unit,
)
from predictionio_tpu.obs.anatomy import (
    SERVING_PATH, AnatomyMetrics, BatchBreakdown, active_breakdown,
    anatomy_enabled, anatomy_metrics, note_stage, observe_serving_batch,
    observe_stage, pop_breakdown, push_breakdown,
)
from predictionio_tpu.obs.capacity import (
    add_capacity_route, register_capacity_metrics, unit_capacity,
)
from predictionio_tpu.obs.jax_stats import register_jax_metrics
from predictionio_tpu.obs.middleware import add_metrics_routes, observability_middleware
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.slo import SLOEngine, SLOSpec
from predictionio_tpu.obs.trace_context import record_event
from predictionio_tpu.obs.tracing import (
    capture_context, carried, current_trace, span, span_histogram,
)
from predictionio_tpu.ops.bucketing import bucket_size, padding_waste
from predictionio_tpu.server.plugins import PluginContext
from predictionio_tpu.storage.base import EngineInstance, Release, generate_id
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.server_config import (
    DeployConfig, FoldinConfig, ScorerConfig, ServingConfig,
)

logger = logging.getLogger("pio.queryserver")

DEFAULT_PORT = 8000

#: ceiling of the ADAPTIVE linger window (`ServingConfig.batch_linger_s
#: = None`): the batcher never waits longer than this for stragglers,
#: and usually waits far less (2x the arrival-interval EWMA)
ADAPTIVE_LINGER_MAX_S = 0.002
#: EWMA smoothing for the arrival-interval estimate
_EWMA_ALPHA = 0.2
#: an arrival gap above this resets the estimator — idle-period gaps
#: describe nothing about burst spacing
_EWMA_RESET_S = 1.0


@contextlib.contextmanager
def _stage(hist, name: str):
    """Stage timing against a PRE-RESOLVED span histogram handle —
    `span(..., registry=...)` would re-resolve the histogram under the
    registry lock on every exit, which has no place on the hot path
    (the tracing.Trace.span_hist rule). When the executor thread runs
    under a carried request trace (MicroBatcher dispatch), the stage
    also lands in that trace so the flight recorder attributes it."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        hist.observe(dt, span=name)
        trace = current_trace()
        if trace is not None:
            trace.add(name, dt)
        # and into the active batch's anatomy breakdown (no-op outside
        # a micro-batch) so members get their per-request stage share
        note_stage(name, dt)


def _to_jsonable(obj: Any) -> Any:
    if hasattr(obj, "to_dict"):
        return obj.to_dict()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    return obj


def _query_class(train_result: TrainResult) -> Optional[type]:
    """Runtime query class resolution (BaseAlgorithm.queryClass:122 analog):
    an explicit `query_class` on the algorithm, else the annotation of
    predict's query parameter."""
    for algo in train_result.algorithms:
        qc = getattr(algo, "query_class", None)
        if qc is not None:
            return qc
        try:
            import typing

            hints = typing.get_type_hints(type(algo).predict)
            qc = hints.get("query")
            if isinstance(qc, type) and dataclasses.is_dataclass(qc):
                return qc
        except Exception:
            pass
    return None


class MicroBatcher:
    """Cross-request micro-batching onto the resident device model.

    The reference answers queries in a serial per-request loop
    (CreateServer.scala:508, marked "TODO: Parallelize"). Here every request
    queued while a batch is on the device is drained into ONE
    `Algorithm.batch_predict` call per algorithm — for vectorized algorithms
    (e.g. ALS recommend_batch) B concurrent queries cost one [B,K]@[K,N]
    matmul instead of B matvecs.

    Three serving-hot-path mechanisms beyond plain coalescing:

    * **pipelining** — up to `inflight` batches run concurrently on a
      dedicated bounded executor, so the worker assembles/supplements
      batch k+1 on the host while batch k is on the device (the classic
      host/device overlap; `inflight=1` restores strict serialization).
    * **adaptive linger** (`linger_s=None`) — the wait-for-stragglers
      window is derived from the arrival-interval EWMA: the worker
      lingers only when another batch is already in flight (the device
      is busy, so waiting is free) AND the EWMA says a second request is
      likely to arrive within ADAPTIVE_LINGER_MAX_S. A lone sequential
      client therefore never pays a linger tax, while a concurrent burst
      coalesces. An explicit `linger_s` number forces a fixed wait
      (0 disables lingering).
    * **shape bucketing** — not here but in the `predict_batch` callable
      (`QueryServer._predict_batch` pads each drained batch up to its
      power-of-two bucket via ops/bucketing before any jitted scorer
      sees it).
    """

    def __init__(self, predict_batch, max_batch: int = 64,
                 linger_s: Optional[float] = None, inflight: int = 2,
                 executor: Optional[ThreadPoolExecutor] = None,
                 registry: Optional[MetricsRegistry] = None):
        self._predict_batch = predict_batch
        self.max_batch = max(1, max_batch)
        #: None = adaptive (EWMA-derived); a number = fixed linger window
        self.linger_s = linger_s
        self.adaptive_linger_max_s = ADAPTIVE_LINGER_MAX_S
        self.inflight = max(1, inflight)
        self._executor = executor
        self._queue: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None
        self._sem: Optional[asyncio.Semaphore] = None
        self._inflight_now = 0
        self._ewma_interval: Optional[float] = None
        self._last_arrival: Optional[float] = None
        self._registry = registry
        self._size_hist = self._inflight_gauge = self._span_hist = None
        self._anatomy: Optional[AnatomyMetrics] = None
        if registry is not None:
            self._anatomy = anatomy_metrics(registry)
            self._size_hist = registry.histogram(
                "pio_batch_size",
                "Queries coalesced per micro-batch drain",
                buckets=tuple(float(1 << i) for i in range(11)))
            self._inflight_gauge = registry.gauge(
                "pio_batch_inflight",
                "Micro-batches currently running on the predict executor")
            registry.gauge_callback(
                "pio_batch_queue_depth",
                "Queries waiting in the micro-batch queue",
                lambda: float(self.queue_depth()))
            self._span_hist = span_histogram(registry)

    # -- arrival-rate estimate (adaptive linger input) -----------------------
    def _note_arrival(self) -> None:
        now = time.monotonic()
        last, self._last_arrival = self._last_arrival, now
        if last is None:
            return
        dt = now - last
        if dt > _EWMA_RESET_S:
            # an idle gap says nothing about spacing WITHIN a burst
            self._ewma_interval = None
        elif self._ewma_interval is None:
            self._ewma_interval = dt
        else:
            self._ewma_interval += _EWMA_ALPHA * (dt - self._ewma_interval)

    def _linger_window(self) -> float:
        if self.linger_s is not None:
            return self.linger_s
        if self._inflight_now == 0:
            # device idle: dispatching now beats betting on a straggler
            return 0.0
        ewma = self._ewma_interval
        if ewma is None or ewma > self.adaptive_linger_max_s:
            return 0.0
        return min(self.adaptive_linger_max_s, 2.0 * ewma)

    def _observe_span(self, name: str, seconds: float) -> None:
        if self._span_hist is not None:
            self._span_hist.observe(seconds, span=name)

    def queue_depth(self) -> int:
        return self._queue.qsize() if self._queue is not None else 0

    async def shutdown(self) -> None:
        """Cancel the worker and wait for its drain to fail everything
        still queued — handlers see a fast RuntimeError, never a hang.
        Batches already on the executor resolve through their callbacks."""
        task = self._task
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:      # worker died of its own accord
                pass

    # -- submit/worker -------------------------------------------------------
    async def submit(self, query):
        loop = asyncio.get_running_loop()
        self._note_arrival()
        fut = loop.create_future()
        # capture the submitting request's trace context so the executor
        # thread's batch spans stay linked to it (the thread hop used to
        # drop the contextvar trace); a cheap contextvar read, None when
        # tracing is off. The submit timestamp + the request's own Trace
        # feed the per-request anatomy (queue wait per member, stages
        # attached to EACH member's trace — not just the first
        # submitter's carried one).
        entry = (query, fut, capture_context(), time.perf_counter(),
                 current_trace())
        while True:
            if self._task is None or self._task.done():
                self._queue = asyncio.Queue()
                self._sem = asyncio.Semaphore(self.inflight)
                self._task = loop.create_task(
                    self._worker(self._queue, self._sem))
            task, queue = self._task, self._queue
            queue.put_nowait(entry)
            if not task.done() or fut.done():
                return await fut
            # the worker completed between the liveness check and the put
            # — its shutdown drain can have run BEFORE our entry landed,
            # which would orphan `fut` and hang this handler forever on
            # `await fut`. Re-check and requeue onto a fresh worker (the
            # dead queue is abandoned; nothing reads it again).

    async def _worker(self, queue: asyncio.Queue, sem: asyncio.Semaphore):
        loop = asyncio.get_running_loop()
        batch = []
        try:
            while True:
                batch = [await queue.get()]
                # take an in-flight slot BEFORE assembling: while every
                # slot is busy the queue keeps filling, which IS the
                # batching signal — no linger needed under saturation
                await sem.acquire()
                dispatched = False
                try:
                    while len(batch) < self.max_batch and not queue.empty():
                        batch.append(queue.get_nowait())
                    linger = self._linger_window()
                    linger_dt = 0.0
                    if linger > 0.0 and len(batch) < self.max_batch:
                        t0 = time.perf_counter()
                        await asyncio.sleep(linger)
                        linger_dt = time.perf_counter() - t0
                        self._observe_span("batch_linger", linger_dt)
                        while (len(batch) < self.max_batch
                               and not queue.empty()):
                            batch.append(queue.get_nowait())
                    if self._size_hist is not None:
                        self._size_hist.observe(float(len(batch)))
                    queries = [entry[0] for entry in batch]
                    # the batch runs under the FIRST traced submitter's
                    # context (coalesced siblings ride the same batch)
                    ctx = next((entry[2] for entry in batch
                                if entry[2] is not None), None)
                    # (submit perf_counter, submitter Trace) per member —
                    # the anatomy observation at batch end amortizes from
                    # these
                    meta = [(entry[3], entry[4]) for entry in batch]
                    t_dispatch = time.perf_counter()
                    ex_fut = loop.run_in_executor(
                        self._executor, self._run_batch, queries, ctx,
                        meta, linger_dt, t_dispatch)
                    self._inflight_now += 1
                    if self._inflight_gauge is not None:
                        self._inflight_gauge.set(float(self._inflight_now))
                    ex_fut.add_done_callback(
                        functools.partial(self._finish_batch, batch, sem))
                    dispatched = True
                finally:
                    if not dispatched:
                        sem.release()
                batch = []
        finally:
            # worker died (cancellation at shutdown, BaseException): fail
            # everything not yet dispatched so no HTTP handler hangs on
            # `await fut`; already-dispatched batches resolve through
            # their executor-future callbacks
            while not queue.empty():
                batch.append(queue.get_nowait())
            for entry in batch:
                fut = entry[1]
                if not fut.done():
                    fut.set_exception(
                        RuntimeError("query micro-batch worker stopped"))

    def _run_batch(self, queries, ctx, meta=(), linger_s=0.0,
                   t_dispatch=0.0):
        """Executor-side batch dispatch, re-entering the submitting
        request's trace when one was captured — the serving_batch hop
        (and its batch_* stage spans) land in the flight recorder under
        the request's trace id."""
        if ctx is None:
            return self._run_measured(queries, meta, linger_s, t_dispatch)
        with carried(ctx, "serving_batch", registry=self._registry,
                     span_hist=self._span_hist,
                     attrs={"batch": len(queries)}):
            return self._run_measured(queries, meta, linger_s, t_dispatch)

    def _run_measured(self, queries, meta, linger_s, t_dispatch):
        """Run the batch under an anatomy breakdown: the predict path's
        _stage blocks, the padding geometry, and the fn_cache dispatch
        wrapper fill it, and each member's per-request stage share is
        observed when the batch completes — before the futures resolve,
        so the stages are on the trace when the middleware records it."""
        if self._anatomy is None or not anatomy_enabled():
            return self._predict_batch(queries)
        bd = BatchBreakdown()
        token = push_breakdown(bd)
        try:
            results = self._predict_batch(queries)
        finally:
            pop_breakdown(token)
        try:
            observe_serving_batch(self._anatomy, bd, meta, linger_s,
                                  t_dispatch)
        except Exception:
            logger.exception("anatomy observation failed")
        return results

    def _finish_batch(self, batch, sem: asyncio.Semaphore, ex_fut) -> None:
        """Runs on the event loop when a dispatched batch's executor
        future settles: free the in-flight slot, then route per-query
        results/errors to their awaiting handlers."""
        self._inflight_now -= 1
        if self._inflight_gauge is not None:
            self._inflight_gauge.set(float(self._inflight_now))
        sem.release()
        try:
            results = ex_fut.result()
        except BaseException as e:   # noqa: BLE001 — must never orphan futs
            err = e if isinstance(e, Exception) else \
                RuntimeError(f"micro-batch dispatch failed: {e!r}")
            results = [err] * len(batch)
        for entry, res in zip(batch, results):
            fut = entry[1]
            if fut.done():
                continue
            if isinstance(res, Exception):
                fut.set_exception(res)
            else:
                fut.set_result(res)


@dataclasses.dataclass
class CanaryState:
    """One in-flight staged rollout: the candidate unit plus its judge."""

    unit: ServingUnit
    controller: CanaryController
    config: CanaryConfig


class QueryServer:
    def __init__(self, engine: Engine, train_result: TrainResult,
                 instance: EngineInstance, ctx,
                 feedback: bool = False,
                 feedback_app_name: Optional[str] = None,
                 access_key: Optional[str] = None,
                 plugin_context: Optional[PluginContext] = None,
                 log_url: Optional[str] = None,
                 log_prefix: str = "",
                 registry: Optional[MetricsRegistry] = None,
                 serving_config: Optional[ServingConfig] = None,
                 deploy_config: Optional[DeployConfig] = None,
                 release: Optional[Release] = None,
                 foldin_config: Optional[FoldinConfig] = None,
                 scorer_config: Optional[ScorerConfig] = None,
                 slo_spec: Optional[SLOSpec] = None,
                 telemetry=None,
                 pin_process_scorer: bool = True):
        self.engine = engine
        self.feedback = feedback
        self.feedback_app_name = feedback_app_name
        #: remote error sink (CreateServer.scala:435-446 remoteLog): on a
        #: failed query, POST log_prefix + {"engineInstance", "message"}
        self.log_url = log_url
        self.log_prefix = log_prefix
        # resolve the feedback app once; a per-query metadata lookup would
        # sit on the hot path
        self._feedback_target = None
        if feedback and feedback_app_name:
            from predictionio_tpu.data.eventstore import resolve_app

            self._feedback_target = resolve_app(feedback_app_name)
        self.access_key = access_key
        self.plugins = plugin_context or PluginContext(
            "predictionio_tpu.engineserver_plugins")
        self.start_time = _dt.datetime.now(tz=UTC)
        self.last_serving_sec = 0.0
        self._stop_event = asyncio.Event()
        self.registry = registry or MetricsRegistry()
        register_jax_metrics(default_registry())
        self.serving_config = serving_config or ServingConfig.from_env()
        self.deploy_config = deploy_config or DeployConfig.from_env()
        self.foldin_config = foldin_config or FoldinConfig.from_env()
        #: resolved scoring-kernel knobs (env > engine.json "scorer" >
        #: server.json — pio deploy passes the engine.json-aware config
        #: explicitly). Pinned process-wide so every scoring surface the
        #: serving units reach (models, warm-up, fold-in drives) sees
        #: ONE mode; /deploy/status.json echoes it per unit.
        from predictionio_tpu.ops import scoring as _scoring

        self.scorer_config = scorer_config or ScorerConfig.from_env()
        #: multi-tenant hosting passes pin_process_scorer=False: N
        #: co-hosted servers cannot all own the ONE process pin, so each
        #: stamps its resolved config onto its own model holders instead
        #: (ops/scoring.holder_scorer_config) — tenant A can hold int8
        #: residency while tenant B holds bf16 in the same process
        self._pin_process_scorer = bool(pin_process_scorer)
        if self._pin_process_scorer:
            _scoring.set_process_scorer_config(self.scorer_config)
        else:
            self._stamp_scorer_override(train_result)
        #: online fold-in controller (deploy/foldin.py), started on the
        #: server loop when enabled AND the engine supports it
        self._foldin = None
        #: dedicated bounded pool for predictions ONLY — feedback writes
        #: and remote logging stay on the loop's default executor, so a
        #: burst of event-store writes can never starve the hot path (and
        #: vice versa). Sized past `batch_inflight` so non-vectorized
        #: engines (per-request path) still get some parallelism.
        self._predict_executor = ThreadPoolExecutor(
            max_workers=max(4, self.serving_config.batch_inflight * 2),
            thread_name_prefix="pio-predict")
        #: one background lane for deploy phases (load/warmup/verify):
        #: a warmup compiling the whole shape ladder must never occupy a
        #: predict slot of the incumbent
        self._deploy_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-deploy")
        #: release-lineage writes are best-effort AND ordered: a single
        #: worker preserves submission order, so a canary's CANARY write
        #: and the operator rollback's ROLLED_BACK that follows it can
        #: never commit inverted (observed as a release stuck at CANARY
        #: when both rode the shared default executor)
        self._lineage_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pio-lineage")
        #: pre-resolved span-histogram handle for batch-stage timings
        #: (_predict_batch runs per batch on the executor — it must not
        #: take the registry lock to re-resolve the histogram each stage)
        self._span_hist = span_histogram(self.registry)
        #: anatomy stage histograms (serialize stage observes per request)
        self._anatomy = anatomy_metrics(self.registry)
        #: capacity ledger: per-unit residency gauge walks the live units
        register_capacity_metrics(self.registry, self._capacity_units)
        self._pad_waste = self.registry.counter(
            "pio_batch_pad_waste_rows_total",
            "Throwaway rows added padding batches up to their shape "
            "bucket (the price of a bounded compile-shape set)")
        self._deploy = deploy_metrics(self.registry)
        #: THE serving state: everything a query touches, swapped as one
        #: reference ('result' and 'vectorized' can never be observed
        #: half-updated). The previous LIVE unit is kept resident as the
        #: instant-rollback standby (blue/green).
        self._unit = ServingUnit(
            instance=instance, result=train_result, ctx=ctx,
            vectorized=self._compute_vectorized(train_result),
            release=release)
        self._attach_batcher(self._unit)
        self._standby: Optional[ServingUnit] = None
        self._canary: Optional["CanaryState"] = None
        #: serializes unit-reference cutover against the fold-in
        #: controller's executor-thread swaps (deploy/foldin.py): the
        #: deploy paths assign on the event loop, fold-in compare-and-
        #: swaps from the deploy executor — without the lock a reload
        #: completing during a fold-in solve could be silently reverted
        self._swap_lock = threading.Lock()
        #: strong refs to fire-and-forget deploy tasks (retire/verdict/
        #: shadow) — the loop holds tasks weakly, so an unreferenced one
        #: can be garbage-collected mid-flight
        self._bg_tasks: set = set()
        self._last_query = None          # warmup fallback for /reload
        self._last_warmup: Optional[WarmupReport] = None
        self._deploy.active_version.set(float(self._unit.release_version))
        self._query_hist = self.registry.histogram(
            "pio_query_duration_seconds",
            "Query hot-path wall time by engine variant",
            labelnames=("engine_variant",))
        self._query_failures = self.registry.counter(
            "pio_query_failures_total",
            "Failed queries by engine variant and cause "
            "(bad_json = client garbage, predict_error = engine failure)",
            labelnames=("engine_variant", "reason"))
        self._feedback_hist = self.registry.histogram(
            "pio_feedback_write_duration_seconds",
            "Feedback-loop event store write wall time")
        self._reload_total = self.registry.counter(
            "pio_reload_total", "Model reload attempts by outcome",
            labelnames=("status",))
        #: warm-eviction residency state (multi-tenant budgeter): an
        #: evicted server keeps serving a WARM unit (instance + registry
        #: release pointer retained, factors dropped) and reloads through
        #: the warmup ladder on the next hit — `_reload_event` is the
        #: single-flight latch queries wait on, `_warm_bytes` remembers
        #: the last resident attribution for pre-reload budget projection
        self._reload_event: Optional[asyncio.Event] = None
        self._warm_bytes: int = 0
        self._evict_total = self.registry.counter(
            "pio_unit_evictions_total",
            "Serving units evicted to warm on-host state (factors "
            "dropped, params + release pointer retained)",
            labelnames=("reason",))
        #: SLO burn-rate engine (obs/slo.py) when the host configured a
        #: server.json "slo" section — evaluated periodically on the loop
        #: and on-demand at /slo.json; canary + fold-in gating consume it
        self._slo = (SLOEngine(self.registry, slo_spec)
                     if slo_spec is not None else None)
        self._slo_task: Optional[asyncio.Task] = None
        #: durable-telemetry recorder (obs/telemetry.py), owned by this
        #: server when given: scrape loop persists the registry + flight
        #: recorder, /history/* serves the host's merged stores, and the
        #: SLO rings REHYDRATE from history so an error budget burned
        #: before a restart stays burned (breach-in-progress survives)
        self._telemetry = telemetry
        if self._telemetry is not None and self._slo is not None:
            try:
                self._slo.rehydrate(self._telemetry.reader())
            except Exception:
                logger.exception("SLO rehydration from history failed")
        self.app = web.Application(middlewares=[
            observability_middleware(self.registry, "query_server")])
        self.app.on_startup.append(self._on_startup_foldin)
        self.app.on_startup.append(self._on_startup_slo)
        self.app.on_cleanup.append(self._on_cleanup)
        self._routes()

    async def _on_startup_foldin(self, app) -> None:
        """Start the online fold-in controller when enabled and the
        deployed engine implements the fold-in hooks; an unsupported
        engine logs and serves exactly as before."""
        if not self.foldin_config.enabled:
            return
        from predictionio_tpu.deploy.foldin import (
            FoldInController, FoldinUnsupported,
        )

        try:
            self._foldin = FoldInController(self, self.foldin_config,
                                            registry=self.registry)
        except FoldinUnsupported as e:
            logger.warning("online fold-in disabled: %s", e)
            return
        self._foldin.start()
        logger.info("online fold-in armed: interval %.2fs, max pending %d",
                    self.foldin_config.apply_interval_s,
                    self.foldin_config.max_pending)

    async def _on_startup_slo(self, app) -> None:
        """Periodic SLO evaluation: burn-rate gauges and breach events
        update every eval interval even when nothing reads /slo.json."""
        if self._slo is None:
            return

        async def _loop():
            interval = self._slo.spec.eval_interval_s
            while True:
                await asyncio.sleep(interval)
                try:
                    self._slo.tick()
                except Exception:
                    logger.exception("SLO evaluation failed")

        self._slo_task = asyncio.get_running_loop().create_task(_loop())
        logger.info("SLO engine armed: %d objective(s), eval every %.2fs",
                    len(self._slo.spec.objectives),
                    self._slo.spec.eval_interval_s)

    async def _on_cleanup(self, app) -> None:
        if self._slo_task is not None:
            self._slo_task.cancel()
            try:
                await self._slo_task
            except (asyncio.CancelledError, Exception):
                pass
        if self._foldin is not None:
            await self._foldin.aclose()
        # settle the deploy background tasks first (a mid-drain
        # _retire_batcher would otherwise die as a destroyed-pending task)
        for task in list(self._bg_tasks):
            task.cancel()
        if self._bg_tasks:
            await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        # then drain every batcher still alive — active, canary, AND a
        # standby whose retirement the cancel above interrupted — BEFORE
        # the executor goes away: their workers' finally fails queued
        # queries fast instead of leaving a pending task (and a 'Task
        # was destroyed' warning) behind the loop
        units = list(self._live_units())
        if self._standby is not None:
            units.append(self._standby)
        for unit in units:
            if unit.batcher is not None:
                await unit.batcher.shutdown()
        self._predict_executor.shutdown(wait=False)
        # join, not fire-and-forget: an in-flight fold-in apply on this
        # executor reads the event store — it must finish BEFORE the
        # caller tears shared state (Storage config) down under it
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._deploy_executor.shutdown(wait=True))
        # lineage writes drain: the last status transition of a shutdown
        # (a rollback's ROLLED_BACK) must land before the process exits
        await asyncio.get_running_loop().run_in_executor(
            None, lambda: self._lineage_executor.shutdown(wait=True))
        if self._telemetry is not None:
            # LAST: the final drain must include the flight-recorder
            # records the steps above just emitted (fold-in close,
            # batcher retirement, the lineage lane's terminal writes)
            await asyncio.get_running_loop().run_in_executor(
                None, self._telemetry.stop)

    def _routes(self):
        r = self.app.router
        r.add_get("/", self.handle_root)
        r.add_post("/queries.json", self.handle_query)
        r.add_get("/reload", self.handle_reload)
        r.add_post("/stop", self.handle_stop)
        r.add_get("/plugins.json", self.handle_plugins)
        r.add_get("/releases.json", self.handle_releases)
        r.add_get("/deploy/status.json", self.handle_deploy_status)
        r.add_post("/deploy.json", self.handle_deploy)
        r.add_post("/rollback.json", self.handle_rollback)
        r.add_get("/slo.json", self.handle_slo)
        r.add_post("/debug/profile", self.handle_profile)
        add_capacity_route(self.app, self._capacity_units)
        add_metrics_routes(self.app, self.registry, default_registry())
        from predictionio_tpu.obs.telemetry import (
            add_history_routes, history_reader_factory,
        )

        add_history_routes(self.app,
                           history_reader_factory(self._telemetry))

    # -- serving-unit plumbing (deploy/ subsystem) ---------------------------
    @property
    def result(self) -> TrainResult:
        return self._unit.result

    @property
    def instance(self) -> EngineInstance:
        return self._unit.instance

    @property
    def ctx(self):
        return self._unit.ctx

    @property
    def batcher(self) -> MicroBatcher:
        return self._unit.batcher

    @property
    def _vectorized_cached(self) -> bool:
        return self._unit.vectorized

    @_vectorized_cached.setter
    def _vectorized_cached(self, value: bool) -> None:
        self._unit.vectorized = value

    def _attach_batcher(self, unit: ServingUnit) -> None:
        """Give a unit its own micro-batcher closed over ITS result —
        batches drained after a swap still score on the release they
        were routed to."""
        unit.batcher = MicroBatcher(
            functools.partial(self._predict_batch_unit, unit),
            max_batch=self.serving_config.batch_max,
            linger_s=self.serving_config.batch_linger_s,
            inflight=self.serving_config.batch_inflight,
            executor=self._predict_executor,
            registry=self.registry)
        # each MicroBatcher points the depth gauge at itself; the server
        # owns the truth: queued queries across every live unit
        self.registry.gauge_callback(
            "pio_batch_queue_depth",
            "Queries waiting in the micro-batch queue",
            lambda: float(sum(
                u.batcher.queue_depth()
                for u in self._live_units() if u.batcher is not None)))

    def _live_units(self) -> List[ServingUnit]:
        units = [self._unit]
        if self._canary is not None:
            units.append(self._canary.unit)
        return units

    def _capacity_units(self) -> List[dict]:
        """Per-unit residency roll-up for /capacity.json and the
        pio_capacity_unit_resident_bytes gauge: the active unit, the
        blue/green standby kept resident for instant rollback, and a
        staged canary — the exact set the memory budgeter must account."""
        units = [unit_capacity(self._unit, "active")]
        if self._standby is not None:
            units.append(unit_capacity(self._standby, "standby"))
        canary = self._canary
        if canary is not None:
            units.append(unit_capacity(canary.unit, "canary"))
        return units

    def _spawn(self, coro) -> None:
        """create_task with a strong reference held until completion."""
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    # -- info ---------------------------------------------------------------
    async def handle_root(self, request):
        """Engine/instance info + serving stats (CreateServer.scala:460-482),
        latency figures sourced from the metrics registry."""
        count = self._query_hist.total_count()
        total = self._query_hist.total_sum()
        uptime = (_dt.datetime.now(tz=UTC) - self.start_time).total_seconds()
        return web.json_response({
            "status": "alive",
            "engineInstance": {
                "id": self.instance.id,
                "engineId": self.instance.engine_id,
                "engineVariant": self.instance.engine_variant,
                "startTime": self.instance.start_time.isoformat(),
                "releaseVersion": self._unit.release_version or None,
            },
            "resident": self.resident,
            "algorithms": [type(a).__name__ for a in
                           (self.result.algorithms
                            if self.result is not None else ())],
            "startTime": self.start_time.isoformat(),
            "uptimeSeconds": uptime,
            "requestCount": int(count),
            "queryCount": int(count),
            "avgServingSec": (total / count) if count else 0.0,
            "p95ServingSec": self._query_hist.quantile(0.95),
            "lastServingSec": self.last_serving_sec,
        })

    async def _remote_log(self, message: str) -> None:
        """POST a serving failure to the operator's log sink
        (CreateServer.scala:435-446 remoteLog parity: prefix + JSON of
        engine-instance metadata and the message; delivery failures are
        logged locally and never propagate to the client response)."""
        import aiohttp

        payload = self.log_prefix + json.dumps({
            "engineInstance": {"id": self.instance.id,
                               "engineId": self.instance.engine_id,
                               "engineVariant": self.instance.engine_variant},
            "message": message})
        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        self.log_url, data=payload,
                        timeout=aiohttp.ClientTimeout(total=5)):
                    pass
        except Exception as e:
            logger.error("Unable to send remote log: %s", e)

    # -- hot path (CreateServer.scala:484-605) -------------------------------
    async def handle_query(self, request):
        t0 = time.perf_counter()
        variant = self.instance.engine_variant
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            self._query_failures.inc(engine_variant=variant,
                                     reason="bad_json")
            return web.json_response({"message": str(e)}, status=400)
        # route: snapshot the unit ONCE — everything this request touches
        # (result, vectorized flag, batcher) rides that one reference, so
        # a concurrent swap can never hand it mismatched halves
        role, unit, canary = ROLE_INCUMBENT, self._unit, self._canary
        if unit.result is None:
            # warm-evicted: factors were dropped under the device-memory
            # budget. Kick (or join) the single-flight reload and wait,
            # bounded — past the bound the client gets a clean 503 with
            # Retry-After rather than an unbounded queue
            if not await self.ensure_resident():
                self._query_failures.inc(engine_variant=variant,
                                         reason="not_resident")
                return web.json_response(
                    {"message": "serving unit is reloading; retry"},
                    status=503, headers={"Retry-After": "1"})
            role, unit, canary = ROLE_INCUMBENT, self._unit, self._canary
        if canary is not None and canary.controller.decided is None:
            if canary.controller.splitter.route():
                role, unit = ROLE_CANARY, canary.unit
            # publish the diffusion accumulator so the telemetry scrape
            # persists it; a restarted server restores the exact
            # mid-stream split instead of re-seeding at zero
            self._deploy.canary_splitter_acc.set(
                canary.controller.splitter.state())
        t_predict = time.perf_counter()
        try:
            # spans resolve through the middleware-installed trace, which
            # carries a pre-resolved histogram handle (no lock on hot path)
            with span("extract_query"):
                query = self._extract_query(body)
            self._last_query = query      # warmup fallback for /reload
            with span("predict"):
                prediction = await self._predict_via(unit, query)
        except Exception as e:
            self._observe_role(canary, role,
                               time.perf_counter() - t_predict, ok=False)
            logger.exception("query failed")
            self._query_failures.inc(engine_variant=variant,
                                     reason="predict_error")
            if self.log_url:
                await self._remote_log(
                    f"Query:\n{json.dumps(body)}\n\nError:\n{e!r}\n\n")
            return web.json_response({"message": str(e)}, status=400)
        self._observe_role(canary, role,
                           time.perf_counter() - t_predict, ok=True)
        t_serialize = time.perf_counter()
        if (canary is not None and canary.config.shadow
                and canary.controller.decided is None):
            # shadow mode: mirror the query into the candidate off the
            # response path; its result is scored for SLOs and discarded
            self._spawn(self._shadow_score(canary, query))

        pred_json = _to_jsonable(prediction)
        # feedback loop: tag with prId and record events (:527-589)
        if self.feedback and self.feedback_app_name:
            pr_id = (pred_json.get("prId") if isinstance(pred_json, dict)
                     else None) or generate_id()
            if isinstance(pred_json, dict):
                pred_json = dict(pred_json)
                pred_json["prId"] = pr_id
            asyncio.get_running_loop().run_in_executor(
                None, self._record_feedback, body, pred_json, pr_id)
        # output blockers transform; sniffers observe
        for blocker in self.plugins.output_blockers.values():
            try:
                pred_json = blocker.process(self.instance, body, pred_json)
            except Exception:
                logger.exception("output blocker failed")
        for sniffer in self.plugins.output_sniffers.values():
            try:
                sniffer.process(self.instance, body, pred_json)
            except Exception:
                logger.exception("output sniffer failed")

        if anatomy_enabled():
            # the post-predict tail: feedback scheduling, blockers,
            # sniffers, JSON conversion — the "serialize" anatomy stage
            observe_stage(self._anatomy, SERVING_PATH, "serialize",
                          time.perf_counter() - t_serialize,
                          current_trace())
        dt = time.perf_counter() - t0
        self.last_serving_sec = dt
        self._query_hist.observe(dt, engine_variant=variant)
        return web.json_response(pred_json)

    def _extract_query(self, body: dict):
        if self.result is None:        # warm-evicted: no algorithms to ask
            return body
        qc = _query_class(self.result)
        if qc is None:
            return body
        return params_from_json(body, qc)

    async def _predict_via(self, unit: ServingUnit, query):
        """Score one query on a specific serving unit (incumbent or
        canary): through ITS batcher when vectorized, else per-request
        on the predict pool."""
        if unit.vectorized:
            return await unit.batcher.submit(query)
        # no vectorized batch_predict to exploit — per-request
        # parallelism on the server's own bounded pool beats
        # serializing into one batch
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._predict_executor, self._predict_unit, unit, query)

    def _observe_role(self, canary: Optional["CanaryState"], role: str,
                      seconds: float, ok: bool) -> None:
        """Per-role accounting: every query increments
        pio_deploy_requests_total, and during a staged rollout feeds the
        SLO judge — whose verdict (promote/rollback) is acted on off the
        request path."""
        self._deploy.requests_total.inc(role=role)
        if canary is None or canary is not self._canary:
            return
        verdict = canary.controller.observe(role, seconds, ok)
        if verdict is not None:
            self._spawn(self._act_on_verdict(canary, verdict))

    def _restore_canary_splitter(self, controller) -> None:
        """Re-seed the canary splitter's diffusion accumulator from the
        durable telemetry store (the restart-skew fix: process-local, a
        restart mid-canary would re-seed at 0 and skew the realized
        fraction for the first ~1/fraction queries). The last persisted
        ``pio_deploy_canary_splitter_acc`` point wins; restore()
        ignores junk."""
        if self._telemetry is None:
            return
        try:
            points = [p for info in self._telemetry.reader().series(
                "pio_deploy_canary_splitter_acc") for p in info.points]
            if points:
                controller.splitter.restore(max(points)[1])
                self._deploy.canary_splitter_acc.set(
                    controller.splitter.state())
        except Exception:
            logger.exception("canary splitter restore failed; starting "
                             "from a zero accumulator")

    async def _shadow_score(self, canary: "CanaryState", query) -> None:
        """Score-but-discard: the candidate sees real traffic shape
        without serving a single user-visible byte."""
        t0 = time.perf_counter()
        try:
            await self._predict_via(canary.unit, query)
            ok = True
        except Exception:
            ok = False
        self._observe_role(canary, ROLE_SHADOW,
                           time.perf_counter() - t0, ok)

    def _vectorized(self) -> bool:
        """Cached per ServingUnit — the walk itself is cheap but it sat
        on EVERY request; recomputed only when a swap installs a new
        unit."""
        return self._unit.vectorized

    @staticmethod
    def _compute_vectorized(result: TrainResult) -> bool:
        """Micro-batching only pays when EVERY algorithm overrides
        batch_predict with a batched implementation — with a mix, the
        non-vectorized algorithms would run their serial per-query loop
        inside the single batcher worker, which is slower than the
        per-request thread-pool path."""
        from predictionio_tpu.core.base import Algorithm

        return bool(result.algorithms) and all(
            type(a).batch_predict is not Algorithm.batch_predict
            for a in result.algorithms)

    def _predict(self, query):
        return self._predict_unit(self._unit, query)

    def _predict_unit(self, unit: ServingUnit, query):
        result = unit.result
        supplemented = result.serving.supplement(query)
        predictions = [
            algo.predict(model, supplemented)
            for algo, model in zip(result.algorithms, result.models)]
        return result.serving.serve(query, predictions)

    def _predict_batch(self, queries):
        """Active-unit batch path (tests/bench call this directly)."""
        return self._predict_batch_unit(self._unit, queries)

    def _predict_batch_unit(self, unit: ServingUnit, queries):
        """Batch path behind each unit's MicroBatcher (runs on the
        predict executor).

        Per-query errors are isolated: a failing query yields its
        Exception in the result slot, never poisoning the rest of the
        batch. Before the scorers run, the batch is padded up to its
        power-of-two shape bucket (ops/bucketing) with clones of the last
        real query under sentinel indices — jitted scorers therefore see
        at most `bucket_count(max_batch)` distinct batch shapes ever, and
        the padded rows are sliced off here so they never reach
        `serving.serve` or a client.

        This server-level pad is what protects engines whose
        batch_predict jits on the RAW batch length (classification's
        `_vector_batch_predict` scores an [B, d] feature matrix through
        a stable jit). ALS additionally re-buckets on its own device
        rows (unknown users shrink B mid-model, so it must); for
        host-BLAS scorers the pad is a few microseconds of duplicated
        matvec — the bounded price of one rule for every engine."""
        result = unit.result      # the unit IS the swap-consistency unit
        n = len(queries)
        out = [None] * n
        ok = []
        with _stage(self._span_hist, "batch_assemble"):
            for i, q in enumerate(queries):
                try:
                    ok.append((i, result.serving.supplement(q)))
                except Exception as e:
                    out[i] = e
            if not ok:
                return out
            bucket = bucket_size(len(ok), self.serving_config.batch_max)
            waste = padding_waste(len(ok), bucket)
            bd = active_breakdown()
            if bd is not None:
                # pad geometry for the per-member pad_share attribution
                bd.note_padding(len(ok), waste, bucket)
            if waste:
                # sentinel indices >= n mark pad rows; their predictions
                # are computed and thrown away — the bounded price of a
                # bounded compile-shape set
                pad_q = ok[-1][1]
                batch = ok + [(n + j, pad_q) for j in range(waste)]
                self._pad_waste.inc(waste)
            else:
                batch = ok
        try:
            per_query = {i: [] for i, _ in ok}
            with _stage(self._span_hist, "batch_device"):
                for algo, model in zip(result.algorithms, result.models):
                    for i, p in algo.batch_predict(model, batch):
                        if i in per_query:      # pad rows sliced off
                            per_query[i].append(p)
            with _stage(self._span_hist, "batch_serve"):
                for i, _ in ok:
                    try:
                        out[i] = result.serving.serve(queries[i],
                                                      per_query[i])
                    except Exception as e:
                        out[i] = e
        except Exception:
            # batch path failed (poison query inside a vectorized
            # batch_predict) — isolate by falling back to per-query predict
            for i, sq in ok:
                try:
                    preds = [a.predict(m, sq) for a, m in
                             zip(result.algorithms, result.models)]
                    out[i] = result.serving.serve(queries[i], preds)
                except Exception as e:
                    out[i] = e
        return out

    def _record_feedback(self, query_json, pred_json, pr_id):
        """Write predict/actual linkage events (CreateServer.scala:563-589)."""
        t0 = time.perf_counter()
        try:
            app_id, channel_id = self._feedback_target
            event = Event(
                event="predict",
                entity_type="pio_pr",
                entity_id=pr_id,
                properties=DataMap({"query": query_json,
                                    "prediction": pred_json}),
            )
            Storage.get_events().insert(event, app_id, channel_id)
            self._feedback_hist.observe(time.perf_counter() - t0)
        except Exception:
            logger.exception("feedback recording failed")

    # -- management ----------------------------------------------------------
    def _authorized(self, request) -> bool:
        if not self.access_key:
            return True
        return request.query.get("accessKey") == self.access_key

    # -- deploy lifecycle (deploy/ subsystem) --------------------------------
    def _effective_warmup(self, override: Optional[bool]) -> bool:
        """The warmup flag a prepare actually ran with: a per-deploy body
        override beats DeployConfig — the swap-mode metric label must
        agree with it."""
        return bool(self.deploy_config.warmup if override is None
                    else override)

    def _phase_timer(self, phase: str):
        """Time one deploy phase into the pio_deploy phase histogram AND
        the request trace (deploy_<phase> span)."""
        @contextlib.contextmanager
        def _cm():
            t0 = time.perf_counter()
            with span(f"deploy_{phase}"):
                try:
                    yield
                finally:
                    self._deploy.phase_hist.observe(
                        time.perf_counter() - t0, phase=phase)
        return _cm()

    async def _prepare_unit(self, instance: EngineInstance,
                            release: Optional[Release],
                            warmup: Optional[bool] = None,
                            warmup_query_json: Optional[dict] = None
                            ) -> ServingUnit:
        """The pre-cutover pipeline: load -> warmup -> verify, all on the
        deploy lane so the incumbent never donates a predict slot. The
        returned unit is fully compiled and health-checked but NOT yet
        taking traffic."""
        loop = asyncio.get_running_loop()
        with self._phase_timer("load"):
            unit = await loop.run_in_executor(
                self._deploy_executor, build_unit, self.engine, instance,
                release)
        self._stamp_scorer_override(unit.result)
        self._attach_batcher(unit)
        predict_batch = functools.partial(self._predict_batch_unit, unit)
        explicit_q = None
        if warmup_query_json is not None:
            explicit_q = self._extract_query(warmup_query_json)
        warm = self._effective_warmup(warmup)
        if warm:
            with self._phase_timer("warmup"):
                report = await loop.run_in_executor(
                    self._deploy_executor, warmup_unit, unit, predict_batch,
                    self.serving_config.batch_max,
                    explicit_q if explicit_q is not None else self._last_query)
            self._deploy.warmup_shapes.inc(len(report.buckets))
            self._last_warmup = report
            logger.info("warmup for instance %s: buckets=%s compiles=%d "
                        "(%.3fs)%s", instance.id, report.buckets,
                        report.compile_delta, report.seconds,
                        f" skipped={report.skipped}" if report.skipped else "")
        else:
            self._last_warmup = WarmupReport(skipped="disabled")
        with self._phase_timer("verify"):
            await loop.run_in_executor(
                self._deploy_executor, verify_unit, unit, predict_batch,
                explicit_q if explicit_q is not None else self._last_query)
        return unit

    def _swap_to(self, unit: ServingUnit, mode: str, reason: str,
                 retire_old: bool = True) -> None:
        """THE cutover: one reference assignment installs the new unit;
        the old unit becomes the instant-rollback standby and its batcher
        drains in the background. ``retire_old=False`` leaves the
        outgoing unit's release status to the caller (rollback marks it
        ROLLED_BACK, not RETIRED)."""
        with self._phase_timer("swap"):
            with self._swap_lock:
                old = self._unit
                self._unit = unit
        self._deploy.swap_total.inc(mode=mode, outcome="ok")
        self._deploy.active_version.set(float(unit.release_version))
        record_event("swap", {
            "mode": mode, "reason": reason,
            "engineInstanceId": unit.instance.id,
            "releaseVersion": unit.release_version or None})
        self._standby = old
        self._spawn(self._retire_batcher(old))
        self._set_release_status(unit.release, "LIVE", reason)
        if retire_old and old.release is not None and (
                unit.release is None or old.release.id != unit.release.id):
            self._set_release_status(old.release, "RETIRED",
                                     f"superseded: {reason}")
        logger.info("swapped to engine instance %s (%s: %s)",
                    unit.instance.id, mode, reason)

    # -- warm eviction / reload (multi-tenant residency budgeter) ------------
    def _stamp_scorer_override(self, result) -> None:
        """When this server does NOT own the process scorer pin (a
        multi-tenant host serves many servers in one process), stamp the
        per-tenant scorer config onto every model holder so
        ``holder_scorer_config`` resolves it instead of the process pin —
        tenant A can stay int8 while tenant B scores bf16."""
        if self._pin_process_scorer or result is None:
            return
        for model in getattr(result, "models", ()) or ():
            try:
                model._scorer_cfg_override = self.scorer_config
            except Exception:  # frozen/odd holders: fall back to process pin
                pass

    @property
    def resident(self) -> bool:
        """Whether the active unit holds device-resident factors."""
        return self._unit.result is not None

    @property
    def warm_bytes(self) -> int:
        """Last known resident attribution: live bytes while resident,
        the pre-eviction footprint while warm (the budgeter's projection
        of what a reload will cost)."""
        if self.resident:
            return int(sum(u.get("residentBytes", 0)
                           for u in self._capacity_units()))
        return self._warm_bytes

    async def evict_to_warm(self, reason: str = "budget") -> bool:
        """Drop the active unit to warm on-host state: the instance and
        registry release pointer stay, the factors (TrainResult, scorer
        caches, standby) go. Runs under the `_swap_lock` discipline — the
        cutover installs a NEW factor-less ServingUnit, so a fold-in
        compare-and-swap racing the eviction loses cleanly
        (FoldinSwapRaced) instead of resurrecting dropped factors.

        Refused (returns False) while a canary window is open (the judge
        would lose its incumbent baseline), while a reload is already in
        flight, and on an already-warm unit."""
        from predictionio_tpu.storage import faults

        if self._canary is not None or self._reload_event is not None:
            return False
        with self._swap_lock:
            old = self._unit
            if old.result is None:
                return False
            warm = ServingUnit(
                instance=old.instance, result=None, ctx=old.ctx,
                vectorized=False, release=old.release)
            self._unit = warm
        # attribution BEFORE the factors drop: the budgeter projects the
        # reload cost from this number
        self._warm_bytes = int(
            unit_capacity(old, "active").get("residentBytes", 0))
        standby, self._standby = self._standby, None
        # in-flight and already-queued batches finish on the old unit's
        # own batcher (they score on the factors they were promised)
        await self._retire_batcher(old)
        faults.maybe_kill("mt:evict:drained")
        old.result = None
        old.batcher = None
        old.foldin_of = None
        if standby is not None:
            standby.result = None
            standby.batcher = None
            standby.foldin_of = None
        self._evict_total.inc(reason=reason)
        self._deploy.swap_total.inc(mode="evict", outcome="ok")
        record_event("evict", {
            "reason": reason,
            "engineInstanceId": warm.instance.id,
            "releaseVersion": warm.release_version or None,
            "residentBytes": self._warm_bytes})
        logger.info("evicted instance %s to warm state (%s, %d bytes)",
                    warm.instance.id, reason, self._warm_bytes)
        faults.maybe_kill("mt:evict:committed")
        return True

    async def ensure_resident(self, wait_s: Optional[float] = None) -> bool:
        """Queries hitting a warm unit call this: start (or join) the
        single-flight warm reload and wait for it, bounded by ``wait_s``
        (default: the deploy drain timeout). True when the active unit is
        resident on return."""
        if self._unit.result is not None:
            return True
        ev = self._reload_event
        if ev is None:
            self._reload_event = ev = asyncio.Event()
            self._spawn(self._reload_from_warm(ev))
        timeout = (wait_s if wait_s is not None
                   else self.deploy_config.drain_timeout_s)
        try:
            await asyncio.wait_for(asyncio.shield(ev.wait()), timeout)
        except asyncio.TimeoutError:
            return False
        return self._unit.result is not None

    async def _reload_from_warm(self, ev: asyncio.Event) -> None:
        """The reload half of the eviction cycle: drive the SAME
        load -> warmup -> verify ladder a deploy uses (the unit that
        swaps in is fully compiled and health-checked — never
        half-resident), then compare-and-swap it over the warm
        placeholder. A deploy/rollback that landed mid-reload wins: the
        reloaded unit is discarded, never silently installed."""
        from predictionio_tpu.storage import faults

        warm = self._unit
        try:
            unit = await self._prepare_unit(warm.instance, warm.release)
            faults.maybe_kill("mt:reload:loaded")
            with self._swap_lock:
                raced = self._unit is not warm
                if not raced:
                    self._unit = unit
            if raced:
                if unit.batcher is not None:
                    await unit.batcher.shutdown()
                unit.result = None
                self._reload_total.inc(status="warm_reload_raced")
                return
            self._deploy.swap_total.inc(mode="warm_reload", outcome="ok")
            self._deploy.active_version.set(float(unit.release_version))
            self._reload_total.inc(status="warm_reload")
            record_event("swap", {
                "mode": "warm_reload",
                "engineInstanceId": unit.instance.id,
                "releaseVersion": unit.release_version or None})
            faults.maybe_kill("mt:reload:committed")
        except DeployError:
            self._reload_total.inc(status="warm_reload_failed")
            self._deploy.swap_total.inc(mode="warm_reload",
                                        outcome="failed")
            logger.exception("warm reload failed; unit stays warm")
        finally:
            # waiters wake either way: resident -> serve, still warm ->
            # clean 503 (and the next hit retries the reload)
            self._reload_event = None
            ev.set()

    # -- online fold-in cutover (deploy/foldin.py) ---------------------------
    def build_foldin_unit(self, new_models, applied_rows: int,
                          drift_release: Optional[Release] = None,
                          base_unit: Optional[ServingUnit] = None
                          ) -> ServingUnit:
        """A fold-in drift of the active unit: same instance/ctx, new
        models, and `foldin_of` pinned to the PRE-fold-in base so every
        later drift (and the rollback path) can find it."""
        base = base_unit if base_unit is not None else self._unit
        result = dataclasses.replace(base.result, models=list(new_models))
        unit = ServingUnit(
            instance=base.instance, result=result, ctx=base.ctx,
            vectorized=self._compute_vectorized(result),
            release=drift_release or base.release)
        unit.foldin_of = base.foldin_of or base
        unit.foldin_rows = base.foldin_rows + applied_rows
        self._stamp_scorer_override(result)
        return unit

    def swap_foldin_unit(self, unit: ServingUnit, loop=None,
                         expected_base: Optional[ServingUnit] = None
                         ) -> None:
        """Fold-in cutover: the /reload atomic-swap discipline, warmup
        only when the drift grew the catalog (the controller pre-warms
        before calling; a user-only drift keeps the base's shapes). One
        reference assignment; in-flight batches keep scoring the unit
        they were routed to; the standby is pinned to the PRE-fold-in
        base so `pio rollback` restores pre-fold-in answers. Callable
        from any thread — the old batcher's drain is marshaled onto
        `loop` when one is running.

        ``expected_base`` makes it a compare-and-swap: the solve ran
        against a snapshot of the serving unit, and a /reload, /deploy,
        rollback, or canary cutover that landed meanwhile must win —
        raises :class:`FoldinSwapRaced` (the controller requeues its
        deltas) instead of silently reverting a real deploy to a drift
        of the old model."""
        if unit.batcher is None:
            self._attach_batcher(unit)
        with self._phase_timer("swap"):
            with self._swap_lock:
                if expected_base is not None and \
                        self._unit is not expected_base:
                    self._deploy.swap_total.inc(mode="foldin",
                                                outcome="raced")
                    raise FoldinSwapRaced(
                        "serving unit changed during the fold-in solve "
                        f"(now instance {self._unit.instance.id})")
                if self._canary is not None:
                    self._deploy.swap_total.inc(mode="foldin",
                                                outcome="raced")
                    raise FoldinSwapRaced(
                        "canary window opened during the fold-in solve")
                old = self._unit
                self._unit = unit
        self._deploy.swap_total.inc(mode="foldin", outcome="ok")
        self._deploy.active_version.set(float(unit.release_version))
        record_event("swap", {
            "mode": "foldin",
            "engineInstanceId": unit.instance.id,
            "releaseVersion": unit.release_version or None,
            "foldinRows": unit.foldin_rows})
        self._standby = unit.foldin_of
        if loop is not None and loop.is_running():
            fut = asyncio.run_coroutine_threadsafe(
                self._retire_batcher(old), loop)
            fut.add_done_callback(_log_retire_failure)

    async def _retire_batcher(self, unit: ServingUnit,
                              timeout: Optional[float] = None) -> None:
        """Graceful retirement: already-routed batches drain on the old
        unit's own batcher (they score on the release they were promised)
        before the worker is torn down. Aborts if the unit was promoted
        back to live mid-drain (a rollback inside the drain window must
        not tear down the batcher now serving traffic)."""
        batcher = unit.batcher
        if batcher is None:
            return

        def _reinstated() -> bool:
            return unit is self._unit or unit.batcher is not batcher

        t0 = time.perf_counter()
        deadline = t0 + (timeout if timeout is not None
                         else self.deploy_config.drain_timeout_s)
        while (batcher.queue_depth() > 0 or batcher._inflight_now > 0) \
                and time.perf_counter() < deadline:
            if _reinstated():
                return
            await asyncio.sleep(0.02)
        if _reinstated():
            return
        await batcher.shutdown()
        if unit.batcher is batcher:
            unit.batcher = None
        self._deploy.phase_hist.observe(time.perf_counter() - t0,
                                        phase="drain")

    def _set_release_status(self, release: Optional[Release], status: str,
                            reason: str) -> None:
        """Best-effort lineage write-back (off-thread; a registry outage
        must never wedge serving), ordered by the single lineage lane."""
        if release is None:
            return
        ctx = capture_context()

        def _write():
            with carried(ctx, "release_status", record=False):
                try:
                    Storage.get_meta_data_releases().set_status(
                        release.id, status, reason=reason)
                except Exception:
                    logger.exception(
                        "release status update failed (%s -> %s)",
                        release.id, status)
        release.status = status          # keep the resident copy honest
        try:
            asyncio.get_running_loop()
            self._lineage_executor.submit(_write)
        except RuntimeError:             # no loop (tests calling directly)
            _write()

    async def _act_on_verdict(self, canary: "CanaryState",
                              verdict) -> None:
        decision, reason = verdict
        if self._canary is not canary:
            return
        self._canary = None
        self._deploy.canary_fraction.set(0.0)
        record_event("canary_verdict", {
            "decision": decision, "reason": reason,
            "engineInstanceId": canary.unit.instance.id,
            "releaseVersion": canary.unit.release_version or None})
        if decision == "promote":
            self._deploy.promote_total.inc(
                reason="healthy" if reason.startswith("healthy") else reason)
            self._swap_to(canary.unit, mode="canary", reason=reason)
        else:
            slug = reason.split(":", 1)[0]
            self._deploy.rollback_total.inc(reason=slug)
            self._set_release_status(canary.unit.release, "ROLLED_BACK",
                                     reason)
            await self._retire_batcher(canary.unit)
            logger.warning("canary rolled back: %s", reason)

    async def handle_reload(self, request):
        """Warm-swap to the latest COMPLETED instance — "prepare new,
        verify healthy, atomically swap, retire old" (the reference's
        :342-371 ReloadServer reloaded cold, in place)."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        blocked = await self._settle_canary_first()
        if blocked is not None:
            return blocked
        loop = asyncio.get_running_loop()

        def _lookup():
            instances = Storage.get_meta_data_engine_instances()
            latest = instances.get_latest_completed(
                self.instance.engine_id, self.instance.engine_version,
                self.instance.engine_variant)
            release = None
            if latest is not None:
                try:
                    releases = Storage.get_meta_data_releases()
                    for r in releases.get_for_variant(
                            latest.engine_id, latest.engine_version,
                            latest.engine_variant):
                        if r.instance_id == latest.id:
                            release = r
                            break
                except Exception:
                    logger.exception("release lookup failed")
            return latest, release

        latest, release = await loop.run_in_executor(None, _lookup)
        if latest is None:
            self._reload_total.inc(status="not_found")
            return web.json_response(
                {"message": "No COMPLETED instance found"}, status=404)
        mode = "warm" if self._effective_warmup(None) else "cold"
        try:
            unit = await self._prepare_unit(latest, release)
        except DeployError as e:
            self._reload_total.inc(status="failed")
            self._deploy.swap_total.inc(mode=mode, outcome="failed")
            return web.json_response({"message": str(e)}, status=500)
        self._swap_to(unit, mode=mode, reason="reload")
        self._reload_total.inc(status="reloaded")
        return web.json_response({
            "message": "Reloaded",
            "engineInstanceId": latest.id,
            "releaseVersion": unit.release_version or None,
            "warmup": (self._last_warmup.to_dict()
                       if self._last_warmup else None)})

    async def handle_deploy(self, request):
        """Warm-deploy a specific release: full cutover by default, a
        canary/shadow rollout when the body asks for one."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        try:
            body = await request.json() if request.can_read_body else {}
        except json.JSONDecodeError as e:
            return web.json_response({"message": str(e)}, status=400)
        blocked = await self._settle_canary_first()
        if blocked is not None:
            return blocked
        loop = asyncio.get_running_loop()

        def _resolve():
            instances = Storage.get_meta_data_engine_instances()
            release = None
            if body.get("engineInstanceId"):
                instance = instances.get(str(body["engineInstanceId"]))
            else:
                selector = body.get("releaseId") or body.get("version")
                releases = Storage.get_meta_data_releases()
                release = resolve_release(
                    releases, self.instance.engine_id,
                    self.instance.engine_version,
                    self.instance.engine_variant,
                    str(selector) if selector is not None else None)
                instance = (instances.get(release.instance_id)
                            if release is not None else None)
            return instance, release

        instance, release = await loop.run_in_executor(None, _resolve)
        if instance is None or instance.status != "COMPLETED":
            return web.json_response(
                {"message": "No deployable release/instance matched."},
                status=404)
        mode = "warm" if self._effective_warmup(body.get("warmup")) \
            else "cold"
        try:
            unit = await self._prepare_unit(
                instance, release, warmup=body.get("warmup"),
                warmup_query_json=body.get("warmupQuery"))
        except DeployError as e:
            self._deploy.swap_total.inc(mode=mode, outcome="failed")
            self._set_release_status(release, "ROLLED_BACK",
                                     f"prepare failed: {e}")
            return web.json_response({"message": str(e)}, status=500)

        cfg = self._canary_config(body)
        if cfg is not None:
            controller = CanaryController(cfg)
            self._restore_canary_splitter(controller)
            self._canary = CanaryState(unit=unit, controller=controller,
                                       config=controller.config)
            self._deploy.canary_fraction.set(
                0.0 if cfg.shadow else controller.config.fraction)
            self._set_release_status(release, "CANARY",
                                     "shadow" if cfg.shadow else
                                     f"fraction={controller.config.fraction}")
            record_event("canary_start", {
                "engineInstanceId": instance.id,
                "releaseVersion": unit.release_version or None,
                "shadow": cfg.shadow,
                "fraction": controller.config.fraction})
            return web.json_response({
                "message": "Canary started",
                "engineInstanceId": instance.id,
                "releaseVersion": unit.release_version or None,
                "canary": controller.to_dict(),
                "warmup": (self._last_warmup.to_dict()
                           if self._last_warmup else None)})
        self._swap_to(unit, mode=mode, reason="deploy")
        return web.json_response({
            "message": "Deployed",
            "engineInstanceId": instance.id,
            "releaseVersion": unit.release_version or None,
            "warmup": (self._last_warmup.to_dict()
                       if self._last_warmup else None)})

    async def _settle_canary_first(self) -> Optional[web.Response]:
        """Swap-initiating endpoints (deploy/reload) must not run over a
        live canary: an undecided rollout is refused with 409 (a swap
        would poison the judge's incumbent baseline), and a decided-but-
        not-yet-acted verdict is acted on NOW so it can never be silently
        overwritten (or resurface after an operator action)."""
        canary = self._canary
        if canary is None:
            return None
        if canary.controller.decided is None:
            return web.json_response(
                {"message": "A canary rollout is already in progress; "
                            "rollback or wait for its verdict first."},
                status=409)
        await self._act_on_verdict(canary, canary.controller.decided)
        return None

    def _canary_config(self, body: dict) -> Optional[CanaryConfig]:
        """A deploy body opts into a staged rollout with canaryFraction
        or shadow; DeployConfig supplies every unspecified knob."""
        if not (body.get("canaryFraction") or body.get("shadow")):
            return None
        dc = self.deploy_config
        return CanaryConfig(
            fraction=float(body.get("canaryFraction",
                                    dc.canary_fraction) or 0.0),
            shadow=bool(body.get("shadow", False)),
            window=int(body.get("canaryWindow", dc.canary_window)),
            min_samples=int(body.get("canaryMinSamples",
                                     dc.canary_min_samples)),
            promote_after=int(body.get("canaryPromoteAfter",
                                       dc.canary_promote_after)),
            p99_ratio=float(body.get("canaryP99Ratio", dc.canary_p99_ratio)),
            latency_slack_s=float(body.get("canaryLatencySlackS",
                                           dc.canary_latency_slack_s)),
            error_rate_slack=float(body.get("canaryErrorRateSlack",
                                            dc.canary_error_rate_slack)),
        )

    async def handle_rollback(self, request):
        """Operator rollback: abort an active canary, else restore the
        resident standby (previous LIVE release) — and as a last resort
        re-load the previous release from the registry."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        canary = self._canary
        if canary is not None:
            if canary.controller.decided is None:
                canary.controller.decided = ("rollback", "operator")
                await self._act_on_verdict(canary, ("rollback", "operator"))
                return web.json_response({
                    "message": "Canary aborted",
                    "engineInstanceId": canary.unit.instance.id})
            # a verdict is queued but unacted: settle it before rolling
            # back, or a pending promote task would silently re-install
            # the release the operator just rolled away from
            decision = canary.controller.decided
            await self._act_on_verdict(canary, decision)
            if decision[0] == "rollback":
                # the SLO guard already did what the operator came to do;
                # demoting the healthy incumbent too would punish a
                # timing race, not a release
                return web.json_response({
                    "message": "Canary aborted",
                    "engineInstanceId": canary.unit.instance.id})
        target = self._standby
        if target is None or target.result is None:
            target = await self._load_previous_release()
        if target is None:
            return web.json_response(
                {"message": "No previous release to roll back to."},
                status=404)
        rolled_back = self._unit
        if target.batcher is None:
            self._attach_batcher(target)
        self._deploy.rollback_total.inc(reason="operator")
        self._swap_to(target, mode="rollback", reason="operator rollback",
                      retire_old=False)
        self._set_release_status(rolled_back.release, "ROLLED_BACK",
                                 "operator rollback")
        self._standby = None      # never flip-flop back onto the bad one
        return web.json_response({
            "message": "Rolled back",
            "engineInstanceId": target.instance.id,
            "releaseVersion": target.release_version or None})

    async def _load_previous_release(self) -> Optional[ServingUnit]:
        """Registry-backed rollback target: the newest RETIRED release
        below the active version (used when no standby is resident —
        e.g. the server restarted since the last swap)."""
        loop = asyncio.get_running_loop()

        def _find():
            try:
                releases = Storage.get_meta_data_releases()
                instances = Storage.get_meta_data_engine_instances()
            except Exception:
                return None, None
            active_v = self._unit.release_version
            for r in releases.get_for_variant(
                    self.instance.engine_id, self.instance.engine_version,
                    self.instance.engine_variant):
                if active_v and r.version >= active_v:
                    continue
                if r.status not in ("RETIRED", "LIVE"):
                    continue
                inst = instances.get(r.instance_id)
                if inst is not None and inst.status == "COMPLETED":
                    return inst, r
            return None, None

        instance, release = await loop.run_in_executor(None, _find)
        if instance is None:
            return None
        try:
            return await self._prepare_unit(instance, release)
        except DeployError:
            logger.exception("previous release failed to prepare")
            return None

    async def handle_releases(self, request):
        """Release manifests for this engine variant, newest first."""
        loop = asyncio.get_running_loop()

        def _list():
            try:
                releases = Storage.get_meta_data_releases()
                return [release_to_json(r) for r in releases.get_for_variant(
                    self.instance.engine_id, self.instance.engine_version,
                    self.instance.engine_variant)]
            except Exception:
                logger.exception("release listing failed")
                return []

        listing = await loop.run_in_executor(None, _list)
        return web.json_response({
            "releases": listing,
            "serving": {
                "engineInstanceId": self.instance.id,
                "releaseVersion": self._unit.release_version or None,
            }})

    async def handle_deploy_status(self, request):
        canary = self._canary
        return web.json_response({
            "active": {
                "engineInstanceId": self.instance.id,
                "releaseVersion": self._unit.release_version or None,
                "vectorized": self._unit.vectorized,
            },
            "standby": ({
                "engineInstanceId": self._standby.instance.id,
                "releaseVersion": self._standby.release_version or None,
            } if self._standby is not None else None),
            "canary": ({
                "engineInstanceId": canary.unit.instance.id,
                "releaseVersion": canary.unit.release_version or None,
                **canary.controller.to_dict(),
            } if canary is not None else None),
            "lastWarmup": (self._last_warmup.to_dict()
                           if self._last_warmup else None),
            "foldin": (self._foldin.status_dict()
                       if self._foldin is not None
                       else {"enabled": False}),
            "scorer": self._scorer_status(),
            "resident": self.resident,
        })

    def _scorer_status(self) -> dict:
        """Resolved scorer mode + per-unit quantized residency (the
        pio deploy echo's live counterpart, mirroring the ALS-solver
        echo). ``units`` is empty until a unit's first device-scored
        batch builds its scorer — warm-up does that on warmed deploys."""
        from predictionio_tpu.ops import scoring

        return {
            "mode": self.scorer_config.mode,
            "tileItems": self.scorer_config.tile_items,
            "shortlist": self.scorer_config.shortlist,
            "units": scoring.unit_scorer_status(self._unit.result),
        }

    async def handle_stop(self, request):
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        self._stop_event.set()
        asyncio.get_running_loop().call_later(0.2, _raise_shutdown)
        return web.json_response({"message": "Shutting down"})

    async def handle_plugins(self, request):
        return web.json_response({"plugins": self.plugins.describe()})

    # -- SLO + profiling surface (obs/slo.py, obs/profiler.py) ---------------
    async def handle_slo(self, request):
        """The burn-rate engine's current evaluation; a read also ticks
        the engine so a breach is visible within one evaluation window
        even between periodic ticks."""
        if self._slo is None:
            return web.json_response({
                "enabled": False,
                "message": 'no SLO spec configured (server.json "slo")'})
        try:
            status = self._slo.tick()
        except Exception as e:
            logger.exception("SLO evaluation failed")
            return web.json_response({"enabled": True, "error": str(e)},
                                     status=500)
        return web.json_response({
            "enabled": True,
            "release": {
                "engineInstanceId": self.instance.id,
                "releaseVersion": self._unit.release_version or None,
            },
            **status})

    async def handle_profile(self, request):
        """Bounded on-demand device profile (key-auth like the deploy
        API): a jax.profiler capture plus the per-family dispatch-time
        attribution table."""
        from predictionio_tpu.obs import profiler

        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        try:
            body = await request.json() if request.can_read_body else {}
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            seconds = float(body.get("seconds", 1.0) or 1.0)
            outdir = body.get("dir")
        except (json.JSONDecodeError, TypeError, ValueError) as e:
            return web.json_response({"message": str(e)}, status=400)
        loop = asyncio.get_running_loop()
        try:
            # the capture sleeps for its whole window: run it on the
            # deploy lane so neither the event loop nor a predict slot
            # blocks for the duration
            out = await loop.run_in_executor(
                self._deploy_executor, profiler.capture, seconds, outdir)
        except profiler.ProfileBusy as e:
            return web.json_response({"message": str(e)}, status=409)
        except RuntimeError as e:
            return web.json_response({"message": str(e)}, status=501)
        record_event("profile_capture", {"seconds": out["seconds"],
                                         "traceDir": out["traceDir"]})
        return web.json_response(out)


def _raise_shutdown():
    raise web.GracefulExit()


def _log_retire_failure(fut) -> None:
    """Done-callback for the fold-in swap's cross-thread batcher drain:
    surface failures instead of letting the future swallow them."""
    try:
        fut.result()
    except Exception:
        logger.exception("fold-in batcher retirement failed")


def create_query_server(engine: Engine, train_result: TrainResult,
                        instance: EngineInstance, ctx,
                        **kwargs) -> QueryServer:
    return QueryServer(engine, train_result, instance, ctx, **kwargs)


def run_query_server(engine: Engine, train_result: TrainResult,
                     instance: EngineInstance, ctx,
                     ip: str = "localhost", port: int = DEFAULT_PORT,
                     **kwargs) -> None:
    from predictionio_tpu.utils.server_config import ServerConfig

    cfg = ServerConfig.load()
    # server.conf key guards /stop, /reload and the deploy endpoints when
    # no explicit key given (CreateServer + KeyAuthentication.scala:33-62)
    kwargs.setdefault("access_key", cfg.key or None)
    # micro-batch tuning from server.json "serving" + PIO_BATCH_* env
    kwargs.setdefault("serving_config", cfg.serving)
    # warm-swap/canary tuning from server.json "deploy" + PIO_CANARY_* env
    kwargs.setdefault("deploy_config", cfg.deploy)
    # online fold-in knobs from server.json "foldin" + PIO_FOLDIN_* env
    # (pio deploy passes an engine.json-aware config explicitly)
    kwargs.setdefault("foldin_config", cfg.foldin)
    # scoring-kernel knobs from server.json "scorer" + PIO_SCORER_* env
    # (pio deploy passes an engine.json-aware config explicitly)
    kwargs.setdefault("scorer_config", cfg.scorer)
    # per-release SLO objectives from server.json "slo" (PIO_SLO=0 off)
    from predictionio_tpu.obs.slo import slo_spec_from_server_json

    kwargs.setdefault("slo_spec", slo_spec_from_server_json())
    # durable telemetry: scrape loop + history surface + SLO rehydration
    # (env > engine.json "telemetry" > server.json; PIO_TELEMETRY=0 off;
    # pio deploy passes the engine.json-aware config explicitly)
    tcfg = kwargs.pop("telemetry_config", None) or cfg.telemetry
    if "telemetry" not in kwargs:
        from predictionio_tpu.obs.telemetry import build_recorder

        registry = kwargs.setdefault("registry", MetricsRegistry())
        kwargs["telemetry"] = build_recorder(
            "query_server", tcfg, instance=str(port),
            registries=[registry, default_registry()])
    server = create_query_server(engine, train_result, instance, ctx, **kwargs)
    ssl_ctx = cfg.ssl_context()
    logger.info("Query server listening on %s:%s%s", ip, port,
                " (TLS)" if ssl_ctx else "")
    web.run_app(server.app, host=ip, port=port,
                ssl_context=ssl_ctx, print=None)
