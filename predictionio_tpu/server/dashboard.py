"""Fleet console — port 9000.

Grown from the reference's evaluation dashboard
(tools/.../dashboard/Dashboard.scala:45-162 — an HTML index of completed
EvaluationInstances, still served here route-for-route) into the
operator's live console over the durable-telemetry plane:

  GET /                      -> the console: releases + lineage, SLO burn
                                tables with sparkline history, the
                                orchestrator cycle timeline, top device
                                dispatch families, recent traces and
                                lifecycle events, completed evaluations
  GET /history/series.json   -> persisted series inventory (fleet-wide)
  GET /history/range.json    -> raw samples / rate() / quantile-over-time
  GET /engine_instances/<id> -> evaluation detail (reference parity)
  GET /evaluations.json, /evaluations/<id>.json -> JSON parity endpoints

Everything longitudinal renders from the merged per-process telemetry
stores (obs/fleet.history_reader over the telemetry root) — no script
tags, no external assets: sparklines are unicode blocks, so the console
works over curl and in an airgap. Optional key auth + TLS come from the
server config; the metrics/history endpoints stay unauthenticated like
every other server's.
"""

from __future__ import annotations

import html
import json
import logging
import os
import time
from typing import List, Optional

from aiohttp import web

from predictionio_tpu.obs.capacity import (
    CAPACITY_PATH, add_capacity_route, register_capacity_metrics,
)
from predictionio_tpu.obs.middleware import (
    METRICS_PATHS, add_metrics_routes, observability_middleware,
)
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.telemetry import (
    HISTORY_PATHS, add_history_routes, history_reader_factory,
)
from predictionio_tpu.obs.trace_context import recorder
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.server_config import ServerConfig

logger = logging.getLogger("pio.dashboard")

DEFAULT_PORT = 9000

_SERVER_CONFIG = web.AppKey("server_config", ServerConfig)
_READER_FACTORY = web.AppKey("history_reader_factory", object)
_ORCH_STATE_DIR = web.AppKey("orch_state_dir", str)

#: unicode sparkline ramp (8 levels)
_SPARK = "▁▂▃▄▅▆▇█"


@web.middleware
async def _key_auth_middleware(request, handler):
    if request.path in METRICS_PATHS or request.path in HISTORY_PATHS \
            or request.path == CAPACITY_PATH:
        return await handler(request)   # scrapers hold no access keys
    cfg = request.app[_SERVER_CONFIG]
    if not cfg.check_key(request.query.get("accessKey")):
        return web.json_response({"message": "Unauthorized"}, status=401)
    return await handler(request)


def sparkline(values: List[float], width: int = 32) -> str:
    """Server-rendered history: the last ``width`` values as unicode
    blocks, scaled to their own max (flat-zero renders as floor)."""
    values = [float(v) for v in values][-width:]
    if not values:
        return ""
    top = max(values)
    if top <= 0:
        return _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int(v / top * (len(_SPARK) - 1)))]
        for v in values)


def _series_sparkline(info, rate: bool = False) -> str:
    """A SeriesInfo's values as a sparkline; cumulative kinds (and all
    histograms, via their total count) plot per-interval increases."""
    if info.kind == "histogram":
        values = [sum(p[1]) for p in info.points]
        rate = True
    else:
        values = [p[1] for p in info.points]
    if rate and len(values) >= 2:
        values = [max(0.0, b - a) for a, b in zip(values, values[1:])]
    return sparkline(values)


def _esc(value) -> str:
    return html.escape(str(value))


# ---------------------------------------------------------------------------
# console sections (each degrades to an honest "no data" row)
# ---------------------------------------------------------------------------

def _section(title: str, body: str) -> str:
    return f"<h2>{_esc(title)}</h2>\n{body}\n"


def _table(headers: List[str], rows: List[List[str]],
           empty: str = "no data") -> str:
    if not rows:
        return f"<p><em>{_esc(empty)}</em></p>"
    head = "".join(f"<th>{h}</th>" for h in headers)
    body = "".join("<tr>" + "".join(f"<td>{c}</td>" for c in row) + "</tr>"
                   for row in rows)
    return f"<table border=1 cellpadding=4><tr>{head}</tr>{body}</table>"


def _releases_rows() -> List[List[str]]:
    try:
        releases = Storage.get_meta_data_releases().get_all()
    except Exception:
        return []
    rows = []
    for r in sorted(releases, key=lambda r: (r.engine_id,
                                             r.engine_variant, -r.version)):
        lineage = " → ".join(h.get("status", "?") for h in r.history) \
            or r.status
        rows.append([
            _esc(f"{r.engine_id.rsplit('.', 1)[-1]}/{r.engine_variant}"),
            f"v{r.version}",
            f"<b>{_esc(r.status)}</b>",
            _esc(r.instance_id),
            _esc(r.created_time.strftime("%Y-%m-%d %H:%M:%S")),
            _esc(lineage)])
    return rows


def _slo_rows(reader, since_ms: int) -> List[List[str]]:
    rows = []
    breached = {}
    for info in reader.series("pio_slo_breached", since_ms=since_ms):
        key = (info.labels.get("process", ""),
               info.labels.get("objective", ""))
        breached[key] = info.points[-1][1] if info.points else 0.0
    for info in reader.series("pio_slo_burn_rate", since_ms=since_ms):
        if not info.points:
            continue
        process = info.labels.get("process", "")
        objective = info.labels.get("objective", "")
        state = "BREACHED" if breached.get((process, objective)) else "ok"
        rows.append([
            _esc(process), _esc(objective),
            _esc(info.labels.get("window", "")),
            f"{info.points[-1][1]:.2f}",
            f"<b>{state}</b>" if state == "BREACHED" else state,
            f"<code>{_series_sparkline(info)}</code>"])
    return rows


def _cycle_rows(state_dir: Optional[str], limit: int = 12
                ) -> List[List[str]]:
    """The orchestrator cycle timeline from its crash-safe history dir
    (deploy/orchestrator.CycleStore archives one JSON per cycle)."""
    if not state_dir:
        return []
    history = os.path.join(state_dir, "history")
    try:
        names = sorted(os.listdir(history))
    except OSError:
        return []
    docs = []
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(history, name)) as f:
                docs.append(json.load(f))
        except (OSError, ValueError):
            continue
    docs.sort(key=lambda d: d.get("started_ms", 0), reverse=True)
    rows = []
    for d in docs[:limit]:
        started = time.strftime(
            "%Y-%m-%d %H:%M:%S",
            time.localtime(d.get("started_ms", 0) / 1000.0))
        wall = (d.get("updated_ms", 0) - d.get("started_ms", 0)) / 1000.0
        outcome = d.get("outcome", "?")
        mark = f"<b>{_esc(outcome)}</b>" if outcome != "promoted" \
            else _esc(outcome)
        rows.append([
            _esc(d.get("cycle_id", "?")), _esc(d.get("trigger", "?")),
            _esc(started), f"{wall:.1f}s", _esc(d.get("phase", "")),
            mark,
            _esc((f"v{d['candidate_release_version']}"
                  if d.get("candidate_release_version") else "-")),
            _esc((d.get("reason") or "")[:80])])
    return rows


def _dispatch_rows(reader, since_ms: int, top: int = 10
                   ) -> List[List[str]]:
    rates = reader.rate("pio_device_dispatch_seconds_total",
                        since_ms=since_ms)
    rates.sort(key=lambda r: -r["increase"])
    return [[_esc(r["labels"].get("family", "?")),
             _esc(r["labels"].get("process", "")),
             f"{r['increase']:.3f}s",
             f"{100.0 * r['rate']:.2f}%"]
            for r in rates[:top]]


def _trace_rows(reader, since_ms: int, limit: int = 12) -> List[List[str]]:
    local = recorder().traces(limit=limit)
    persisted = [t for _ts, t in reader.traces(since_ms=since_ms)]
    seen, rows = set(), []
    for t in (persisted + local)[-4 * limit:]:
        key = (t.get("traceId"), t.get("spanId"))
        if key in seen:
            continue
        seen.add(key)
        rows.append(t)
    rows.sort(key=lambda t: t.get("ts", 0), reverse=True)
    return [[_esc((t.get("traceId") or "?")[:12]),
             _esc(t.get("name", "?")),
             f"{1e3 * t.get('durationSec', 0.0):.1f}ms",
             _esc(t.get("status", "?")),
             _esc(t.get("process", ""))]
            for t in rows[:limit]]


def _event_rows(reader, since_ms: int, limit: int = 12) -> List[List[str]]:
    local = recorder().events(limit=limit)
    persisted = [e for _ts, e in reader.events(since_ms=since_ms)]
    seen, rows = set(), []
    for e in persisted + local:
        key = (e.get("ts"), e.get("kind"), e.get("traceId"))
        if key in seen:
            continue
        seen.add(key)
        rows.append(e)
    rows.sort(key=lambda e: e.get("ts", 0), reverse=True)
    out = []
    for e in rows[:limit]:
        detail = {k: v for k, v in e.items()
                  if k not in ("kind", "ts", "traceId", "process")}
        out.append([
            _esc(time.strftime("%H:%M:%S",
                               time.localtime(e.get("ts", 0)))),
            _esc(e.get("kind", "?")),
            _esc((e.get("traceId") or "-")[:12]),
            _esc(e.get("process", "")),
            _esc(json.dumps(detail, sort_keys=True)[:100])])
    return out


def _serving_rows(reader, since_ms: int) -> List[List[str]]:
    rows = []
    for info in reader.series("pio_query_duration_seconds",
                              since_ms=since_ms):
        if info.kind != "histogram" or not info.points:
            continue
        rows.append([
            _esc(info.labels.get("process", "")),
            _esc(info.labels.get("engine_variant", "")),
            f"{sum(info.points[-1][1]):.0f}",
            f"<code>{_series_sparkline(info)}</code>"])
    if rows:
        q99 = reader.quantile_over_time("pio_query_duration_seconds",
                                        0.99, since_ms=since_ms)
        rows[0].append(f"{1e3 * q99:.1f}ms" if q99 is not None else "")
        for row in rows[1:]:
            row.append("")
    return rows


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.0f}B" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TiB"


def _capacity_rows(reader, since_ms: int) -> List[List[str]]:
    """Per-process capacity ledger: live device bytes (+history), the
    process watermark, host RSS, and the per-role unit residency."""
    last, spark = {}, {}
    for name in ("pio_capacity_device_bytes",
                 "pio_capacity_device_watermark_bytes",
                 "pio_capacity_host_rss_bytes"):
        for info in reader.series(name, since_ms=since_ms):
            if not info.points:
                continue
            proc = info.labels.get("process", "")
            last[(proc, name)] = info.points[-1][1]
            if name == "pio_capacity_device_bytes":
                spark[proc] = sparkline([p[1] for p in info.points])
    units: dict = {}
    for info in reader.series("pio_capacity_unit_resident_bytes",
                              since_ms=since_ms):
        if not info.points:
            continue
        proc = info.labels.get("process", "")
        units.setdefault(proc, []).append(
            f"{info.labels.get('role', '?')}="
            f"{_fmt_bytes(info.points[-1][1])}")
    rows = []
    for proc in sorted({p for p, _n in last}):
        rows.append([
            _esc(proc),
            _fmt_bytes(last.get((proc, "pio_capacity_device_bytes"), 0.0)),
            _fmt_bytes(last.get(
                (proc, "pio_capacity_device_watermark_bytes"), 0.0)),
            _fmt_bytes(last.get((proc, "pio_capacity_host_rss_bytes"),
                                0.0)),
            _esc(", ".join(sorted(units.get(proc, []))) or "-"),
            f"<code>{spark.get(proc, '')}</code>"])
    return rows


def _evaluation_rows() -> List[List[str]]:
    try:
        instances = \
            Storage.get_meta_data_evaluation_instances().get_completed()
    except Exception:
        return []
    return [[
        f"<a href='/engine_instances/{_esc(i.id)}'>{_esc(i.id)}</a>",
        _esc(i.evaluation_class),
        _esc(i.start_time.isoformat()),
        _esc(i.end_time.isoformat()),
        _esc(i.evaluator_results)] for i in instances]


def render_console(reader, orch_state_dir: Optional[str],
                   window_s: float = 3600.0) -> str:
    since_ms = int((time.time() - window_s) * 1000)
    sections = [
        _section("Releases", _table(
            ["engine/variant", "version", "status", "instance", "created",
             "lineage"], _releases_rows(),
            empty="no releases registered")),
        _section("SLO burn (trailing hour)", _table(
            ["process", "objective", "window", "burn now", "state",
             "history"], _slo_rows(reader, since_ms),
            empty="no persisted SLO history — is telemetry enabled on "
                  "the query server?")),
        _section("Serving (trailing hour)", _table(
            ["process", "variant", "queries", "throughput history",
             "p99 over window"], _serving_rows(reader, since_ms),
            empty="no persisted serving history")),
        _section("Capacity ledger (trailing hour)", _table(
            ["process", "device bytes", "watermark", "host RSS",
             "unit residency", "device history"],
            _capacity_rows(reader, since_ms),
            empty="no persisted capacity history — /capacity.json "
                  "answers live per process")),
        _section("Orchestrator cycles", _table(
            ["cycle", "trigger", "started", "wall", "last phase",
             "outcome", "release", "reason"],
            _cycle_rows(orch_state_dir),
            empty="no archived cycles (pio orchestrate writes them)")),
        _section("Top dispatch families (trailing hour)", _table(
            ["family", "process", "device seconds", "duty"],
            _dispatch_rows(reader, since_ms),
            empty="no dispatch attribution persisted")),
        _section("Recent traces", _table(
            ["trace", "name", "wall", "status", "process"],
            _trace_rows(reader, since_ms), empty="no traces recorded")),
        _section("Lifecycle events", _table(
            ["at", "kind", "trace", "process", "detail"],
            _event_rows(reader, since_ms), empty="no lifecycle events")),
        _section("Completed evaluations", _table(
            ["ID", "Evaluation", "Started", "Finished", "Result"],
            _evaluation_rows(), empty="no completed evaluations")),
    ]
    return (
        "<html><head><title>predictionio_tpu fleet console</title>"
        "<style>body{font-family:monospace;margin:24px}"
        "table{border-collapse:collapse;margin-bottom:12px}"
        "td,th{text-align:left}code{font-size:14px}</style></head><body>"
        "<h1>predictionio_tpu fleet console</h1>"
        "<p>JSON: <a href='/history/series.json'>/history/series.json</a>"
        " · /history/range.json?name=&lt;metric&gt;&amp;sinceS=3600"
        "[&amp;rate=1|&amp;quantile=0.99] · "
        "<a href='/metrics'>/metrics</a> · "
        "<a href='/debug/traces.json'>/debug/traces.json</a></p>"
        + "".join(sections) + "</body></html>")


# ---------------------------------------------------------------------------
# handlers
# ---------------------------------------------------------------------------

async def handle_index(request):
    import asyncio

    # the render reads (and CRC-checks) every telemetry segment plus
    # storage tables — synchronous by nature, so it runs off the event
    # loop; a slow console page must never stall concurrent requests
    reader = request.app[_READER_FACTORY]()
    page = await asyncio.get_running_loop().run_in_executor(
        None, render_console, reader, request.app.get(_ORCH_STATE_DIR))
    return web.Response(text=page, content_type="text/html")


async def handle_detail(request):
    instance_id = request.match_info["instance_id"]
    instance = Storage.get_meta_data_evaluation_instances().get(instance_id)
    if instance is None:
        raise web.HTTPNotFound(text="evaluation instance not found")
    body = instance.evaluator_results_html or (
        f"<html><body><pre>{html.escape(instance.evaluator_results)}</pre>"
        "</body></html>")
    return web.Response(text=body, content_type="text/html")


async def handle_index_json(request):
    instances = Storage.get_meta_data_evaluation_instances().get_completed()
    return web.json_response([{
        "id": i.id,
        "evaluationClass": i.evaluation_class,
        "startTime": i.start_time.isoformat(),
        "endTime": i.end_time.isoformat(),
        "result": i.evaluator_results,
    } for i in instances])


async def handle_detail_json(request):
    instance_id = request.match_info["instance_id"]
    instance = Storage.get_meta_data_evaluation_instances().get(instance_id)
    if instance is None:
        return web.json_response({"message": "Not Found"}, status=404)
    return web.json_response({
        "id": instance.id,
        "evaluationClass": instance.evaluation_class,
        "result": instance.evaluator_results,
        "resultJSON": instance.evaluator_results_json,
    })


def create_dashboard(server_config: Optional[ServerConfig] = None,
                     registry: Optional[MetricsRegistry] = None,
                     telemetry=None,
                     history_root: Optional[str] = None,
                     orch_state_dir: Optional[str] = None
                     ) -> web.Application:
    registry = registry or MetricsRegistry()
    app = web.Application(middlewares=[
        observability_middleware(registry, "dashboard"),
        _key_auth_middleware])
    app[_SERVER_CONFIG] = server_config or ServerConfig()
    app[_READER_FACTORY] = history_reader_factory(telemetry,
                                                  root=history_root)
    if orch_state_dir:
        app[_ORCH_STATE_DIR] = orch_state_dir
    app.router.add_get("/", handle_index)
    app.router.add_get("/engine_instances/{instance_id}", handle_detail)
    app.router.add_get("/evaluations.json", handle_index_json)
    app.router.add_get("/evaluations/{instance_id}.json", handle_detail_json)
    register_capacity_metrics(registry)
    add_capacity_route(app)
    add_metrics_routes(app, registry, default_registry())
    add_history_routes(app, app[_READER_FACTORY])
    if telemetry is not None:
        async def _stop_telemetry(app):
            import asyncio

            await asyncio.get_running_loop().run_in_executor(
                None, telemetry.stop)
        app.on_shutdown.append(_stop_telemetry)
    return app


def run_dashboard(ip: str = "localhost", port: int = DEFAULT_PORT,
                  server_config: Optional[ServerConfig] = None) -> None:
    from predictionio_tpu.deploy.orchestrator import default_state_dir
    from predictionio_tpu.obs.telemetry import build_recorder

    cfg = server_config or ServerConfig.load()
    registry = MetricsRegistry()
    telemetry = build_recorder("dashboard", cfg.telemetry,
                               instance=str(port),
                               registries=[registry, default_registry()])
    ssl_ctx = cfg.ssl_context()
    logger.info("Fleet console listening on %s:%s%s", ip, port,
                " (TLS)" if ssl_ctx else "")
    web.run_app(
        create_dashboard(cfg, registry, telemetry=telemetry,
                         history_root=cfg.telemetry.root_dir(),
                         orch_state_dir=(cfg.orchestrator.state_dir
                                         or default_state_dir())),
        host=ip, port=port, ssl_context=ssl_ctx, print=None)
