"""Evaluation dashboard — port 9000.

Parity with the reference Dashboard (tools/.../dashboard/Dashboard.scala:45-162):
an HTML index of completed EvaluationInstances (newest first) with per-instance
detail pages rendering the stored evaluator HTML, plus JSON endpoints for
programmatic access. Optional key auth + TLS come from the server config
(the reference's with-key-auth SSL dashboard, Dashboard.scala:65+ /
KeyAuthentication.scala:33-62).
"""

from __future__ import annotations

import html
import logging
from typing import Optional

from aiohttp import web

from predictionio_tpu.obs.middleware import (
    METRICS_PATHS, add_metrics_routes, observability_middleware,
)
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.server_config import ServerConfig

logger = logging.getLogger("pio.dashboard")

DEFAULT_PORT = 9000

_SERVER_CONFIG = web.AppKey("server_config", ServerConfig)


@web.middleware
async def _key_auth_middleware(request, handler):
    if request.path in METRICS_PATHS:  # scrapers hold no access keys
        return await handler(request)
    cfg = request.app[_SERVER_CONFIG]
    if not cfg.check_key(request.query.get("accessKey")):
        return web.json_response({"message": "Unauthorized"}, status=401)
    return await handler(request)


def _index_html(instances) -> str:
    rows = "".join(
        f"<tr><td><a href='/engine_instances/{html.escape(i.id)}'>"
        f"{html.escape(i.id)}</a></td>"
        f"<td>{html.escape(i.evaluation_class)}</td>"
        f"<td>{i.start_time.isoformat()}</td>"
        f"<td>{i.end_time.isoformat()}</td>"
        f"<td>{html.escape(i.evaluator_results)}</td></tr>"
        for i in instances)
    return (
        "<html><head><title>predictionio_tpu dashboard</title></head><body>"
        "<h1>Completed evaluations</h1>"
        "<table border=1><tr><th>ID</th><th>Evaluation</th><th>Started</th>"
        f"<th>Finished</th><th>Result</th></tr>{rows}</table></body></html>")


async def handle_index(request):
    instances = Storage.get_meta_data_evaluation_instances().get_completed()
    return web.Response(text=_index_html(instances), content_type="text/html")


async def handle_detail(request):
    instance_id = request.match_info["instance_id"]
    instance = Storage.get_meta_data_evaluation_instances().get(instance_id)
    if instance is None:
        raise web.HTTPNotFound(text="evaluation instance not found")
    body = instance.evaluator_results_html or (
        f"<html><body><pre>{html.escape(instance.evaluator_results)}</pre>"
        "</body></html>")
    return web.Response(text=body, content_type="text/html")


async def handle_index_json(request):
    instances = Storage.get_meta_data_evaluation_instances().get_completed()
    return web.json_response([{
        "id": i.id,
        "evaluationClass": i.evaluation_class,
        "startTime": i.start_time.isoformat(),
        "endTime": i.end_time.isoformat(),
        "result": i.evaluator_results,
    } for i in instances])


async def handle_detail_json(request):
    instance_id = request.match_info["instance_id"]
    instance = Storage.get_meta_data_evaluation_instances().get(instance_id)
    if instance is None:
        return web.json_response({"message": "Not Found"}, status=404)
    return web.json_response({
        "id": instance.id,
        "evaluationClass": instance.evaluation_class,
        "result": instance.evaluator_results,
        "resultJSON": instance.evaluator_results_json,
    })


def create_dashboard(server_config: Optional[ServerConfig] = None,
                     registry: Optional[MetricsRegistry] = None
                     ) -> web.Application:
    registry = registry or MetricsRegistry()
    app = web.Application(middlewares=[
        observability_middleware(registry, "dashboard"),
        _key_auth_middleware])
    app[_SERVER_CONFIG] = server_config or ServerConfig()
    app.router.add_get("/", handle_index)
    app.router.add_get("/engine_instances/{instance_id}", handle_detail)
    app.router.add_get("/evaluations.json", handle_index_json)
    app.router.add_get("/evaluations/{instance_id}.json", handle_detail_json)
    add_metrics_routes(app, registry, default_registry())
    return app


def run_dashboard(ip: str = "localhost", port: int = DEFAULT_PORT,
                  server_config: Optional[ServerConfig] = None) -> None:
    cfg = server_config or ServerConfig.load()
    ssl_ctx = cfg.ssl_context()
    logger.info("Dashboard listening on %s:%s%s", ip, port,
                " (TLS)" if ssl_ctx else "")
    web.run_app(create_dashboard(cfg), host=ip, port=port,
                ssl_context=ssl_ctx, print=None)
