"""Multi-tenant serving host: N ServingUnits, one process, one budget.

ROADMAP item 1's scaling ceiling: production means tens of engine
variants (six engine families x apps x canaries) sharing device memory,
and a process+JAX-runtime per variant wastes the scarcest resource the
TPU-native rebuild has — HBM-resident factors. This module hosts N
full :class:`~predictionio_tpu.server.query_server.QueryServer`\\ s
behind per-tenant routes (``POST /t/{tenant}/queries.json``) in ONE
process, under ONE device-memory budget:

* **residency budgeter** — attributes bytes per tenant from the
  capacity ledger (``obs/capacity.py``, the PR 14 scorer
  ``factorBytes`` roll-up), evicts the least-recently-queried tenant
  to warm on-host state (params + registry release pointer retained,
  factors dropped) when the budget is exceeded, and reloads through
  the existing ``warmup_unit`` ladder on the next hit;
* **per-tenant scorer residency** — each tenant's QueryServer is
  built with ``pin_process_scorer=False`` and stamps ITS resolved
  :class:`ScorerConfig` onto its model holders
  (``ops/scoring.holder_scorer_config``), so tenant A holds int8
  factors (3.8x under f32) while tenant B holds bf16 in the same
  process — the eviction-avoidance lever;
* **per-tenant isolation** — every tenant keeps its OWN MicroBatcher,
  fold-in/canary controllers and release lineage (the registry already
  keys on engineId/engineVersion/engineVariant), plus tenant-labelled
  metrics and an SLO burn-rate engine;
* **admission control** — a tenant whose SLO budget is burning is
  429'd (with Retry-After) at the host gate, so one noisy tenant
  cannot evict or queue-starve the rest.

Knobs: ``PIO_MT_*`` / server.json ``multitenant``
(:class:`~predictionio_tpu.utils.server_config.MultiTenantConfig`).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from aiohttp import web

from predictionio_tpu.obs.capacity import (
    add_capacity_route, register_capacity_metrics,
)
from predictionio_tpu.obs.middleware import (
    add_metrics_routes, observability_middleware,
)
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.slo import (
    KIND_ERRORS, KIND_FRESHNESS, KIND_LATENCY, SLOEngine, SLOSpec,
)
from predictionio_tpu.server.query_server import QueryServer
from predictionio_tpu.utils.server_config import MultiTenantConfig

logger = logging.getLogger("pio.server.multitenant")

DEFAULT_PORT = 8800

#: tenant names become URL path segments and metric label values — keep
#: them boring (no '/', no label-breaking characters)
_TENANT_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")


@dataclasses.dataclass
class TenantSpec:
    """Everything needed to co-host one engine variant as a tenant.

    ``scorer_config`` is the per-tenant residency choice (int8 keeps
    ~3.8x more tenants resident than f32 before the budgeter has to
    evict); ``slo`` is a raw server.json-style ``"slo"`` section whose
    objective names get tenant-prefixed so N tenants share one
    registry's ``pio_slo_*`` gauges without colliding.
    """

    name: str
    engine: Any
    train_result: Any
    instance: Any
    ctx: Any
    release: Any = None
    scorer_config: Any = None
    serving_config: Any = None
    deploy_config: Any = None
    foldin_config: Any = None
    slo: Optional[dict] = None


class Tenant:
    """One co-hosted tenant: its QueryServer plus the host-side state
    the budgeter and admission gate need (LRU clock, SLO engine)."""

    __slots__ = ("name", "server", "slo", "last_hit")

    def __init__(self, name: str, server: QueryServer,
                 slo: Optional[SLOEngine]):
        self.name = name
        self.server = server
        self.slo = slo
        self.last_hit = time.monotonic()


class MultiTenantServer:
    """One process, N tenants, one device-memory budget."""

    def __init__(self, specs: List[TenantSpec],
                 config: Optional[MultiTenantConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 access_key: Optional[str] = None,
                 telemetry=None):
        if not specs:
            raise ValueError("multi-tenant host needs at least one tenant")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {sorted(names)}")
        for name in names:
            if not _TENANT_NAME_RE.match(name):
                raise ValueError(
                    f"tenant name {name!r} is not URL/label safe "
                    f"(want {_TENANT_NAME_RE.pattern})")
        self.config = config or MultiTenantConfig.from_env()
        self.registry = registry or MetricsRegistry()
        self.access_key = access_key
        self._telemetry = telemetry
        cap = self.config.max_tenant_series
        self._queries = self.registry.counter(
            "pio_tenant_queries_total",
            "Queries admitted per tenant at the multi-tenant gate",
            labelnames=("tenant",), max_series=cap)
        self._failures = self.registry.counter(
            "pio_tenant_query_failures_total",
            "Admitted queries that answered >= 400 per tenant (the "
            "errors-SLO burn numerator)",
            labelnames=("tenant",), max_series=cap)
        self._hist = self.registry.histogram(
            "pio_tenant_query_duration_seconds",
            "Gate-to-answer wall time per tenant (the latency-SLO "
            "burn source)",
            labelnames=("tenant",), max_series=cap)
        self._rejected = self.registry.counter(
            "pio_tenant_admission_rejected_total",
            "Queries 429'd at the gate because the tenant's SLO "
            "budget is burning (NOT counted as tenant failures — "
            "shedding must let the burn recover)",
            labelnames=("tenant",), max_series=cap)
        self._reload_timeouts = self.registry.counter(
            "pio_tenant_reload_timeouts_total",
            "Queries that hit a warm tenant and timed out waiting for "
            "the warm reload (answered 503)",
            labelnames=("tenant",), max_series=cap)
        self.registry.gauge(
            "pio_mt_device_budget_bytes",
            "Configured device-memory residency budget "
            "(0 = unlimited, never evict)").set(
                float(self.config.budget_bytes))
        #: construction order = route order; dict preserves it
        self.tenants: Dict[str, Tenant] = {}
        for spec in specs:
            self.tenants[spec.name] = self._build_tenant(spec)
        # each tenant's QueryServer re-pointed the shared registry's
        # per-unit residency gauge at ITS OWN units; the host owns the
        # truth — every tenant's units, tenant-labelled
        register_capacity_metrics(self.registry, self._all_capacity_units)
        self.registry.gauge_callback(
            "pio_tenant_resident_bytes",
            "Device-resident factor bytes per tenant (0 while evicted "
            "to warm state)",
            self._resident_samples, labelnames=("tenant",))
        self.registry.gauge_callback(
            "pio_mt_resident_bytes_total",
            "Device-resident factor bytes across all tenants (the "
            "number the budgeter keeps under pio_mt_device_budget_bytes)",
            lambda: float(self.resident_bytes()))
        self._sweep_task: Optional[asyncio.Task] = None
        self._slo_task: Optional[asyncio.Task] = None
        self.app = web.Application(middlewares=[
            observability_middleware(self.registry, "multitenant")])
        self._routes()
        self.app.on_startup.append(self._on_startup)
        self.app.on_cleanup.append(self._on_cleanup)

    # -- construction --------------------------------------------------------
    def _build_tenant(self, spec: TenantSpec) -> Tenant:
        kwargs: Dict[str, Any] = {}
        for key in ("release", "scorer_config", "serving_config",
                    "deploy_config", "foldin_config"):
            value = getattr(spec, key)
            if value is not None:
                kwargs[key] = value
        server = QueryServer(
            spec.engine, spec.train_result, spec.instance, spec.ctx,
            access_key=self.access_key, registry=self.registry,
            pin_process_scorer=False, **kwargs)
        slo = self._build_slo(spec)
        return Tenant(spec.name, server, slo)

    def _build_slo(self, spec: TenantSpec) -> Optional[SLOEngine]:
        """A per-tenant burn-rate engine over the HOST's tenant-labelled
        metrics. Objective names get a ``{tenant}:`` prefix — all N
        engines share one registry, and ``pio_slo_*`` label by
        objective name."""
        if not spec.slo:
            return None
        data = dict(spec.slo)
        data["objectives"] = [
            {**o, "name": f"{spec.name}:{o.get('name', o.get('kind', 'slo'))}"}
            for o in data.get("objectives", ())]
        parsed = SLOSpec.from_dict(data)
        if parsed is None:
            return None
        name = spec.name

        def _errors(obj) -> Tuple[float, float]:
            return (self._failures.value(tenant=name),
                    self._queries.value(tenant=name))

        def _latency(obj) -> Tuple[float, float]:
            total = self._hist.count(tenant=name)
            bad = total - self._hist.count_below(obj.threshold_s,
                                                 tenant=name)
            return bad, total

        return SLOEngine(self.registry, parsed,
                         sources={KIND_ERRORS: _errors,
                                  KIND_LATENCY: _latency})

    def _routes(self) -> None:
        r = self.app.router
        r.add_get("/", self.handle_root)
        r.add_get("/tenants.json", self.handle_tenants)
        r.add_get("/residency.json", self.handle_residency)
        # the gate needs EXACT per-tenant resources: the router's index
        # walk tries the longest matching path first, so a plain
        # /t/<name>/queries.json outranks the subapp's /t/<name> prefix
        # (a dynamic /t/{tenant} route would index under /t and lose).
        # Queries therefore route through admission + residency while
        # every other per-tenant endpoint (deploy, reload, slo,
        # capacity...) falls through to the tenant's own app
        for name in self.tenants:
            r.add_post(f"/t/{name}/queries.json", self.handle_tenant_query)
        # unknown tenants land on the dynamic fallback for a clean 404
        r.add_post("/t/{tenant}/queries.json", self.handle_tenant_query)
        add_capacity_route(self.app, self._all_capacity_units)
        add_metrics_routes(self.app, self.registry, default_registry())
        for name, tenant in self.tenants.items():
            self.app.add_subapp(f"/t/{name}/", tenant.server.app)

    # -- lifecycle -----------------------------------------------------------
    async def _on_startup(self, app) -> None:
        if self.config.budget_bytes > 0:
            self._sweep_task = asyncio.get_running_loop().create_task(
                self._sweep_loop())
        intervals = [t.slo.spec.eval_interval_s
                     for t in self.tenants.values() if t.slo is not None]
        if intervals:
            self._slo_task = asyncio.get_running_loop().create_task(
                self._slo_loop(min(intervals)))
        logger.info(
            "multi-tenant host up: %d tenant(s) [%s], budget %s bytes, "
            "admission %s", len(self.tenants),
            ", ".join(self.tenants), self.config.budget_bytes or "off",
            "on" if self.config.admission else "off")

    async def _on_cleanup(self, app) -> None:
        for task in (self._sweep_task, self._slo_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        # tenant QueryServer cleanups run via their subapps' signals;
        # the host only owns the shared recorder
        if self._telemetry is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self._telemetry.stop)

    async def _sweep_loop(self) -> None:
        """Background LRU budget sweep: a standby/canary growing a
        tenant past the budget gets corrected within one interval even
        if that tenant is never queried again."""
        while True:
            await asyncio.sleep(self.config.sweep_interval_s)
            try:
                await self.enforce_budget()
            except Exception:
                logger.exception("residency sweep failed")

    async def _slo_loop(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            for tenant in self.tenants.values():
                if tenant.slo is None:
                    continue
                try:
                    tenant.slo.tick()
                except Exception:
                    logger.exception("SLO evaluation failed for tenant %s",
                                     tenant.name)

    # -- residency budgeter --------------------------------------------------
    def _resident_tenants(self) -> List[Tenant]:
        return [t for t in self.tenants.values() if t.server.resident]

    def resident_bytes(self) -> int:
        """Live device-resident attribution across all tenants (warm
        tenants contribute 0 — their remembered footprint only enters
        as the RELOAD projection)."""
        return sum(t.server.warm_bytes for t in self._resident_tenants())

    def _resident_samples(self):
        return [({"tenant": t.name},
                 float(t.server.warm_bytes if t.server.resident else 0))
                for t in self.tenants.values()]

    def _all_capacity_units(self) -> List[dict]:
        units: List[dict] = []
        for tenant in self.tenants.values():
            for unit in tenant.server._capacity_units():
                units.append({**unit, "tenant": tenant.name})
        return units

    async def _evict_lru(self, exclude: Tuple[str, ...] = (),
                         reason: str = "budget") -> bool:
        """Evict the least-recently-queried resident tenant (skipping
        ``exclude`` and tenants mid-canary — the judge needs its
        incumbent baseline). True when something was evicted."""
        candidates = sorted(
            (t for t in self._resident_tenants()
             if t.name not in exclude and t.server._canary is None),
            key=lambda t: t.last_hit)
        for tenant in candidates:
            if await tenant.server.evict_to_warm(reason):
                logger.info("evicted tenant %s (%s)", tenant.name, reason)
                return True
        return False

    async def enforce_budget(self) -> None:
        """Evict LRU tenants until resident bytes fit the budget,
        never below the ``min_resident`` floor."""
        budget = self.config.budget_bytes
        if budget <= 0:
            return
        while (self.resident_bytes() > budget
               and len(self._resident_tenants()) > self.config.min_resident):
            if not await self._evict_lru():
                return

    async def ensure_tenant_resident(self, tenant: Tenant) -> bool:
        """The miss path: make room for the tenant's projected reload
        footprint (its last resident attribution), drive the warm-reload
        ladder, then re-enforce against the ACTUAL bytes (a projection
        is last cycle's truth, not this one's)."""
        budget = self.config.budget_bytes
        if not tenant.server.resident and budget > 0:
            while (self.resident_bytes() + tenant.server.warm_bytes > budget
                   and await self._evict_lru(exclude=(tenant.name,))):
                pass
        ok = await tenant.server.ensure_resident(
            wait_s=self.config.reload_wait_s)
        if ok and budget > 0:
            while (self.resident_bytes() > budget
                   and await self._evict_lru(exclude=(tenant.name,))):
                pass
        return ok

    # -- the gate ------------------------------------------------------------
    async def handle_tenant_query(self, request) -> web.Response:
        # exact per-tenant routes carry no match_info; the path shape
        # is fixed (/t/<name>/queries.json) so the name is segment 2
        name = request.match_info.get("tenant") or request.path.split("/")[2]
        tenant = self.tenants.get(name)
        if tenant is None:
            return web.json_response(
                {"message": f"unknown tenant {name!r}"}, status=404)
        if (self.config.admission and tenant.slo is not None
                and tenant.slo.breached(exclude_kinds=(KIND_FRESHNESS,))):
            self._rejected.inc(tenant=name)
            return web.json_response(
                {"message": f"tenant {name!r} SLO budget is burning; "
                            "shedding load"},
                status=429,
                headers={"Retry-After":
                         f"{self.config.retry_after_s:g}"})
        tenant.last_hit = time.monotonic()
        if not tenant.server.resident:
            if not await self.ensure_tenant_resident(tenant):
                self._reload_timeouts.inc(tenant=name)
                return web.json_response(
                    {"message": f"tenant {name!r} is reloading; retry"},
                    status=503,
                    headers={"Retry-After":
                             f"{self.config.retry_after_s:g}"})
        t0 = time.perf_counter()
        self._queries.inc(tenant=name)
        try:
            response = await tenant.server.handle_query(request)
        except Exception:
            self._failures.inc(tenant=name)
            self._hist.observe(time.perf_counter() - t0, tenant=name)
            raise
        self._hist.observe(time.perf_counter() - t0, tenant=name)
        if response.status >= 400:
            self._failures.inc(tenant=name)
        return response

    # -- status surfaces -----------------------------------------------------
    def _tenant_doc(self, tenant: Tenant) -> dict:
        server = tenant.server
        return {
            "tenant": tenant.name,
            "resident": server.resident,
            "residentBytes": server.warm_bytes if server.resident else 0,
            "warmBytes": 0 if server.resident else server.warm_bytes,
            "lastHitAgoS": round(time.monotonic() - tenant.last_hit, 3),
            "canary": server._canary is not None,
            "slo": (tenant.slo.breached(exclude_kinds=(KIND_FRESHNESS,))
                    if tenant.slo is not None else None),
            "engineInstanceId": server.instance.id,
            "scorerMode": server.scorer_config.mode,
        }

    async def handle_root(self, request) -> web.Response:
        return web.json_response({
            "status": "alive",
            "tenants": list(self.tenants),
            "budgetBytes": self.config.budget_bytes,
            "residentBytes": self.resident_bytes(),
            "admission": self.config.admission,
        })

    async def handle_tenants(self, request) -> web.Response:
        return web.json_response({
            "tenants": [self._tenant_doc(t)
                        for t in self.tenants.values()]})

    async def handle_residency(self, request) -> web.Response:
        resident = self._resident_tenants()
        return web.json_response({
            "budgetBytes": self.config.budget_bytes,
            "residentBytes": self.resident_bytes(),
            "residentTenants": len(resident),
            "minResident": self.config.min_resident,
            "tenants": [self._tenant_doc(t)
                        for t in self.tenants.values()],
        })


def create_multitenant_server(specs: List[TenantSpec],
                              **kwargs) -> MultiTenantServer:
    return MultiTenantServer(specs, **kwargs)


def run_multitenant_server(specs: List[TenantSpec],
                           ip: str = "localhost",
                           port: int = DEFAULT_PORT,
                           **kwargs) -> None:
    from predictionio_tpu.utils.server_config import ServerConfig

    cfg = ServerConfig.load()
    kwargs.setdefault("access_key", cfg.key or None)
    kwargs.setdefault("config", cfg.multitenant)
    if "telemetry" not in kwargs:
        from predictionio_tpu.obs.telemetry import build_recorder

        registry = kwargs.setdefault("registry", MetricsRegistry())
        kwargs["telemetry"] = build_recorder(
            "multitenant", cfg.telemetry, instance=str(port),
            registries=[registry, default_registry()])
    server = create_multitenant_server(specs, **kwargs)
    ssl_ctx = cfg.ssl_context()
    logger.info("Multi-tenant server listening on %s:%s%s (%d tenants)",
                ip, port, " (TLS)" if ssl_ctx else "", len(server.tenants))
    web.run_app(server.app, host=ip, port=port,
                ssl_context=ssl_ctx, print=None)
