"""Event-server ingest statistics.

Parity with the reference's Stats/StatsActor
(data/.../api/Stats.scala:28-80, StatsActor.scala:30-76): per-app counters
keyed by (status, event name, entity type), kept for the current hour and
for the server's lifetime, surfaced at /stats.json.

The lifetime ("longLive") counts are backed by the obs metrics registry
(``pio_event_bookkeeping_total``), so the same numbers appear at
``/metrics`` and ``/stats.json`` without double accounting.  The hourly
window stays a plain dict because Prometheus counters are monotonic and
cannot roll; on a window roll the previous hour is preserved and exposed
as the additive ``prevHourly`` key (the reference silently dropped it).
"""

from __future__ import annotations

import datetime as _dt
import threading
from collections import Counter
from typing import Dict, Optional

from predictionio_tpu.data.event import UTC, Event
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry

#: event/entity-type label values are client-supplied; past this many
#: distinct series new combos collapse into "__other__" so an adversarial
#: key holder cannot grow the (unauthenticated) /metrics exposition
#: without bound
MAX_BOOKKEEPING_SERIES = 1000


class Stats:
    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._hour_start = self._floor_hour(_dt.datetime.now(tz=UTC))
        self._hourly: Dict[int, Counter] = {}
        self._prev_hourly: Dict[int, Counter] = {}
        self.registry = registry or default_registry()
        self._longlive = self.registry.counter(
            "pio_event_bookkeeping_total",
            "Lifetime ingest bookkeeping by app/status/event/entity type",
            labelnames=("app_id", "status", "event", "entity_type"))

    @staticmethod
    def _floor_hour(t: _dt.datetime) -> _dt.datetime:
        return t.replace(minute=0, second=0, microsecond=0)

    def bookkeeping(self, app_id: int, status: int, event: Event) -> None:
        key = (status, event.event, event.entity_type)
        now = _dt.datetime.now(tz=UTC)
        with self._lock:
            hour = self._floor_hour(now)
            if hour != self._hour_start:  # roll the hourly window
                # "previous hour" only means the immediately preceding one;
                # after an idle gap the old window is stale, not previous
                contiguous = hour == self._hour_start + _dt.timedelta(hours=1)
                self._prev_hourly = self._hourly if contiguous else {}
                self._hour_start = hour
                self._hourly = {}
            self._hourly.setdefault(app_id, Counter())[key] += 1
        labels = dict(app_id=str(app_id), status=str(status),
                      event=event.event,
                      entity_type=event.entity_type or "")
        if (not self._longlive.contains(**labels)
                and self._longlive.series_count() >= MAX_BOOKKEEPING_SERIES):
            labels["event"] = "__other__"
            labels["entity_type"] = "__other__"
        self._longlive.inc(**labels)

    def _longlive_counter(self, app_id: int) -> Counter:
        app = str(app_id)
        out: Counter = Counter()
        for labels, value in self._longlive.samples():
            if labels["app_id"] != app:
                continue
            key = (int(labels["status"]), labels["event"],
                   labels["entity_type"])
            out[key] += int(value)
        return out

    def get(self, app_id: int) -> dict:
        with self._lock:
            # snapshot under the lock: a concurrent bookkeeping() may
            # mutate these Counters mid-render otherwise
            hourly = Counter(self._hourly.get(app_id, Counter()))
            prev = Counter(self._prev_hourly.get(app_id, Counter()))
            start = self._hour_start
        return {
            "startTime": start.isoformat(),
            "hourly": _render(hourly),
            "longLive": _render(self._longlive_counter(app_id)),
            "prevHourly": _render(prev),
        }


def _render(counter: Counter) -> list:
    return [
        {"status": status, "event": event, "entityType": etype, "count": count}
        for (status, event, etype), count in sorted(counter.items())
    ]
