"""Event-server ingest statistics.

Parity with the reference's Stats/StatsActor
(data/.../api/Stats.scala:28-80, StatsActor.scala:30-76): per-app counters
keyed by (status, event name, entity type), kept for the current hour and
for the server's lifetime, surfaced at /stats.json.
"""

from __future__ import annotations

import datetime as _dt
import threading
from collections import Counter
from typing import Dict

from predictionio_tpu.data.event import UTC, Event


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._hour_start = self._floor_hour(_dt.datetime.now(tz=UTC))
        self._hourly: Dict[int, Counter] = {}
        self._longlive: Dict[int, Counter] = {}

    @staticmethod
    def _floor_hour(t: _dt.datetime) -> _dt.datetime:
        return t.replace(minute=0, second=0, microsecond=0)

    def bookkeeping(self, app_id: int, status: int, event: Event) -> None:
        key = (status, event.event, event.entity_type)
        now = _dt.datetime.now(tz=UTC)
        with self._lock:
            hour = self._floor_hour(now)
            if hour != self._hour_start:  # roll the hourly window
                self._hour_start = hour
                self._hourly = {}
            self._hourly.setdefault(app_id, Counter())[key] += 1
            self._longlive.setdefault(app_id, Counter())[key] += 1

    def get(self, app_id: int) -> dict:
        with self._lock:
            return {
                "startTime": self._hour_start.isoformat(),
                "hourly": _render(self._hourly.get(app_id, Counter())),
                "longLive": _render(self._longlive.get(app_id, Counter())),
            }


def _render(counter: Counter) -> list:
    return [
        {"status": status, "event": event, "entityType": etype, "count": count}
        for (status, event, etype), count in sorted(counter.items())
    ]
