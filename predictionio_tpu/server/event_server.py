"""Event Server — REST event ingest on port 7070.

Route-for-route parity with the reference EventServer
(data/.../api/EventServer.scala):

  GET  /                      -> {"status": "alive"}          (:150)
  POST /events.json           -> 201 {"eventId": id}          (:241)
  GET  /events.json           -> query with filters           (:274)
  GET  /events/<id>.json      -> one event                    (:207)
  DELETE /events/<id>.json    -> {"message": "Found"}         (:224)
  POST /batch/events.json     -> per-event status list, <=50  (:340)
  GET  /stats.json            -> ingest counters (--stats)    (:421)
  GET  /plugins.json          -> plugin registry dump         (:155)
  POST /webhooks/<name>.json  -> connector-parsed event       (:442)
  GET  /webhooks/<name>.json  -> connector liveness           (:delegates)

Auth: accessKey query parameter or `Authorization: Basic <key:>` header;
optional `channel` query parameter (:92-142). Event writes are group-
committed through the bounded WriteBuffer (data/write_buffer.py): many
concurrent requests coalesce into few `insert_batch` flushes, the server
sheds with 429 + Retry-After once the queue bound is hit, and a graceful
shutdown drains the buffer before exiting (`PIO_INGEST_BUFFER=0` restores
the per-request thread-pool write path).
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Optional

from aiohttp import web

from predictionio_tpu.data.event import Event, EventValidationError, parse_event_time, validate_event
from predictionio_tpu.data.write_buffer import BufferFull, WriteBuffer
from predictionio_tpu.obs.middleware import add_metrics_routes, observability_middleware
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.server.plugins import PluginContext
from predictionio_tpu.server.stats import Stats
from predictionio_tpu.storage.base import StorageError
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.utils.server_config import IngestConfig

logger = logging.getLogger("pio.eventserver")

#: EventServer.scala:66 — the NON-CONFIGURED parity default only.
#: Handlers read the effective cap from their IngestConfig (tunable via
#: PIO_MAX_EVENTS_PER_BATCH / server.json ingest.maxEventsPerBatch);
#: this module constant does not reflect runtime configuration.
MAX_EVENTS_PER_BATCH = IngestConfig.max_events_per_batch
DEFAULT_PORT = 7070


class AuthData:
    __slots__ = ("app_id", "channel_id", "events")

    def __init__(self, app_id: int, channel_id: Optional[int], events):
        self.app_id = app_id
        self.channel_id = channel_id
        self.events = tuple(events)


def _json_response(data, status=200):
    return web.json_response(data, status=status)


class EventServer:
    def __init__(self, stats: bool = False,
                 plugin_context: Optional[PluginContext] = None,
                 registry: Optional[MetricsRegistry] = None,
                 ingest: Optional[IngestConfig] = None,
                 telemetry=None):
        self.stats_enabled = stats
        self.registry = registry or MetricsRegistry()
        self.ingest_config = ingest or IngestConfig.from_env()
        #: durable-telemetry recorder (obs/telemetry.py) when wired by
        #: run_event_server: ingest metrics + lifecycle events survive
        #: the process, /history/* serves the host's merged stores
        self.telemetry = telemetry
        self.buffer: Optional[WriteBuffer] = None
        if self.ingest_config.buffer:
            ic = self.ingest_config
            self.buffer = WriteBuffer(
                store_fn=Storage.get_events,
                queue_max=ic.queue_max, flush_max=ic.flush_max,
                linger_s=ic.linger_s, retries=ic.retries,
                backoff_s=ic.backoff_s, backoff_cap_s=ic.backoff_cap_s,
                flush_timeout_s=ic.flush_timeout_s,
                partitions=ic.partitions, registry=self.registry)
        self.stats = Stats(registry=self.registry)
        from predictionio_tpu.obs.capacity import register_capacity_metrics

        register_capacity_metrics(self.registry)
        self._ingest_total = self.registry.counter(
            "pio_event_ingest_total",
            "Event ingest attempts by response status",
            labelnames=("status",))
        self._rejected_total = self.registry.counter(
            "pio_event_rejected_total",
            "Rejected events by reason (invalid/forbidden/blocked/storage)",
            labelnames=("reason",))
        self._batch_size = self.registry.histogram(
            "pio_event_batch_size", "Events per /batch/events.json request",
            buckets=(1, 2, 5, 10, 20, 50))
        self.plugins = plugin_context or PluginContext(
            "predictionio_tpu.eventserver_plugins")
        self.app = web.Application(middlewares=[
            observability_middleware(self.registry, "event_server")])
        self._routes()
        self.app.on_shutdown.append(self._drain_on_shutdown)

    async def _drain_on_shutdown(self, app) -> None:
        """Graceful shutdown: flush every buffered event before the
        process exits — accepted (201-pending) events are never dropped;
        the telemetry recorder then drains its final snapshot + the
        flight-recorder remainder into the durable store."""
        if self.buffer is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self.buffer.stop)
        if self.telemetry is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, self.telemetry.stop)

    # -- auth ---------------------------------------------------------------
    async def _auth(self, request: web.Request) -> AuthData:
        """EventServer.scala:92-142 — query param first, then Basic header."""
        access_key = request.query.get("accessKey")
        if access_key is None:
            header = request.headers.get("Authorization", "")
            if header.startswith("Basic "):
                try:
                    decoded = base64.b64decode(header[len("Basic "):]).decode()
                    access_key = decoded.strip().split(":")[0]
                except Exception:
                    raise web.HTTPUnauthorized(
                        text=json.dumps({"message": "Invalid accessKey."}),
                        content_type="application/json")
            else:
                raise web.HTTPUnauthorized(
                    text=json.dumps({"message": "Missing accessKey."}),
                    content_type="application/json")
        key = await self._run(Storage.get_meta_data_access_keys().get, access_key)
        if key is None:
            raise web.HTTPUnauthorized(
                text=json.dumps({"message": "Invalid accessKey."}),
                content_type="application/json")
        channel_id = None
        channel = request.query.get("channel")
        if channel is not None:
            channels = await self._run(
                Storage.get_meta_data_channels().get_by_appid, key.appid)
            matched = [c for c in channels if c.name == channel]
            if not matched:
                raise web.HTTPUnauthorized(
                    text=json.dumps({"message": f"Invalid channel '{channel}'."}),
                    content_type="application/json")
            channel_id = matched[0].id
        return AuthData(key.appid, channel_id, key.events)

    async def _run(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    # -- routes -------------------------------------------------------------
    def _routes(self):
        r = self.app.router
        r.add_get("/", self.handle_root)
        r.add_post("/events.json", self.handle_create)
        r.add_get("/events.json", self.handle_find)
        r.add_get("/events/{event_id}.json", self.handle_get)
        r.add_delete("/events/{event_id}.json", self.handle_delete)
        r.add_post("/batch/events.json", self.handle_batch)
        r.add_get("/stats.json", self.handle_stats)
        r.add_get("/plugins.json", self.handle_plugins)
        r.add_route("*", "/plugins/{tail:.*}", self.handle_plugin_rest)
        r.add_post("/webhooks/{name}.json", self.handle_webhook_post)
        r.add_get("/webhooks/{name}.json", self.handle_webhook_get)
        add_metrics_routes(self.app, self.registry, default_registry())
        from predictionio_tpu.obs.capacity import add_capacity_route

        add_capacity_route(self.app)
        from predictionio_tpu.obs.telemetry import (
            add_history_routes, history_reader_factory,
        )

        add_history_routes(self.app, history_reader_factory(self.telemetry))

    def _ingest(self, status: int, reason: Optional[str] = None) -> None:
        self._ingest_total.inc(status=str(status))
        if reason is not None:
            self._rejected_total.inc(reason=reason)

    def _shed_response(self, bf: BufferFull) -> web.Response:
        """Explicit load shedding: the ingest queue is at its bound."""
        self._ingest(429, "shed")
        return web.json_response(
            {"message": str(bf)}, status=429,
            headers={"Retry-After": str(bf.retry_after)})

    async def _insert(self, events, auth: AuthData):
        """Persist events, returning their ids. Group-commit path when the
        buffer is enabled (BufferFull/StorageError propagate to the
        caller); direct thread-pool insert_batch otherwise."""
        if self.buffer is not None:
            future = self.buffer.submit(events, auth.app_id, auth.channel_id)
            return await asyncio.wrap_future(future)
        return await self._run(
            Storage.get_events().insert_batch, events, auth.app_id,
            auth.channel_id)

    async def handle_root(self, request):
        return _json_response({"status": "alive"})

    async def handle_create(self, request):
        auth = await self._auth(request)
        try:
            body = await request.json()
            event = Event.from_dict(body)
            validate_event(event)
        except (EventValidationError, json.JSONDecodeError, TypeError,
                AttributeError, ValueError) as e:
            self._ingest(400, "invalid")
            return _json_response({"message": str(e)}, status=400)
        if auth.events and event.event not in auth.events:
            self._ingest(403, "forbidden")
            return _json_response(
                {"message": f"{event.event} events are not allowed"}, status=403)
        for blocker in self.plugins.input_blockers.values():
            try:
                blocker.process(auth.app_id, auth.channel_id, event)
            except Exception as e:  # blocker rejected the event
                self._ingest(403, "blocked")
                return _json_response({"message": str(e)}, status=403)
        try:
            event_id = (await self._insert([event], auth))[0]
        except BufferFull as bf:
            return self._shed_response(bf)
        except StorageError as e:
            # buffered failures already exhausted retries: retryable 503;
            # the direct path keeps the reference's 500
            status = 503 if self.buffer is not None else 500
            self._ingest(status, "storage_error")
            return _json_response({"message": str(e)}, status=status)
        for sniffer in self.plugins.input_sniffers.values():
            try:
                sniffer.process(auth.app_id, auth.channel_id, event)
            except Exception:
                logger.exception("input sniffer failed")
        if self.stats_enabled:
            self.stats.bookkeeping(auth.app_id, 201, event)
        self._ingest(201)
        return _json_response({"eventId": event_id}, status=201)

    async def handle_find(self, request):
        auth = await self._auth(request)
        q = request.query
        try:
            reversed_order = q.get("reversed", "false").lower() == "true"
            if reversed_order and not (q.get("entityType") and q.get("entityId")):
                # EventServer.scala:302-305
                return _json_response(
                    {"message": "the parameter reversed can only be used with "
                                "both entityType and entityId specified."},
                    status=400)
            kwargs = dict(
                start_time=(parse_event_time(q["startTime"])
                            if "startTime" in q else None),
                until_time=(parse_event_time(q["untilTime"])
                            if "untilTime" in q else None),
                entity_type=q.get("entityType"),
                entity_id=q.get("entityId"),
                event_names=[q["event"]] if "event" in q else None,
                limit=int(q.get("limit", 20)),  # default 20 (:319)
                reversed_order=reversed_order,
            )
            if "targetEntityType" in q:
                kwargs["target_entity_type"] = q["targetEntityType"]
            if "targetEntityId" in q:
                kwargs["target_entity_id"] = q["targetEntityId"]
        except (EventValidationError, ValueError) as e:
            return _json_response({"message": str(e)}, status=400)

        def _find():
            return list(Storage.get_events().find(
                auth.app_id, auth.channel_id, **kwargs))
        try:
            events = await self._run(_find)
        except StorageError as e:
            return _json_response({"message": str(e)}, status=500)
        if not events:
            return _json_response({"message": "Not Found"}, status=404)
        return _json_response([e.to_dict() for e in events])

    async def handle_get(self, request):
        auth = await self._auth(request)
        event_id = request.match_info["event_id"]
        try:
            event = await self._run(
                Storage.get_events().get, event_id, auth.app_id, auth.channel_id)
        except StorageError as e:
            return _json_response({"message": str(e)}, status=500)
        if event is None:
            return _json_response({"message": "Not Found"}, status=404)
        return _json_response(event.to_dict())

    async def handle_delete(self, request):
        auth = await self._auth(request)
        event_id = request.match_info["event_id"]
        try:
            found = await self._run(
                Storage.get_events().delete, event_id, auth.app_id, auth.channel_id)
        except StorageError as e:
            return _json_response({"message": str(e)}, status=500)
        if found:
            return _json_response({"message": "Found"})
        return _json_response({"message": "Not Found"}, status=404)

    async def handle_batch(self, request):
        """EventServer.scala:340-419 — per-event results, original order."""
        auth = await self._auth(request)
        try:
            body = await request.json()
            if not isinstance(body, list):
                raise ValueError("batch body must be a JSON array")
        except (json.JSONDecodeError, ValueError) as e:
            return _json_response({"message": str(e)}, status=400)
        max_batch = self.ingest_config.max_events_per_batch
        if len(body) > max_batch:
            return _json_response(
                {"message": "Batch request must have less than or equal to "
                            f"{max_batch} events"}, status=400)
        self._batch_size.observe(len(body))
        results = []
        to_insert = []  # (index, event)
        for i, item in enumerate(body):
            try:
                event = Event.from_dict(item)
                validate_event(event)
            except (EventValidationError, TypeError, AttributeError) as e:
                self._ingest(400, "invalid")
                results.append((i, {"status": 400, "message": str(e)}))
                continue
            if auth.events and event.event not in auth.events:
                self._ingest(403, "forbidden")
                results.append((i, {
                    "status": 403,
                    "message": f"{event.event} events are not allowed"}))
                continue
            blocked = False
            for blocker in self.plugins.input_blockers.values():
                try:
                    blocker.process(auth.app_id, auth.channel_id, event)
                except Exception as e:
                    self._ingest(403, "blocked")
                    results.append((i, {"status": 403, "message": str(e)}))
                    blocked = True
                    break
            if not blocked:
                to_insert.append((i, event))
        if to_insert:
            try:
                ids = await self._insert([e for _, e in to_insert], auth)
            except BufferFull as bf:
                # nothing was accepted: shed the whole request explicitly
                return self._shed_response(bf)
            except StorageError as e:
                # per-event status entries, preserving the reference's
                # per-event-result semantics: the already-computed 400/403
                # entries survive, the failed inserts report a retryable
                # 503 each (not a wholesale 500 discarding the rest)
                for i, _event in to_insert:
                    self._ingest(503, "storage_error")
                    results.append((i, {"status": 503, "message": str(e)}))
                ids = None
            if ids is not None:
                for (i, event), event_id in zip(to_insert, ids):
                    self._ingest(201)
                    if self.stats_enabled:
                        self.stats.bookkeeping(auth.app_id, 201, event)
                    for sniffer in self.plugins.input_sniffers.values():
                        try:
                            sniffer.process(auth.app_id, auth.channel_id, event)
                        except Exception:
                            logger.exception("input sniffer failed")
                    results.append((i, {"status": 201, "eventId": event_id}))
        results.sort(key=lambda pair: pair[0])
        return _json_response([r for _, r in results])

    async def handle_stats(self, request):
        auth = await self._auth(request)
        if not self.stats_enabled:
            return _json_response(
                {"message": "To see stats, launch Event Server with --stats "
                            "argument."}, status=404)
        return _json_response(self.stats.get(auth.app_id))

    async def handle_plugins(self, request):
        return _json_response({"plugins": self.plugins.describe()})

    async def handle_plugin_rest(self, request):
        auth = await self._auth(request)
        segments = request.match_info["tail"].split("/")
        if len(segments) < 2:
            return _json_response({"message": "Not Found"}, status=404)
        plugin_type, plugin_name, *args = segments
        registry = {"inputblockers": self.plugins.input_blockers,
                    "inputsniffers": self.plugins.input_sniffers}.get(plugin_type)
        if registry is None or plugin_name not in registry:
            return _json_response({"message": "Not Found"}, status=404)
        out = registry[plugin_name].handle_rest(auth.app_id, auth.channel_id, args)
        return _json_response(out)

    # -- webhooks (EventServer.scala:442-523) -------------------------------
    async def handle_webhook_post(self, request):
        auth = await self._auth(request)
        name = request.match_info["name"]
        from predictionio_tpu.data.webhooks import get_connector
        connector = get_connector(name)
        if connector is None:
            return _json_response(
                {"message": f"webhooks connection for {name} is not supported."},
                status=404)
        try:
            if connector.form_based:
                payload = dict(await request.post())
            else:
                payload = await request.json()
            event = connector.to_event(payload)
            validate_event(event)
        except Exception as e:
            self._ingest(400, "invalid")
            return _json_response({"message": str(e)}, status=400)
        try:
            event_id = (await self._insert([event], auth))[0]
        except BufferFull as bf:
            return self._shed_response(bf)
        except StorageError as e:
            status = 503 if self.buffer is not None else 500
            self._ingest(status, "storage_error")
            return _json_response({"message": str(e)}, status=status)
        if self.stats_enabled:
            self.stats.bookkeeping(auth.app_id, 201, event)
        self._ingest(201)
        return _json_response({"eventId": event_id}, status=201)

    async def handle_webhook_get(self, request):
        await self._auth(request)
        name = request.match_info["name"]
        from predictionio_tpu.data.webhooks import get_connector
        connector = get_connector(name)
        if connector is None:
            return _json_response(
                {"message": f"webhooks connection for {name} is not supported."},
                status=404)
        return _json_response({"message": f"webhooks connection for {name} is ok."})


def create_event_server(stats: bool = False,
                        plugin_context: Optional[PluginContext] = None,
                        registry: Optional[MetricsRegistry] = None,
                        ingest: Optional[IngestConfig] = None,
                        telemetry=None) -> web.Application:
    """EventServer.createEventServer:528 parity."""
    return EventServer(stats=stats, plugin_context=plugin_context,
                       registry=registry, ingest=ingest,
                       telemetry=telemetry).app


def run_event_server(ip: str = "localhost", port: int = DEFAULT_PORT,
                     stats: bool = False) -> None:
    """Standalone entry (EventServer Run.main:552)."""
    from predictionio_tpu.obs.telemetry import build_recorder
    from predictionio_tpu.utils.server_config import ServerConfig

    cfg = ServerConfig.load()
    registry = MetricsRegistry()
    telemetry = build_recorder("event_server", cfg.telemetry,
                               instance=str(port),
                               registries=[registry, default_registry()])
    app = create_event_server(stats=stats, ingest=cfg.ingest,
                              registry=registry, telemetry=telemetry)
    ssl_ctx = cfg.ssl_context()
    logger.info("Event Server listening on %s:%s%s", ip, port,
                " (TLS)" if ssl_ctx else "")
    web.run_app(app, host=ip, port=port, ssl_context=ssl_ctx, print=None)
