"""Server plugin registries.

Parity with the reference's plugin SPIs discovered via ServiceLoader:
  * EventServerPlugin (data/.../api/EventServerPlugin.scala) — input blockers
    (synchronous, may reject an event) and input sniffers (async observers)
  * EngineServerPlugin (core/.../workflow/EngineServerPlugin.scala:24-41) —
    output blockers (synchronous prediction transforms) and output sniffers

The rebuild discovers plugins through explicit registration or setuptools
entry points (groups `predictionio_tpu.eventserver_plugins` and
`predictionio_tpu.engineserver_plugins`).
"""

from __future__ import annotations

import abc
from typing import Dict, List, Optional

from predictionio_tpu.data.event import Event


class EventServerPlugin(abc.ABC):
    """Input blocker/sniffer on the ingest path."""

    INPUT_BLOCKER = "inputblocker"
    INPUT_SNIFFER = "inputsniffer"

    plugin_name: str = ""
    plugin_description: str = ""
    plugin_type: str = INPUT_SNIFFER

    @abc.abstractmethod
    def process(self, app_id: int, channel_id: Optional[int],
                event: Event) -> None:
        """Blockers raise to reject the event; sniffers observe."""

    def handle_rest(self, app_id: int, channel_id: Optional[int],
                    args: List[str]) -> dict:
        return {}


class EngineServerPlugin(abc.ABC):
    """Output blocker/sniffer on the query path."""

    OUTPUT_BLOCKER = "outputblocker"
    OUTPUT_SNIFFER = "outputsniffer"

    plugin_name: str = ""
    plugin_description: str = ""
    plugin_type: str = OUTPUT_SNIFFER

    @abc.abstractmethod
    def process(self, engine_instance, query: dict, prediction: dict) -> dict:
        """Blockers return a (possibly modified) prediction; sniffers observe
        and their return value is ignored."""

    def handle_rest(self, args: List[str]) -> dict:
        return {}


class PluginContext:
    """Holds registered plugins, split by type (EventServerPluginContext parity)."""

    def __init__(self, entry_point_group: Optional[str] = None):
        self.input_blockers: Dict[str, EventServerPlugin] = {}
        self.input_sniffers: Dict[str, EventServerPlugin] = {}
        self.output_blockers: Dict[str, EngineServerPlugin] = {}
        self.output_sniffers: Dict[str, EngineServerPlugin] = {}
        if entry_point_group:
            self._load_entry_points(entry_point_group)

    def register(self, plugin) -> None:
        if isinstance(plugin, EventServerPlugin):
            target = (self.input_blockers
                      if plugin.plugin_type == EventServerPlugin.INPUT_BLOCKER
                      else self.input_sniffers)
        elif isinstance(plugin, EngineServerPlugin):
            target = (self.output_blockers
                      if plugin.plugin_type == EngineServerPlugin.OUTPUT_BLOCKER
                      else self.output_sniffers)
        else:
            raise TypeError(f"not a plugin: {plugin!r}")
        target[plugin.plugin_name] = plugin

    def _load_entry_points(self, group: str) -> None:
        try:
            from importlib.metadata import entry_points
            for ep in entry_points(group=group):
                self.register(ep.load()())
        except Exception:  # plugin discovery must never break the server
            pass

    def describe(self) -> dict:
        def _desc(plugins):
            return {name: {"name": p.plugin_name,
                           "description": p.plugin_description,
                           "class": type(p).__qualname__}
                    for name, p in plugins.items()}
        return {
            "inputblockers": _desc(self.input_blockers),
            "inputsniffers": _desc(self.input_sniffers),
            "outputblockers": _desc(self.output_blockers),
            "outputsniffers": _desc(self.output_sniffers),
        }
