"""Metric library for evaluation.

Parity with the reference Metric family (core/.../controller/Metric.scala:39-268):
Metric computes a result R from [(EvalInfo, [(Q, P, A)])]; comparison uses the
result value. Subclasses implement calculate_point (per Q/P/A) and the
aggregation (average / stdev / sum), with Option* variants skipping None
points. The reference's Spark StatCounter aggregation becomes numpy.
"""

from __future__ import annotations

import abc
import math
from typing import Generic, Optional, Sequence, Tuple, TypeVar

import numpy as np

from predictionio_tpu.core.base import A, EI, P, Q

R = TypeVar("R")

EvalDataSet = Sequence[Tuple[EI, Sequence[Tuple[Q, P, A]]]]


class Metric(Generic[EI, Q, P, A, R], abc.ABC):
    """Metric.scala:39. Higher is better by default; set smaller_is_better.

    ``sweep_kind`` opts a metric into the device-batched evaluation sweep
    (core/evaluation.py): a metric that names one of the kinds an
    algorithm's ``sweep_eval`` can compute on device ("precision_at_k",
    "topn_mse", "zero") is evaluated in batch over the whole candidate
    grid instead of through per-fold Q/P/A loops. ``None`` (the default)
    keeps the metric on the sequential path.
    """

    smaller_is_better: bool = False
    sweep_kind = None  # type: Optional[str]

    @abc.abstractmethod
    def calculate(self, ctx, eval_data_set: EvalDataSet) -> R: ...

    def compare(self, r0: R, r1: R) -> int:
        sign = -1 if self.smaller_is_better else 1
        if r0 == r1:
            return 0
        return sign if r0 > r1 else -sign

    def header(self) -> str:
        return type(self).__name__


class _PointMetric(Metric):
    """Shared base: flatten the eval matrix to per-(Q,P,A) scores."""

    @abc.abstractmethod
    def calculate_point(self, eval_info, query, prediction, actual
                        ) -> Optional[float]: ...

    def _points(self, eval_data_set: EvalDataSet) -> np.ndarray:
        scores = []
        for eval_info, qpa in eval_data_set:
            for q, p, a in qpa:
                scores.append(self.calculate_point(eval_info, q, p, a))
        return np.asarray([s for s in scores if s is not None], dtype=np.float64)


class AverageMetric(_PointMetric):
    """Metric.scala:99 — mean of per-point scores (None is an error)."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        points = self._points(eval_data_set)
        return float(points.mean()) if points.size else float("nan")


class OptionAverageMetric(AverageMetric):
    """Metric.scala:124 — mean over points where calculate_point is not None.

    (The numeric behavior matches AverageMetric because _points already
    drops None; the distinct class preserves the reference API where
    returning None from a plain AverageMetric is a contract violation.)
    """


class StdevMetric(_PointMetric):
    """Metric.scala:151 — population stdev of scores."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        points = self._points(eval_data_set)
        return float(points.std()) if points.size else float("nan")


class OptionStdevMetric(StdevMetric):
    """Metric.scala:179."""


class SumMetric(_PointMetric):
    """Metric.scala:205 — sum of scores."""

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        points = self._points(eval_data_set)
        return float(points.sum())


class ZeroMetric(Metric):
    """Metric.scala:234 — always 0; for evaluations without a real metric."""

    sweep_kind = "zero"

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        return 0.0


class QPAMetric(_PointMetric):
    """Convenience: build a metric from a scoring callable."""

    def __init__(self, fn, aggregation: str = "average",
                 smaller_is_better: bool = False):
        self._fn = fn
        self._agg = aggregation
        self.smaller_is_better = smaller_is_better

    def calculate_point(self, eval_info, query, prediction, actual):
        return self._fn(query, prediction, actual)

    def calculate(self, ctx, eval_data_set: EvalDataSet) -> float:
        points = self._points(eval_data_set)
        if not points.size:
            return float("nan")
        if self._agg == "average":
            return float(points.mean())
        if self._agg == "sum":
            return float(points.sum())
        if self._agg == "stdev":
            return float(points.std())
        raise ValueError(f"unknown aggregation {self._agg}")


def rmse(predicted: float, actual: float) -> float:
    """Squared-error point score; AverageMetric of this is MSE (sqrt for RMSE)."""
    d = predicted - actual
    return d * d


def is_nan(x: float) -> bool:
    return isinstance(x, float) and math.isnan(x)
