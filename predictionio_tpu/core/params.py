"""Component parameters and their JSON round-trip.

Parity with the reference's Params/EngineParams
(core/.../controller/{Params.scala:26-34,EngineParams.scala:35-152}) and the
JSON extraction in Engine.jValueToEngineParams (Engine.scala:355-418) /
JsonExtractor (core/.../workflow/JsonExtractor.scala:37-167). The reference
needs a dual json4s/Gson stack to cover Scala and Java engines; the rebuild
uses dataclasses, so one extractor suffices.

Engine variant JSON keeps the reference's engine.json schema:

    {
      "id": "default",
      "engineFactory": "mypkg.engine:factory",
      "datasource": {"params": {...}},
      "preparator": {"params": {...}},
      "algorithms": [{"name": "als", "params": {...}}],
      "serving": {"params": {...}}
    }
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type


class Params:
    """Marker base for component params (Params.scala:26). Subclasses are
    normally dataclasses; plain dicts are also accepted anywhere Params are."""


@dataclasses.dataclass(frozen=True)
class EmptyParams(Params):
    pass


def params_to_json(params: Any) -> Any:
    """Params (dataclass | dict | None) -> JSON value."""
    if params is None:
        return {}
    if dataclasses.is_dataclass(params) and not isinstance(params, type):
        return dataclasses.asdict(params)
    if isinstance(params, dict):
        return params
    raise TypeError(f"cannot serialize params of type {type(params).__name__}")


def _snake(name: str) -> str:
    """camelCase -> snake_case (appName -> app_name)."""
    return re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", name).lower()


def params_from_json(data: Any, params_class: Optional[type] = None) -> Any:
    """JSON value -> params_class instance (or plain dict when no class).

    Reference engine.json variants use camelCase keys (appName,
    numIterations — Engine.scala:355 extracts into Scala case classes);
    those are accepted and mapped onto the snake_case dataclass fields, as
    are per-class `json_aliases` (e.g. ALS's "lambda" -> reg). Unknown keys
    raise (the reference's json4s extract is strict in the same way for
    missing fields; strictness here catches typo'd hyperparameters).
    """
    if data is None:
        data = {}
    if params_class is None:
        return dict(data)
    if not dataclasses.is_dataclass(params_class):
        return params_class(**data)
    field_names = {f.name for f in dataclasses.fields(params_class)}
    aliases = getattr(params_class, "json_aliases", {})
    mapped = {}
    sources = {}
    unknown = []
    for key, value in dict(data).items():
        name = aliases.get(key, key)
        if name not in field_names:
            name = _snake(name)
        if name in field_names:
            if name in mapped:
                raise ValueError(
                    f"parameters {sources[name]!r} and {key!r} both set "
                    f"field {name!r} of {params_class.__name__}")
            mapped[name] = value
            sources[name] = key
        else:
            unknown.append(key)
    if unknown:
        raise ValueError(
            f"unknown parameter(s) {sorted(unknown)} for "
            f"{params_class.__name__}; expected among {sorted(field_names)}")
    return params_class(**mapped)


@dataclasses.dataclass
class EngineParams:
    """EngineParams.scala:35 — named params for each DASE component.

    Component names select among an Engine's registered classes; "" selects
    the single/default one.
    """

    data_source_name: str = ""
    data_source_params: Any = None
    preparator_name: str = ""
    preparator_params: Any = None
    #: list of (algorithm name, params)
    algorithm_params_list: Sequence[Tuple[str, Any]] = ()
    serving_name: str = ""
    serving_params: Any = None

    def to_json_dict(self) -> Dict[str, Any]:
        """engineParamsToJson parity (JsonExtractor.scala:95)."""
        return {
            "datasource": {"name": self.data_source_name,
                           "params": params_to_json(self.data_source_params)},
            "preparator": {"name": self.preparator_name,
                           "params": params_to_json(self.preparator_params)},
            "algorithms": [
                {"name": name, "params": params_to_json(p)}
                for name, p in self.algorithm_params_list],
            "serving": {"name": self.serving_name,
                        "params": params_to_json(self.serving_params)},
        }

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), sort_keys=True)

    def copy(self, **updates) -> "EngineParams":
        return dataclasses.replace(self, **updates)


def engine_params_from_json(
    data: Dict[str, Any],
    data_source_params_class: Optional[type] = None,
    preparator_params_class: Optional[type] = None,
    algorithm_params_classes: Optional[Dict[str, type]] = None,
    serving_params_class: Optional[type] = None,
) -> EngineParams:
    """jValueToEngineParams parity (Engine.scala:355-418)."""
    def _component(key: str, cls: Optional[type]):
        node = data.get(key) or {}
        if not isinstance(node, dict):
            raise ValueError(f"{key} must be an object")
        name = node.get("name", "")
        params = params_from_json(node.get("params"), cls)
        return name, params

    ds_name, ds_params = _component("datasource", data_source_params_class)
    p_name, p_params = _component("preparator", preparator_params_class)
    s_name, s_params = _component("serving", serving_params_class)

    algo_list: List[Tuple[str, Any]] = []
    for node in data.get("algorithms") or []:
        name = node.get("name", "")
        cls = (algorithm_params_classes or {}).get(name)
        algo_list.append((name, params_from_json(node.get("params"), cls)))

    return EngineParams(
        data_source_name=ds_name, data_source_params=ds_params,
        preparator_name=p_name, preparator_params=p_params,
        algorithm_params_list=algo_list,
        serving_name=s_name, serving_params=s_params)
