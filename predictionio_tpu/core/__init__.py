"""DASE controller API (L3).

Rebuilds the reference's core/controller + core/core
(SURVEY.md sections 2.4-2.5) as plain Python protocols over JAX: DataSource ->
Preparator -> Algorithm(s) -> Serving, plus Evaluation/Metric. Where the
reference splits L/P/P2L class families by Spark physical placement
(LAlgorithm.scala / P2LAlgorithm.scala / PAlgorithm.scala), the rebuild has
ONE protocol per component: "local" is simply a mesh of one device, and every
model is a pytree, making serialization uniform (SURVEY.md section 7 design
mapping).
"""

from predictionio_tpu.core.base import (
    Algorithm,
    DataSource,
    Preparator,
    IdentityPreparator,
    SanityCheck,
    Serving,
    FirstServing,
    AverageServing,
    PersistentModel,
)
from predictionio_tpu.core.params import EngineParams, Params, params_to_json, params_from_json
from predictionio_tpu.core.engine import Engine, EngineFactory, TrainResult
from predictionio_tpu.core.metrics import (
    Metric,
    AverageMetric,
    OptionAverageMetric,
    StdevMetric,
    OptionStdevMetric,
    SumMetric,
    ZeroMetric,
)
from predictionio_tpu.core.evaluation import (
    Evaluation,
    EngineParamsGenerator,
    MetricEvaluator,
    MetricEvaluatorResult,
)

__all__ = [
    "Algorithm", "DataSource", "Preparator", "IdentityPreparator",
    "SanityCheck", "Serving", "FirstServing", "AverageServing",
    "PersistentModel", "EngineParams", "Params", "params_to_json",
    "params_from_json", "Engine", "EngineFactory", "TrainResult", "Metric",
    "AverageMetric", "OptionAverageMetric", "StdevMetric", "OptionStdevMetric",
    "SumMetric", "ZeroMetric", "Evaluation", "EngineParamsGenerator",
    "MetricEvaluator", "MetricEvaluatorResult",
]
