"""Evaluation workflow: metric sweeps over engine params.

Parity map (reference file:line):
  * Evaluation            <- controller/Evaluation.scala:34-125
  * EngineParamsGenerator <- controller/EngineParamsGenerator.scala:30-46
  * MetricEvaluator       <- controller/MetricEvaluator.scala:185-263
    (evaluateBase:218, best selection:246-249, best.json:252)
  * prefix-memoized sweep <- controller/FastEvalEngine.scala:46-346 —
    rebuilt as CachedEvalRunner: datasource / preparator / per-algorithm
    train results are cached by params-JSON prefix across the sweep, the
    compilation-cache analog of FastEvalEngine's pipeline memoization
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.core.engine import Engine, evaluate_fold
from predictionio_tpu.core.metrics import Metric
from predictionio_tpu.core.params import EngineParams, params_to_json

logger = logging.getLogger("pio.evaluation")


class EngineParamsGenerator:
    """Supplies the list of EngineParams to sweep (EngineParamsGenerator.scala:30)."""

    engine_params_list: Sequence[EngineParams] = ()


class Evaluation:
    """Glue object tying an Engine to a Metric (Evaluation.scala:34).

    Subclass (declaring engine/metric as class attributes, the reference's
    `engineMetric =` style) or instantiate with engine + metric
    (+ other_metrics). The evaluator writes best.json
    (Evaluation.engineMetric_= sugar, :91-99).
    """

    # class-attribute declaration point for subclasses
    engine: Optional[Engine] = None
    metric: Optional[Metric] = None
    other_metrics: Sequence[Metric] = ()
    output_path: Optional[str] = "best.json"
    #: optional params list carried by the evaluation itself
    engine_params_list: Sequence[EngineParams] = ()

    def __init__(self, engine: Optional[Engine] = None,
                 metric: Optional[Metric] = None,
                 other_metrics: Optional[Sequence[Metric]] = None,
                 output_path: Optional[str] = "__default__"):
        # only override class-level declarations when explicitly given
        if engine is not None:
            self.engine = engine
        if metric is not None:
            self.metric = metric
        if other_metrics is not None:
            self.other_metrics = list(other_metrics)
        if output_path != "__default__":
            self.output_path = output_path

    @property
    def evaluator(self) -> "MetricEvaluator":
        return MetricEvaluator(self.metric, self.other_metrics,
                               self.output_path)

    def run(self, ctx, engine_params_list: Sequence[EngineParams]
            ) -> "MetricEvaluatorResult":
        return self.evaluator.evaluate(ctx, self.engine, engine_params_list)


@dataclasses.dataclass
class MetricEvaluatorResult:
    """MetricEvaluator.scala:64-110 — scores per params with the best pick."""

    best_score: float
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: List[str]
    engine_params_scores: List[Tuple[EngineParams, float, List[float]]]

    def to_one_liner(self) -> str:
        return f"[{self.metric_header}] {self.best_score}"

    def to_json_dict(self) -> dict:
        return {
            "bestScore": self.best_score,
            "bestEngineParams": self.best_engine_params.to_json_dict(),
            "bestIdx": self.best_idx,
            "metricHeader": self.metric_header,
            "otherMetricHeaders": self.other_metric_headers,
            "engineParamsScores": [
                {"engineParams": ep.to_json_dict(), "score": s, "others": o}
                for ep, s, o in self.engine_params_scores],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), sort_keys=True)

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{i}</td><td>{s}</td><td><pre>{ep.to_json()}</pre></td></tr>"
            for i, (ep, s, _o) in enumerate(self.engine_params_scores))
        return (f"<html><body><h1>{self.metric_header}</h1>"
                f"<p>Best score: {self.best_score} "
                f"(params #{self.best_idx})</p>"
                f"<table border=1><tr><th>#</th><th>score</th>"
                f"<th>engine params</th></tr>{rows}</table></body></html>")


class CachedEvalRunner:
    """FastEvalEngine.scala:46-346 rebuilt: memoize shared pipeline prefixes.

    Within one sweep, engine params sharing a prefix reuse results:
      * data source (read_eval folds) keyed by datasource params
      * prepared data keyed by (datasource, preparator) params
      * trained models keyed by (datasource, preparator, single algo params)
    Jitted train functions additionally hit XLA's compilation cache when only
    numeric hyperparameters change.
    """

    def __init__(self, engine: Engine):
        self.engine = engine
        self._ds_cache: Dict[str, Any] = {}
        self._prep_cache: Dict[str, Any] = {}
        self._model_cache: Dict[str, Any] = {}

    @staticmethod
    def _key(*parts: Any) -> str:
        return json.dumps([_jsonable(p) for p in parts], sort_keys=True,
                          default=str)

    def eval(self, ctx, ep: EngineParams):
        ds_key = self._key(ep.data_source_name, ep.data_source_params)
        if ds_key not in self._ds_cache:
            data_source = self.engine._data_source(ep)
            self._ds_cache[ds_key] = list(data_source.read_eval(ctx))
        eval_data = self._ds_cache[ds_key]

        prep_key = self._key(ds_key, ep.preparator_name, ep.preparator_params)
        if prep_key not in self._prep_cache:
            preparator = self.engine._preparator(ep)
            self._prep_cache[prep_key] = [
                preparator.prepare(ctx, td) for td, _ei, _qa in eval_data]
        prepared = self._prep_cache[prep_key]

        named_algos = self.engine._algorithms(ep)
        serving = self.engine._serving(ep)

        results = []
        for fold_idx, ((td, eval_info, qa_pairs), pd) in enumerate(
                zip(eval_data, prepared)):
            models = []
            for (name, algo), (pname, algo_params) in zip(
                    named_algos, ep.algorithm_params_list):
                model_key = self._key(prep_key, fold_idx, pname, algo_params)
                if model_key not in self._model_cache:
                    self._model_cache[model_key] = algo.train(ctx, pd)
                models.append(self._model_cache[model_key])
            qpa = evaluate_fold(named_algos, models, serving, qa_pairs)
            results.append((eval_info, qpa))
        return results


def _jsonable(p: Any) -> Any:
    try:
        return params_to_json(p)
    except TypeError:
        return repr(p)


class MetricEvaluator:
    """MetricEvaluator.scala:185 — score every engine params, pick the best."""

    def __init__(self, metric: Metric, other_metrics: Sequence[Metric] = (),
                 output_path: Optional[str] = "best.json"):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path

    def evaluate(self, ctx, engine: Engine,
                 engine_params_list: Sequence[EngineParams]
                 ) -> MetricEvaluatorResult:
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        runner = CachedEvalRunner(engine)
        scores: List[Tuple[EngineParams, float, List[float]]] = []
        for i, ep in enumerate(engine_params_list):
            eval_data = runner.eval(ctx, ep)
            score = self.metric.calculate(ctx, eval_data)
            others = [m.calculate(ctx, eval_data) for m in self.other_metrics]
            logger.info("engine params %d/%d: %s = %s",
                        i + 1, len(engine_params_list),
                        self.metric.header(), score)
            scores.append((ep, score, others))

        import math

        # NaN scores (e.g. empty folds) can never win; if all are NaN the
        # first is reported so the caller still sees the failure
        best_idx = 0
        for i in range(1, len(scores)):
            cur, best = scores[i][1], scores[best_idx][1]
            if isinstance(cur, float) and math.isnan(cur):
                continue
            if (isinstance(best, float) and math.isnan(best)) \
                    or self.metric.compare(cur, best) > 0:
                best_idx = i
        best_ep, best_score, _ = scores[best_idx]
        result = MetricEvaluatorResult(
            best_score=best_score,
            best_engine_params=best_ep,
            best_idx=best_idx,
            metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            engine_params_scores=scores)
        if self.output_path:
            self._save_best_json(best_ep)
        return result

    def _save_best_json(self, ep: EngineParams) -> None:
        """MetricEvaluator.saveEngineJson:193 — the deployable best variant."""
        try:
            with open(self.output_path, "w") as f:
                json.dump(ep.to_json_dict(), f, indent=2, sort_keys=True)
            logger.info("best engine params written to %s",
                        os.path.abspath(self.output_path))
        except OSError as e:
            logger.warning("cannot write %s: %s", self.output_path, e)
