"""Evaluation workflow: metric sweeps over engine params.

Parity map (reference file:line):
  * Evaluation            <- controller/Evaluation.scala:34-125
  * EngineParamsGenerator <- controller/EngineParamsGenerator.scala:30-46
  * MetricEvaluator       <- controller/MetricEvaluator.scala:185-263
    (evaluateBase:218, best selection:246-249, best.json:252)
  * prefix-memoized sweep <- controller/FastEvalEngine.scala:46-346 —
    rebuilt as CachedEvalRunner: datasource / preparator / per-algorithm
    train results are cached by params-JSON prefix across the sweep, the
    compilation-cache analog of FastEvalEngine's pipeline memoization

Beyond the reference: the DEVICE-BATCHED sweep. When every candidate in
the grid shares its non-algorithm params, the single algorithm supports
``sweep_eval`` (models/als_sweep vectorized k-fold x hyperparameter
training) and the metrics declare a device ``sweep_kind``, the whole
grid runs as a few large device programs — one compile per distinct
rank, folds realized as zero-weight masks over ONE shared data layout —
instead of the reference's P x K sequential trains. Anything outside
that contract falls back to the sequential loop unchanged
(``PIO_EVAL_VECTORIZE=0`` forces the fallback).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from predictionio_tpu.core.engine import Engine, evaluate_fold
from predictionio_tpu.core.metrics import Metric
from predictionio_tpu.core.params import EngineParams, params_to_json

logger = logging.getLogger("pio.evaluation")

#: set to "0" to force the sequential per-candidate loop
VECTORIZE_ENV = "PIO_EVAL_VECTORIZE"


class EngineParamsGenerator:
    """Supplies the list of EngineParams to sweep (EngineParamsGenerator.scala:30)."""

    engine_params_list: Sequence[EngineParams] = ()


class Evaluation:
    """Glue object tying an Engine to a Metric (Evaluation.scala:34).

    Subclass (declaring engine/metric as class attributes, the reference's
    `engineMetric =` style) or instantiate with engine + metric
    (+ other_metrics). The evaluator writes best.json
    (Evaluation.engineMetric_= sugar, :91-99).
    """

    # class-attribute declaration point for subclasses
    engine: Optional[Engine] = None
    metric: Optional[Metric] = None
    other_metrics: Sequence[Metric] = ()
    output_path: Optional[str] = "best.json"
    #: optional params list carried by the evaluation itself
    engine_params_list: Sequence[EngineParams] = ()

    def __init__(self, engine: Optional[Engine] = None,
                 metric: Optional[Metric] = None,
                 other_metrics: Optional[Sequence[Metric]] = None,
                 output_path: Optional[str] = "__default__"):
        # only override class-level declarations when explicitly given
        if engine is not None:
            self.engine = engine
        if metric is not None:
            self.metric = metric
        if other_metrics is not None:
            self.other_metrics = list(other_metrics)
        if output_path != "__default__":
            self.output_path = output_path

    @property
    def evaluator(self) -> "MetricEvaluator":
        return MetricEvaluator(self.metric, self.other_metrics,
                               self.output_path)

    def run(self, ctx, engine_params_list: Sequence[EngineParams]
            ) -> "MetricEvaluatorResult":
        return self.evaluator.evaluate(ctx, self.engine, engine_params_list)


@dataclasses.dataclass
class MetricEvaluatorResult:
    """MetricEvaluator.scala:64-110 — scores per params with the best pick.

    ``candidate_details`` (parallel to ``engine_params_scores``) carries
    per-candidate wall time and the compile group that trained it —
    persisted into ``evaluator_results_json`` so `pio eval` output and
    the dashboard can show where sweep time went. ``sweep`` summarizes
    the execution (mode, compile groups, device batch sizes).
    """

    best_score: float
    best_engine_params: EngineParams
    best_idx: int
    metric_header: str
    other_metric_headers: List[str]
    engine_params_scores: List[Tuple[EngineParams, float, List[float]]]
    candidate_details: List[dict] = dataclasses.field(default_factory=list)
    sweep: Optional[dict] = None

    def to_one_liner(self) -> str:
        return f"[{self.metric_header}] {self.best_score}"

    def to_json_dict(self) -> dict:
        return {
            "bestScore": self.best_score,
            "bestEngineParams": self.best_engine_params.to_json_dict(),
            "bestIdx": self.best_idx,
            "metricHeader": self.metric_header,
            "otherMetricHeaders": self.other_metric_headers,
            "engineParamsScores": [
                {"engineParams": ep.to_json_dict(), "score": s, "others": o}
                for ep, s, o in self.engine_params_scores],
            "candidates": self.candidate_details,
            "sweep": self.sweep,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), sort_keys=True)

    def to_html(self) -> str:
        rows = "".join(
            f"<tr><td>{i}</td><td>{s}</td><td><pre>{ep.to_json()}</pre></td></tr>"
            for i, (ep, s, _o) in enumerate(self.engine_params_scores))
        return (f"<html><body><h1>{self.metric_header}</h1>"
                f"<p>Best score: {self.best_score} "
                f"(params #{self.best_idx})</p>"
                f"<table border=1><tr><th>#</th><th>score</th>"
                f"<th>engine params</th></tr>{rows}</table></body></html>")


class CachedEvalRunner:
    """FastEvalEngine.scala:46-346 rebuilt: memoize shared pipeline prefixes.

    Within one sweep, engine params sharing a prefix reuse results:
      * data source (read_eval folds) keyed by datasource params
      * prepared data keyed by (datasource, preparator) params
      * trained models keyed by (datasource, preparator, single algo params)
    Jitted train functions additionally hit XLA's compilation cache when only
    numeric hyperparameters change.
    """

    def __init__(self, engine: Engine):
        self.engine = engine
        self._ds_cache: Dict[str, Any] = {}
        self._prep_cache: Dict[str, Any] = {}
        self._model_cache: Dict[str, Any] = {}

    @staticmethod
    def _key(*parts: Any) -> str:
        return json.dumps([_jsonable(p) for p in parts], sort_keys=True,
                          default=str)

    def eval(self, ctx, ep: EngineParams):
        ds_key = self._key(ep.data_source_name, ep.data_source_params)
        if ds_key not in self._ds_cache:
            data_source = self.engine._data_source(ep)
            self._ds_cache[ds_key] = list(data_source.read_eval(ctx))
        eval_data = self._ds_cache[ds_key]

        prep_key = self._key(ds_key, ep.preparator_name, ep.preparator_params)
        if prep_key not in self._prep_cache:
            preparator = self.engine._preparator(ep)
            self._prep_cache[prep_key] = [
                preparator.prepare(ctx, td) for td, _ei, _qa in eval_data]
        prepared = self._prep_cache[prep_key]

        named_algos = self.engine._algorithms(ep)
        serving = self.engine._serving(ep)

        results = []
        for fold_idx, ((td, eval_info, qa_pairs), pd) in enumerate(
                zip(eval_data, prepared)):
            models = []
            for (name, algo), (pname, algo_params) in zip(
                    named_algos, ep.algorithm_params_list):
                model_key = self._key(prep_key, fold_idx, pname, algo_params)
                if model_key not in self._model_cache:
                    self._model_cache[model_key] = algo.train(ctx, pd)
                models.append(self._model_cache[model_key])
            qpa = evaluate_fold(named_algos, models, serving, qa_pairs)
            results.append((eval_info, qpa))
        return results


def _jsonable(p: Any) -> Any:
    try:
        return params_to_json(p)
    except TypeError:
        return repr(p)


# ---------------------------------------------------------------------------
# Device-batched sweep plumbing
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EvalGrid:
    """What a DataSource hands the vectorized sweep instead of K
    materialized folds: the FULL eval data (engine-specific payload,
    e.g. rating columns) plus the fold count and per-query settings.
    Produced by an optional ``DataSource.read_eval_grid(ctx)``."""

    data: Any
    k_fold: int
    query_num: int = 10


def expand_param_grid(engine_params_list: Sequence[EngineParams],
                      grid_specs: Sequence[str]) -> List[EngineParams]:
    """Cross-product hyperparameter expansion for `pio eval --grid`.

    Each spec is ``name=v1,v2,...`` overriding a field of the (single)
    algorithm's params; the result is base-params x the full cross
    product, in deterministic order. Values parse as JSON scalars when
    possible (ints/floats/bools), else strings.
    """
    if not grid_specs:
        return list(engine_params_list)
    dims: List[Tuple[str, List[Any]]] = []
    for spec in grid_specs:
        name, sep, vals = spec.partition("=")
        name = name.strip()
        values = [v for v in vals.split(",") if v.strip()]
        if not sep or not name or not values:
            raise ValueError(
                f"--grid spec {spec!r}: expected name=v1,v2,...")
        if any(n == name for n, _ in dims):
            # last-spec-wins would silently drop half the grid
            raise ValueError(f"--grid field {name!r} specified twice")
        parsed = []
        for v in values:
            try:
                parsed.append(json.loads(v))
            except json.JSONDecodeError:
                parsed.append(v.strip())
        dims.append((name, parsed))
    out: List[EngineParams] = []
    for ep in engine_params_list:
        if len(ep.algorithm_params_list) != 1:
            raise ValueError(
                "--grid requires exactly one algorithm per EngineParams "
                f"(got {len(ep.algorithm_params_list)})")
        algo_name, algo_params = ep.algorithm_params_list[0]
        for f, _vals in dims:
            if not hasattr(algo_params, f):
                raise ValueError(
                    f"--grid field {f!r} is not a parameter of "
                    f"{type(algo_params).__name__}")
        for combo in itertools.product(*[vals for _n, vals in dims]):
            new_ap = dataclasses.replace(
                algo_params, **{n: v for (n, _), v in zip(dims, combo)})
            out.append(dataclasses.replace(
                ep, algorithm_params_list=[(algo_name, new_ap)]))
    return out


def sweep_kind_of(metric: Metric) -> Optional[str]:
    """The metric's device ``sweep_kind``, or None when it must stay on
    the sequential path.

    Guards against silent inheritance: a subclass that overrides
    ``calculate``/``calculate_point`` (custom math the device kernel
    knows nothing about) WITHOUT re-declaring ``sweep_kind`` in its own
    body would otherwise inherit the parent's kind and get the stock
    device computation instead of its override. The rule: ``sweep_kind``
    counts only if it is declared at or below the most-derived class
    that overrides the calculation methods.
    """
    cls = type(metric)
    kind_cls = next((k for k in cls.__mro__ if "sweep_kind" in k.__dict__),
                    None)
    if kind_cls is None or kind_cls.__dict__["sweep_kind"] is None:
        return None
    for klass in cls.__mro__:
        if klass is kind_cls:
            return kind_cls.__dict__["sweep_kind"]
        if "calculate" in klass.__dict__ \
                or "calculate_point" in klass.__dict__:
            return None       # customized math below the declaration
    return None


def _try_vectorized_sweep(ctx, engine: Engine,
                          engine_params_list: Sequence[EngineParams],
                          metric: Metric, other_metrics: Sequence[Metric]):
    """The device-batched sweep, when the grid fits its contract; None
    when it doesn't (the caller falls back to the sequential loop).

    Contract: every metric declares a ``sweep_kind``; every candidate
    shares datasource/preparator/serving params and carries exactly ONE
    algorithm (same name across the grid); the algorithm implements
    ``sweep_eval`` and the datasource ``read_eval_grid``. Structural
    mismatches return None cheaply (no jax import, no data read); real
    errors past that point propagate — a broken sweep must fail loudly,
    not silently retrain P x K times.
    """
    if os.environ.get(VECTORIZE_ENV, "1") == "0":
        return None
    all_metrics = [metric, *other_metrics]
    if any(sweep_kind_of(m) is None for m in all_metrics):
        return None
    eps = list(engine_params_list)
    shared = CachedEvalRunner._key(
        eps[0].data_source_name, eps[0].data_source_params,
        eps[0].preparator_name, eps[0].preparator_params,
        eps[0].serving_name, eps[0].serving_params)
    for ep in eps:
        if len(ep.algorithm_params_list) != 1:
            return None
        if CachedEvalRunner._key(
                ep.data_source_name, ep.data_source_params,
                ep.preparator_name, ep.preparator_params,
                ep.serving_name, ep.serving_params) != shared:
            return None
    algo_names = {ep.algorithm_params_list[0][0] for ep in eps}
    if len(algo_names) != 1:
        return None
    name, algo = engine._algorithms(eps[0])[0]
    if not hasattr(algo, "sweep_eval"):
        return None
    data_source = engine._data_source(eps[0])
    if not hasattr(data_source, "read_eval_grid"):
        return None

    from predictionio_tpu.obs.registry import default_registry
    from predictionio_tpu.obs.tracing import span

    registry = default_registry()
    with span("eval_split", registry):
        grid = data_source.read_eval_grid(ctx)
    algo_params = [ep.algorithm_params_list[0][1] for ep in eps]
    sweep = algo.sweep_eval(ctx, grid, algo_params, metric,
                            other_metrics=other_metrics, registry=registry)
    if sweep is None:      # the algorithm declined (unsupported combo)
        return None
    logger.info("vectorized eval sweep: %d candidates x %d folds in %d "
                "compile group(s)", len(eps), grid.k_fold,
                sweep["info"].get("compileGroups", 0))
    return sweep


class MetricEvaluator:
    """MetricEvaluator.scala:185 — score every engine params, pick the best."""

    def __init__(self, metric: Metric, other_metrics: Sequence[Metric] = (),
                 output_path: Optional[str] = "best.json"):
        self.metric = metric
        self.other_metrics = list(other_metrics)
        self.output_path = output_path

    def evaluate(self, ctx, engine: Engine,
                 engine_params_list: Sequence[EngineParams]
                 ) -> MetricEvaluatorResult:
        if not engine_params_list:
            raise ValueError("engine_params_list must not be empty")
        scores: List[Tuple[EngineParams, float, List[float]]] = []
        details: List[dict] = []
        sweep_info: Optional[dict] = None

        vec = _try_vectorized_sweep(ctx, engine, engine_params_list,
                                    self.metric, self.other_metrics)
        if vec is not None:
            for i, (ep, (score, others)) in enumerate(
                    zip(engine_params_list, vec["scores"])):
                scores.append((ep, score, list(others)))
                details.append({"index": i, **vec["details"][i]})
            sweep_info = vec["info"]
        else:
            from predictionio_tpu.obs.eval_stats import (
                eval_candidates_counter,
            )

            runner = CachedEvalRunner(engine)
            for i, ep in enumerate(engine_params_list):
                t0 = time.perf_counter()
                eval_data = runner.eval(ctx, ep)
                score = self.metric.calculate(ctx, eval_data)
                others = [m.calculate(ctx, eval_data)
                          for m in self.other_metrics]
                logger.info("engine params %d/%d: %s = %s",
                            i + 1, len(engine_params_list),
                            self.metric.header(), score)
                scores.append((ep, score, others))
                details.append({
                    "index": i, "group": "sequential",
                    "wallTimeS": round(time.perf_counter() - t0, 4)})
            eval_candidates_counter().inc(len(engine_params_list),
                                          mode="sequential")
            sweep_info = {"mode": "sequential", "compileGroups": None,
                          "batchSizes": []}

        import math

        # NaN scores (e.g. empty folds) can never win; if all are NaN the
        # first is reported so the caller still sees the failure
        best_idx = 0
        for i in range(1, len(scores)):
            cur, best = scores[i][1], scores[best_idx][1]
            if isinstance(cur, float) and math.isnan(cur):
                continue
            if (isinstance(best, float) and math.isnan(best)) \
                    or self.metric.compare(cur, best) > 0:
                best_idx = i
        best_ep, best_score, _ = scores[best_idx]
        result = MetricEvaluatorResult(
            best_score=best_score,
            best_engine_params=best_ep,
            best_idx=best_idx,
            metric_header=self.metric.header(),
            other_metric_headers=[m.header() for m in self.other_metrics],
            engine_params_scores=scores,
            candidate_details=details,
            sweep=sweep_info)
        if self.output_path:
            self._save_best_json(best_ep)
        return result

    def _save_best_json(self, ep: EngineParams) -> None:
        """MetricEvaluator.saveEngineJson:193 — the deployable best variant.

        Temp-write + rename: this file is what `pio deploy` reads, so a
        crash mid-write must leave either the previous best or nothing —
        never a torn JSON that a deploy then ships."""
        tmp = f"{self.output_path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(ep.to_json_dict(), f, indent=2, sort_keys=True)
            os.replace(tmp, self.output_path)
            logger.info("best engine params written to %s",
                        os.path.abspath(self.output_path))
        except OSError as e:
            logger.warning("cannot write %s: %s", self.output_path, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
