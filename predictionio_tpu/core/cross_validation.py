"""Shared k-fold cross-validation helpers.

The analog of the reference's e2 CommonHelperFunctions.splitData
(e2/src/main/scala/org/apache/predictionio/e2/evaluation/
CrossValidation.scala:36): fold membership by index modulo, shared by
every engine's readEval instead of hand-rolled per template.

The split exists in two shapes:

* ``split_data`` / ``k_fold`` — per-fold index/item views, the
  reference-parity API the sequential eval path consumes.
* ``fold_assignments`` / ``fold_masks`` — ONE vectorized pass emitting
  the fold id per data point (and boolean test-mask columns derived from
  it). The device-batched eval sweep trains every fold from a single
  shared data layout with test entries zero-weighted, so it needs fold
  membership as an array aligned with the data, not K index subsets.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple, TypeVar

import numpy as np

T = TypeVar("T")


def _check_k(k: int, n: int) -> None:
    if k < 1:
        raise ValueError(f"kFold must be >= 1, got {k}")
    if k > n:
        # index-mod-k membership would silently yield EMPTY test folds for
        # every fold >= n, and a sweep scored on an empty fold reports NaN
        # instead of the configuration error it actually is
        raise ValueError(
            f"kFold={k} exceeds the number of data points ({n}); "
            "every fold needs at least one test point")


def fold_assignments(k: int, n: int) -> np.ndarray:
    """int32 [n] fold id per data point (index mod k), validated once.

    The single source of truth for fold membership: ``split_data`` and the
    batched sweep's per-fold weight masks both derive from it, so the
    sequential and vectorized eval paths can never disagree on the split.
    """
    _check_k(k, n)
    return (np.arange(n, dtype=np.int64) % k).astype(np.int32)


def fold_masks(k: int, n: int) -> np.ndarray:
    """bool [k, n] — row f is the TEST mask of fold f (train = ~row).

    The mask-column view of ``fold_assignments``, built by one
    vectorized comparison instead of K index scans — for host-side
    consumers that want boolean columns. (The device-batched eval sweep
    itself packs the raw ``fold_assignments`` ids into its row layout
    and derives ``fold_ids != fold`` on device; both views share the
    same assignment, so they can never disagree.)
    """
    fold_of = fold_assignments(k, n)
    return fold_of[None, :] == np.arange(k, dtype=np.int32)[:, None]


def split_data(k: int, n: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield (train_indices, test_indices) per fold for n data points,
    fold membership = index mod k (CrossValidation.scala:36 parity)."""
    fold_of = fold_assignments(k, n)
    idx = np.arange(n)
    for fold in range(k):
        test_mask = fold_of == fold
        yield idx[~test_mask], idx[test_mask]


def k_fold(items: Sequence[T], k: int) -> Iterator[Tuple[List[T], List[T]]]:
    """Yield (train_items, test_items) per fold over a concrete sequence."""
    for train_idx, test_idx in split_data(k, len(items)):
        yield ([items[i] for i in train_idx],
               [items[i] for i in test_idx])
