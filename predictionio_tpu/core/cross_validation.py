"""Shared k-fold cross-validation helpers.

The analog of the reference's e2 CommonHelperFunctions.splitData
(e2/src/main/scala/org/apache/predictionio/e2/evaluation/
CrossValidation.scala:36): fold membership by index modulo, shared by
every engine's readEval instead of hand-rolled per template.
"""

from __future__ import annotations

from typing import Iterator, List, Sequence, Tuple, TypeVar

import numpy as np

T = TypeVar("T")


def split_data(k: int, n: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield (train_indices, test_indices) per fold for n data points,
    fold membership = index mod k (CrossValidation.scala:36 parity)."""
    if k < 1:
        raise ValueError(f"kFold must be >= 1, got {k}")
    idx = np.arange(n)
    for fold in range(k):
        test = idx[idx % k == fold]
        train = idx[idx % k != fold]
        yield train, test


def k_fold(items: Sequence[T], k: int) -> Iterator[Tuple[List[T], List[T]]]:
    """Yield (train_items, test_items) per fold over a concrete sequence."""
    for train_idx, test_idx in split_data(k, len(items)):
        yield ([items[i] for i in train_idx],
               [items[i] for i in test_idx])
