"""DASE component protocols.

Parity map (reference file:line):
  * DataSource  <- BaseDataSource (core/.../core/BaseDataSource.scala:34-55),
    PDataSource/LDataSource (controller/{PDataSource.scala:37,LDataSource.scala:38})
  * Preparator  <- BasePreparator.scala:33-45, PPreparator/LPreparator
  * Algorithm   <- BaseAlgorithm.scala:58-126 unifying LAlgorithm.scala:45,
    P2LAlgorithm.scala:46, PAlgorithm.scala:47 — one protocol; models are
    pytrees, "local vs distributed" is a property of the mesh, not the class
  * Serving     <- BaseServing.scala:31-54, LServing.scala:30
  * SanityCheck <- core/.../core/SanityCheck.scala:27-33
  * PersistentModel(+loader) <- controller/PersistentModel.scala:67-103

Component constructors take their params object (or nothing) — the Doer
convention (core/.../core/AbstractDoer.scala:29-69) resolved by signature
inspection instead of JVM reflection.
"""

from __future__ import annotations

import abc
import inspect
from typing import (Any, Generic, List, Optional, Sequence, Tuple, TypeVar)

TD = TypeVar("TD")   # training data
EI = TypeVar("EI")   # evaluation info
PD = TypeVar("PD")   # prepared data
Q = TypeVar("Q")     # query
P = TypeVar("P")     # prediction
A = TypeVar("A")     # actual
M = TypeVar("M")     # model


class SanityCheck(abc.ABC):
    """Data classes may self-validate during training (SanityCheck.scala:27)."""

    @abc.abstractmethod
    def sanity_check(self) -> None:
        """Raise if the data is invalid."""


def instantiate(cls: type, params: Any):
    """Doer.apply parity (AbstractDoer.scala:29-69): construct with the params
    object when the constructor accepts one, else no-arg. When no params were
    configured (None), a no-arg constructor is preferred — matching the
    reference's fallback to the zero-argument constructor."""
    try:
        sig = inspect.signature(cls.__init__)
        positional = [
            p for name, p in sig.parameters.items()
            if name not in ("self",) and p.kind in (
                inspect.Parameter.POSITIONAL_ONLY,
                inspect.Parameter.POSITIONAL_OR_KEYWORD)
        ]
    except (TypeError, ValueError):
        positional = []
    no_arg_ok = all(p.default is not inspect.Parameter.empty
                    for p in positional)
    if positional and not (params is None and no_arg_ok):
        return cls(params)
    return cls()


def params_class_of(cls: type) -> Optional[type]:
    """The component's declared params dataclass, if any.

    Resolution order: an explicit `params_class` attribute, then the type
    annotation of the constructor's first parameter.
    """
    explicit = getattr(cls, "params_class", None)
    if explicit is not None:
        return explicit
    try:
        import dataclasses
        import typing

        hints = typing.get_type_hints(cls.__init__)
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError, NameError):
        return None
    for name, p in sig.parameters.items():
        if name == "self":
            continue
        ann = hints.get(name)
        # only a declared params type counts: a dataclass or Params subclass
        # (primitive annotations like `int = 0` are construction defaults)
        from predictionio_tpu.core.params import Params as _Params

        if isinstance(ann, type) and (dataclasses.is_dataclass(ann)
                                      or issubclass(ann, _Params)):
            return ann
        return None
    return None


class DataSource(Generic[TD, EI, Q, A], abc.ABC):
    """Reads training and evaluation data from the event store."""

    @abc.abstractmethod
    def read_training(self, ctx) -> TD:
        """BaseDataSource.readTrainingBase (BaseDataSource.scala:43)."""

    def read_eval(self, ctx) -> Sequence[Tuple[TD, EI, Sequence[Tuple[Q, A]]]]:
        """K folds of (training data, eval info, (query, actual) pairs)
        (BaseDataSource.readEvalBase:55). Default: no eval data."""
        return []


class Preparator(Generic[TD, PD], abc.ABC):
    @abc.abstractmethod
    def prepare(self, ctx, training_data: TD) -> PD:
        """BasePreparator.prepareBase (BasePreparator.scala:42)."""


class IdentityPreparator(Preparator):
    """controller/IdentityPreparator.scala:32."""

    def prepare(self, ctx, training_data):
        return training_data


class Algorithm(Generic[PD, M, Q, P], abc.ABC):
    """One algorithm: train on the mesh, predict at serving time.

    The model M must be a picklable object; pytrees of (device or numpy)
    arrays are the norm and are converted to numpy at checkpoint time.
    """

    @abc.abstractmethod
    def train(self, ctx, prepared_data: PD) -> M:
        """BaseAlgorithm.trainBase (BaseAlgorithm.scala:69)."""

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P:
        """Single-query predict (BaseAlgorithm.predictBase:93)."""

    def batch_predict(self, model: M, queries: Sequence[Tuple[int, Q]]
                      ) -> List[Tuple[int, P]]:
        """Indexed batch predict for eval/batch scoring
        (BaseAlgorithm.batchPredictBase:81). Override with a vmap'd/jitted
        implementation where shapes allow."""
        return [(i, self.predict(model, q)) for i, q in queries]

    def warmup_query(self, model: M) -> Optional[Q]:
        """A representative query the deploy warm-swap ladder can drive
        through this algorithm's scorers before a release takes traffic
        (deploy/warm.py). Return None (the default) when no meaningful
        query can be synthesized from the model alone — warmup then
        falls back to the last live query or skips with a recorded
        reason. No reference counterpart: the reference has no warmup
        phase to feed."""
        return None

    def make_persistent_model(self, ctx, model_id: str, algo_params: Any,
                              model: M) -> Any:
        """BaseAlgorithm.makePersistentModel:111 — return value semantics:
          * the model object itself (default): checkpoint it in the model store
          * a PersistentModelManifest: the algorithm saved it itself
            (PersistentModel contract)
          * None: do not persist; retrain at deploy (PAlgorithm.scala:112
            default behavior)
        """
        if isinstance(model, PersistentModel):
            if model.save(model_id, algo_params, ctx):
                return PersistentModelManifest(_class_path(type(model)))
            return None
        return model


class Serving(Generic[Q, P], abc.ABC):
    def supplement(self, query: Q) -> Q:
        """BaseServing.supplementBase (BaseServing.scala:39)."""
        return query

    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P:
        """Combine per-algorithm predictions (BaseServing.serveBase:54)."""


class FirstServing(Serving):
    """controller/LFirstServing.scala:28."""

    def serve(self, query, predictions):
        return predictions[0]


class AverageServing(Serving):
    """controller/LAverageServing.scala:28 — numeric mean of predictions."""

    def serve(self, query, predictions):
        return sum(predictions) / len(predictions)


class PersistentModel(abc.ABC):
    """Custom model persistence contract (PersistentModel.scala:67-103).

    Models implementing this save themselves (e.g. to an orbax checkpoint
    dir) and are reloaded through their class `load` method at deploy.
    """

    @abc.abstractmethod
    def save(self, model_id: str, params: Any, ctx) -> bool:
        """Return True if saved; False falls back to retrain-on-deploy."""

    @classmethod
    @abc.abstractmethod
    def load(cls, model_id: str, params: Any, ctx) -> "PersistentModel":
        """PersistentModelLoader.apply parity."""


class PersistentModelManifest:
    """Stored in place of the model when custom persistence is used
    (core/.../workflow/PersistentModelManifest.scala:21)."""

    def __init__(self, class_path: str):
        self.class_path = class_path

    def __repr__(self):
        return f"PersistentModelManifest({self.class_path})"


def _class_path(cls: type) -> str:
    return f"{cls.__module__}:{cls.__qualname__}"


def load_class(path: str) -> type:
    """Resolve 'module.sub:Class' or 'module.sub.Class' to a class object."""
    import importlib

    if ":" in path:
        module_name, qualname = path.split(":", 1)
    else:
        module_name, _, qualname = path.rpartition(".")
    module = importlib.import_module(module_name)
    obj = module
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj
