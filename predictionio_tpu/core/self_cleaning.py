"""Self-cleaning data source: sliding event-window cleanup.

Parity with the reference SelfCleaningDataSource trait
(core/.../core/SelfCleaningDataSource.scala:42-324): a DataSource may declare
an EventWindow; `clean_persisted_events` then

  * drops events older than the window duration          (:160 cleanPersisted)
  * compresses each entity's `$set` chain into one `$set`
    carrying the folded properties                        (:106 compressProperties)
  * de-duplicates identical events                        (removeDuplicates)
  * rewrites the store atomically (write new, remove old) (:176 wipe)

`get_cleaned_events` applies the same rules read-only for training.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import logging
from typing import Iterable, List, Optional

from predictionio_tpu.data.aggregator import aggregate_properties_single
from predictionio_tpu.data.event import Event, UTC, millis

logger = logging.getLogger("pio.selfcleaning")


@dataclasses.dataclass
class EventWindow:
    """EventWindow parity: duration like "30 days"/"12 hours"; flags."""

    duration: Optional[str] = None
    remove_duplicates: bool = False
    compress_properties: bool = False

    def cutoff(self, now: Optional[_dt.datetime] = None
               ) -> Optional[_dt.datetime]:
        if not self.duration:
            return None
        now = now or _dt.datetime.now(tz=UTC)
        value, _, unit = self.duration.partition(" ")
        seconds_per = {"second": 1, "minute": 60, "hour": 3600, "day": 86400,
                       "week": 604800}
        unit = unit.rstrip("s") or "day"
        if unit not in seconds_per:
            raise ValueError(f"unknown EventWindow duration unit {unit!r}")
        return now - _dt.timedelta(seconds=float(value) * seconds_per[unit])


def _dedup_key(e: Event) -> tuple:
    return (e.event, e.entity_type, e.entity_id, e.target_entity_type,
            e.target_entity_id, e.properties.to_json(), millis(e.event_time))


def clean_events(events: Iterable[Event], window: EventWindow,
                 now: Optional[_dt.datetime] = None) -> List[Event]:
    """Apply window rules to an event list, newest semantics preserved."""
    events = list(events)
    cutoff = window.cutoff(now)
    if cutoff is not None:
        events = [e for e in events if e.event_time >= cutoff]
    if window.compress_properties:
        special, rest = [], []
        for e in events:
            (special if e.event in ("$set", "$unset", "$delete")
             else rest).append(e)
        compressed = []
        by_entity: dict = {}
        for e in special:
            by_entity.setdefault((e.entity_type, e.entity_id), []).append(e)
        for (etype, eid), evs in by_entity.items():
            pm = aggregate_properties_single(evs)
            if pm is None:
                continue  # entity deleted within the window
            compressed.append(Event(
                event="$set", entity_type=etype, entity_id=eid,
                properties=pm.fields, event_time=pm.last_updated,
                creation_time=pm.last_updated))
        events = sorted(compressed + rest, key=lambda e: millis(e.event_time))
    if window.remove_duplicates:
        seen = set()
        out = []
        for e in events:
            k = _dedup_key(e)
            if k not in seen:
                seen.add(k)
                out.append(e)
        events = out
    return events


class SelfCleaningDataSource:
    """Mixin for DataSources (SelfCleaningDataSource.scala:42).

    Subclasses set `event_window` and `app_name` (and optionally
    `channel_name`); call `get_cleaned_events()` for a cleaned read or
    `clean_persisted_events()` to rewrite the store in place.
    """

    event_window: Optional[EventWindow] = None
    app_name: str = ""
    channel_name: Optional[str] = None

    def get_cleaned_events(self, **find_kwargs) -> List[Event]:
        """getCleanedPEvents:77 parity (read-only)."""
        from predictionio_tpu.data.eventstore import EventStoreClient

        events = EventStoreClient.find(
            app_name=self.app_name, channel_name=self.channel_name,
            **find_kwargs)
        if self.event_window is None:
            return list(events)
        return clean_events(events, self.event_window)

    def clean_persisted_events(self) -> int:
        """cleanPersistedPEvents:160 — rewrite the store with cleaned events;
        returns the cleaned event count."""
        if self.event_window is None:
            return 0
        from predictionio_tpu.data.eventstore import resolve_app
        from predictionio_tpu.storage.registry import Storage

        app_id, channel_id = resolve_app(self.app_name, self.channel_name)
        store = Storage.get_events()
        old = list(store.find(app_id, channel_id))
        cleaned = clean_events(old, self.event_window)
        # crash-safe order: write the cleaned events under NEW ids first,
        # then delete the old rows — a crash in between leaves duplicates
        # (re-cleanable), never data loss
        fresh = [dataclasses.replace(e, event_id=None) for e in cleaned]
        if fresh:
            store.insert_batch(fresh, app_id, channel_id)
        for e in old:
            if e.event_id:
                store.delete(e.event_id, app_id, channel_id)
        logger.info("cleaned %s events for app %s", len(fresh), self.app_name)
        return len(fresh)
