"""The Engine: component registries + train / eval / prepare-deploy drivers.

Parity with the reference Engine (core/.../controller/Engine.scala:82-818):
  * registries of named D/P/A/S classes with params-from-JSON      (:82-155)
  * train: instantiate -> read -> sanity -> prepare -> per-algo train (:623-726)
  * prepare_deploy: restore/retrain models for serving              (:198-282)
  * eval: k-fold x algorithms matrix with supplement/serve          (:728-818)

The reference's makeSerializableModels/Kryo machinery disappears: every model
is picklable by construction (pytrees of numpy arrays after device_get).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from predictionio_tpu.core import params as params_mod
from predictionio_tpu.core.base import (
    Algorithm, DataSource, PersistentModel, PersistentModelManifest, Preparator,
    SanityCheck, Serving, instantiate, load_class, params_class_of,
)
from predictionio_tpu.core.params import EngineParams, engine_params_from_json

logger = logging.getLogger("pio.engine")

ClassMap = Union[type, Dict[str, type]]


def algo_model_id(instance_id: str, index: int, name: str) -> str:
    """Per-algorithm persistence key (Engine.scala:244 `id-ax-algoName`)."""
    return f"{instance_id}-ax{index}-{name}" if name else f"{instance_id}-ax{index}"


def _as_map(classes: ClassMap) -> Dict[str, type]:
    if isinstance(classes, dict):
        return dict(classes)
    return {"": classes}


def _pick(classes: Dict[str, type], name: str, what: str) -> type:
    if name in classes:
        return classes[name]
    if name == "" and len(classes) == 1:
        return next(iter(classes.values()))
    raise KeyError(f"unknown {what} name {name!r}; known: {sorted(classes)}")


def _sanity(obj: Any, what: str, skip: bool) -> None:
    """Engine.scala:650-706 — run SanityCheck when implemented."""
    if skip:
        return
    if isinstance(obj, SanityCheck):
        logger.debug("%s: running sanity check on %s", what, type(obj).__name__)
        obj.sanity_check()


@dataclasses.dataclass
class TrainResult:
    """Per-algorithm trained models plus the instantiated components."""

    models: List[Any]
    algorithms: List[Algorithm]
    serving: Serving
    engine_params: EngineParams


class Engine:
    """Engine.scala:82 — holds name->class maps for the DASE components."""

    def __init__(self,
                 data_source_classes: ClassMap,
                 preparator_classes: ClassMap,
                 algorithm_classes: ClassMap,
                 serving_classes: ClassMap):
        self.data_source_classes = _as_map(data_source_classes)
        self.preparator_classes = _as_map(preparator_classes)
        self.algorithm_classes = _as_map(algorithm_classes)
        self.serving_classes = _as_map(serving_classes)

    # -- component instantiation -------------------------------------------
    def _data_source(self, ep: EngineParams) -> DataSource:
        cls = _pick(self.data_source_classes, ep.data_source_name, "data source")
        return instantiate(cls, ep.data_source_params)

    def _preparator(self, ep: EngineParams) -> Preparator:
        cls = _pick(self.preparator_classes, ep.preparator_name, "preparator")
        return instantiate(cls, ep.preparator_params)

    def _algorithms(self, ep: EngineParams) -> List[Tuple[str, Algorithm]]:
        if not ep.algorithm_params_list:
            raise ValueError("EngineParams.algorithm_params_list must not be empty")
        out = []
        for name, algo_params in ep.algorithm_params_list:
            cls = _pick(self.algorithm_classes, name, "algorithm")
            out.append((name, instantiate(cls, algo_params)))
        return out

    def _serving(self, ep: EngineParams) -> Serving:
        cls = _pick(self.serving_classes, ep.serving_name, "serving")
        return instantiate(cls, ep.serving_params)

    # -- params parsing ------------------------------------------------------
    def engine_params_from_json(self, data: dict) -> EngineParams:
        """jValueToEngineParams parity, resolving params classes per component."""
        algo_params_classes = {
            name: params_class_of(cls)
            for name, cls in self.algorithm_classes.items()}
        # entries omitting "name" select the single algorithm (like _pick)
        if "" not in algo_params_classes and len(self.algorithm_classes) == 1:
            algo_params_classes[""] = params_class_of(
                next(iter(self.algorithm_classes.values())))
        ds_name = (data.get("datasource") or {}).get("name", "")
        prep_name = (data.get("preparator") or {}).get("name", "")
        serving_name = (data.get("serving") or {}).get("name", "")
        return engine_params_from_json(
            data,
            data_source_params_class=params_class_of(
                _pick(self.data_source_classes, ds_name, "data source")),
            preparator_params_class=params_class_of(
                _pick(self.preparator_classes, prep_name, "preparator")),
            algorithm_params_classes=algo_params_classes,
            serving_params_class=params_class_of(
                _pick(self.serving_classes, serving_name, "serving")),
        )

    # -- train (object Engine.train, Engine.scala:623) -----------------------
    def train(self, ctx, engine_params: EngineParams,
              skip_sanity_check: bool = False,
              stop_after_read: bool = False,
              stop_after_prepare: bool = False) -> TrainResult:
        data_source = self._data_source(engine_params)
        td = data_source.read_training(ctx)
        _sanity(td, "training data", skip_sanity_check)
        if stop_after_read:
            raise StopAfterReadInterruption(td)

        preparator = self._preparator(engine_params)
        pd = preparator.prepare(ctx, td)
        _sanity(pd, "prepared data", skip_sanity_check)
        if stop_after_prepare:
            raise StopAfterPrepareInterruption(pd)

        named_algos = self._algorithms(engine_params)
        models = []
        shared_ckpt = getattr(ctx, "checkpointer", None)
        for i, (name, algo) in enumerate(named_algos):
            logger.info("training algorithm %s (%s)",
                        name or "<default>", type(algo).__name__)
            if shared_ckpt is not None:
                # per-algorithm namespace: algorithm i must never resume
                # from algorithm j's snapshots
                ctx.checkpointer = shared_ckpt.scoped(
                    f"algo_{i}_{name or type(algo).__name__}")
            try:
                model = algo.train(ctx, pd)
            finally:
                if shared_ckpt is not None:
                    ctx.checkpointer = shared_ckpt
            _sanity(model, f"model of {name or type(algo).__name__}",
                    skip_sanity_check)
            models.append(model)
        return TrainResult(
            models=models,
            algorithms=[a for _, a in named_algos],
            serving=self._serving(engine_params),
            engine_params=engine_params)

    # -- model persistence (Engine.makeSerializableModels / prepareDeploy) ---
    def persist_models(self, ctx, model_id: str,
                       train_result: TrainResult) -> List[Any]:
        """Per-algo persistable representation (Engine.scala:284-311):
        model | PersistentModelManifest | None(retrain-at-deploy).

        Each algorithm gets a distinct id `<instance>-ax<i>-<name>` so
        multiple PersistentModel algorithms never collide
        (Engine.scala:244 keys custom-persisted models the same way).
        """
        out = []
        for i, ((name, algo_params), algo, model) in enumerate(zip(
                train_result.engine_params.algorithm_params_list,
                train_result.algorithms, train_result.models)):
            out.append(algo.make_persistent_model(
                ctx, algo_model_id(model_id, i, name), algo_params, model))
        return out

    def prepare_deploy(self, ctx, engine_params: EngineParams,
                       model_id: str, persisted: Sequence[Any]) -> TrainResult:
        """Engine.prepareDeploy:198 — restore each algorithm's model:
          * PersistentModelManifest -> class loader (:241-250)
          * None -> retrain from the event store (:210-228)
          * otherwise the checkpointed model itself
        """
        named_algos = self._algorithms(engine_params)
        # retrain ONLY the slots persisted as None (Engine.scala:211-227
        # reads+prepares once and calls trainBase only for the Unit slots)
        prepared = None
        if any(m is None for m in persisted):
            logger.info("some models are not persisted; retraining for deploy")
            data_source = self._data_source(engine_params)
            td = data_source.read_training(ctx)
            preparator = self._preparator(engine_params)
            prepared = preparator.prepare(ctx, td)
        models = []
        for i, ((name, algo_params), (_, algo), m) in enumerate(zip(
                engine_params.algorithm_params_list, named_algos, persisted)):
            if isinstance(m, PersistentModelManifest):
                cls = load_class(m.class_path)
                models.append(cls.load(
                    algo_model_id(model_id, i, name), algo_params, ctx))
            elif m is None:
                models.append(algo.train(ctx, prepared))
            else:
                models.append(m)
        return TrainResult(
            models=models,
            algorithms=[a for _, a in named_algos],
            serving=self._serving(engine_params),
            engine_params=engine_params)

    # -- eval (object Engine.eval, Engine.scala:728) -------------------------
    def eval(self, ctx, engine_params: EngineParams,
             skip_sanity_check: bool = True):
        """Returns [(EvalInfo, [(Q, P, A)])] per fold: train on each fold's
        training data, predict its queries through supplement/serve."""
        data_source = self._data_source(engine_params)
        eval_data = data_source.read_eval(ctx)
        preparator = self._preparator(engine_params)
        named_algos = self._algorithms(engine_params)
        serving = self._serving(engine_params)

        results = []
        for fold_idx, (td, eval_info, qa_pairs) in enumerate(eval_data):
            _sanity(td, f"fold {fold_idx} training data", skip_sanity_check)
            pd = preparator.prepare(ctx, td)
            models = [algo.train(ctx, pd) for _, algo in named_algos]
            qpa = evaluate_fold(named_algos, models, serving, qa_pairs)
            results.append((eval_info, qpa))
        return results

    def batch_eval(self, ctx, engine_params_list: Sequence[EngineParams]):
        """BaseEngine.batchEval:82 — default: eval per params."""
        return [(ep, self.eval(ctx, ep)) for ep in engine_params_list]


def evaluate_fold(named_algos, models, serving, qa_pairs):
    """The per-fold predict pipeline (Engine.scala:767-812): supplement each
    query, batch-predict per algorithm, align per query, serve.

    The reference aligns per-query predictions with zipWithUniqueId +
    union/groupByKey over RDDs (:777-794); here queries are indexed directly.
    """
    supplemented = [(i, serving.supplement(q))
                    for i, (q, _a) in enumerate(qa_pairs)]
    per_algo: List[Dict[int, Any]] = []
    for (name, algo), model in zip(named_algos, models):
        preds = dict(algo.batch_predict(model, supplemented))
        per_algo.append(preds)
    out = []
    for i, (q, a) in enumerate(qa_pairs):
        predictions = [preds[i] for preds in per_algo]
        out.append((q, serving.serve(q, predictions), a))
    return out


class StopAfterReadInterruption(Exception):
    """WorkflowParams.stopAfterRead debug stop (CreateWorkflow.scala parity)."""

    def __init__(self, training_data):
        super().__init__("stopped after read")
        self.training_data = training_data


class StopAfterPrepareInterruption(Exception):
    def __init__(self, prepared_data):
        super().__init__("stopped after prepare")
        self.prepared_data = prepared_data


class EngineFactory:
    """EngineFactory.scala:31 — a callable returning an Engine; referenced by
    dotted path in engine.json ("engineFactory")."""

    @classmethod
    def apply(cls) -> Engine:
        raise NotImplementedError

    def __call__(self) -> Engine:
        return self.apply()
