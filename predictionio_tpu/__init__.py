"""predictionio_tpu — a TPU-native ML server framework.

A ground-up JAX/XLA redesign of the capabilities of Apache PredictionIO
(reference: /root/reference, Scala/Spark): an event-collection REST server over
pluggable storage, a DASE engine abstraction (DataSource -> Preparator ->
Algorithm(s) -> Serving, plus Evaluation), a train workflow running sharded
JAX training over a TPU mesh, model checkpointing with engine-instance
metadata, a deployed query server with resident device arrays, batch
prediction, and a k-fold metric-evaluation workflow.

Layer map (mirrors SURVEY.md section 1, rebuilt TPU-first):
  L0 substrate   jax/XLA on a `jax.sharding.Mesh` (replaces Spark+Akka)
  L1 backends    predictionio_tpu.storage.* (sqlite default; replaces JDBC/HBase/ES)
  L2 data access predictionio_tpu.data.* (EventStore facades, aggregation)
  L3 controller  predictionio_tpu.core.* (DASE protocols)
  L4 workflow    predictionio_tpu.workflow.*
  L5 servers     predictionio_tpu.server.* (event/query/admin REST)
  L6 templates   predictionio_tpu.engines.* (recommendation/similarproduct/
                 classification/ecommerce)
  L7 CLI         predictionio_tpu.cli.* (`pio` command)
"""

__version__ = "0.1.0"
