"""Multi-host (multi-process) runtime initialization.

The single-controller analog of the reference's driver/executor control plane
(SURVEY.md section 2.9 P5): every host runs the same program,
`jax.distributed.initialize` wires them into one JAX runtime, and
`jax.devices()` then spans all hosts — meshes built afterwards schedule XLA
collectives over ICI within a slice and DCN across slices. Training scripts
call initialize_distributed() first (a no-op single-host).

Env contract (standard JAX):
  PIO_COORDINATOR_ADDRESS  host:port of process 0 (or JAX autodetects on TPU pods)
  PIO_NUM_PROCESSES        total process count
  PIO_PROCESS_ID           this process's index
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("pio.distributed")

_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Idempotent jax.distributed.initialize with PIO_* env fallbacks.

    On TPU pods with no explicit configuration, jax autodetects topology;
    single-host runs skip initialization entirely.
    """
    global _initialized
    if _initialized:
        return
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "PIO_COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else (
        int(os.environ["PIO_NUM_PROCESSES"])
        if "PIO_NUM_PROCESSES" in os.environ else None)
    process_id = process_id if process_id is not None else (
        int(os.environ["PIO_PROCESS_ID"])
        if "PIO_PROCESS_ID" in os.environ else None)

    if coordinator_address is None and num_processes is None:
        logger.info("single-process run; jax.distributed not initialized")
        _initialized = True
        return
    if (num_processes or 0) > 1:
        _enable_cpu_collectives(jax)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    logger.info("jax.distributed initialized: process %s/%s",
                jax.process_index(), jax.process_count())
    _initialized = True


def _enable_cpu_collectives(jax) -> None:
    """Multi-process runs on the CPU backend need a cross-process
    collectives transport: without one, the first computation over a
    cross-process mesh dies with XLA's "Multiprocess computations aren't
    implemented on the CPU backend". Newer jaxlibs ship a Gloo transport
    behind ``jax_cpu_collectives_implementation``; select it BEFORE the
    backend initializes (a no-op on TPU — the flag only affects the CPU
    client). Best-effort: older jax versions without the flag keep their
    previous behavior."""
    if os.environ.get("JAX_PLATFORMS", "").lower() not in ("", "cpu"):
        return
    for flag, value in (("jax_cpu_collectives_implementation", "gloo"),
                        ("jax_cpu_enable_gloo_collectives", True)):
        try:
            jax.config.update(flag, value)
            logger.info("CPU collectives transport: %s=%r", flag, value)
            return
        except (AttributeError, ValueError):
            continue


def resolve_worker(rank: Optional[int] = None,
                   size: Optional[int] = None) -> "tuple[int, int]":
    """This process's (rank, size) under the PIO_* process contract.

    Explicit arguments win; then the ``PIO_PROCESS_ID`` /
    ``PIO_NUM_PROCESSES`` env pair (the same contract
    `initialize_distributed` reads — offline batch workers honor it
    WITHOUT requiring the collective runtime, so a `pio batchpredict`
    shard fleet is just N processes with two env vars each); then an
    already-initialized multi-process jax runtime; else (0, 1).
    """
    if rank is not None and size is not None:
        if not 0 <= rank < size:
            raise ValueError(f"worker rank {rank} outside [0, {size})")
        return rank, size
    if "PIO_NUM_PROCESSES" in os.environ:
        size = int(os.environ["PIO_NUM_PROCESSES"])
        rank = int(os.environ.get("PIO_PROCESS_ID", "0"))
        if not 0 <= rank < size:
            raise ValueError(
                f"PIO_PROCESS_ID={rank} outside [0, PIO_NUM_PROCESSES={size})")
        return rank, size
    if _initialized:
        import jax

        return jax.process_index(), jax.process_count()
    return 0, 1


def worker_env(rank: int, size: int, base: Optional[dict] = None,
               trace_context=None) -> dict:
    """The environment for spawning one shard of a fleet run: the
    ``PIO_PROCESS_ID``/``PIO_NUM_PROCESSES`` contract plus the parent's
    trace context as ``PIO_TRACE_CONTEXT`` (obs/trace_context.py), so
    one trace id spans the parent and every shard it launches. The
    parent's context defaults to whatever trace is active at call time
    (``tracing.adopt`` the parent run first); pass ``trace_context``
    explicitly to pin one."""
    if not 0 <= rank < size:
        raise ValueError(f"worker rank {rank} outside [0, {size})")
    from predictionio_tpu.obs.trace_context import child_env
    from predictionio_tpu.obs.tracing import capture_context

    ctx = trace_context if trace_context is not None else capture_context()
    env = child_env(ctx, base)
    env["PIO_PROCESS_ID"] = str(rank)
    env["PIO_NUM_PROCESSES"] = str(size)
    return env


def contiguous_range(n: int, rank: int, size: int) -> "tuple[int, int]":
    """Row range [lo, hi) owned by `rank` of `size` over `n` rows:
    contiguous, disjoint, covering, balanced to within one row (the
    JdbcRDD-style partition bounds the sharded readers use)."""
    if size <= 0 or not 0 <= rank < size:
        raise ValueError(f"bad shard ({rank}, {size})")
    base, extra = divmod(max(0, n), size)
    lo = rank * base + min(rank, extra)
    return lo, lo + base + (1 if rank < extra else 0)


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def global_array_from_local(mesh, local: "object", axis: str = "data"):
    """Assemble a mesh-sharded global array from each process's local shard.

    The sharded event-log reader contract (SURVEY.md P2): each host loads its
    slice of the training data, and this stitches them into one global array
    sharded along `axis` without gathering to any single host.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(axis))
    return jax.make_array_from_process_local_data(sharding, local)
