"""Distributed substrate: meshes, collectives, multi-host init, sharded IO.

The rebuild's communication backend (SURVEY.md section 2.9 C1): where the
reference relies on Spark shuffle (netty RPC) between executors, all
cross-device communication here is XLA collectives over ICI within a slice
and DCN across slices, set up with `jax.distributed.initialize` and a
`jax.sharding.Mesh`. No custom transport exists or is needed.
"""

from predictionio_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, make_mesh, mesh_shape_from_conf,
)
from predictionio_tpu.parallel.distributed import (
    initialize_distributed, process_count, process_index,
)

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "make_mesh", "mesh_shape_from_conf",
    "initialize_distributed", "process_count", "process_index",
]
