"""Cross-process row exchange over the device interconnect.

The reference redistributes training rows by key with a Spark shuffle
(sort-based, spilled to disk, shipped executor-to-executor). The
TPU-native answer keeps the thesis of SURVEY.md §2.9 P4 — "the shuffle
becomes an XLA collective" — for the DATA path too: each process bins its
locally-loaded rows by destination, and ONE jitted `lax.all_to_all` over
a process-spanning mesh moves every bin to its owner, riding ICI/DCN
instead of a TCP shuffle service. Combined with the storage shard readers
(`find_columnar(shard=...)`, the JDBCPEvents.scala:89-101 partition
analog) this completes the partitioned input pipeline: no process ever
materializes the full event set.

Host-object collectives (`allgather_object`) cover the tiny metadata the
exchange needs (vocabularies, row counts, digests); they ride the same
jax runtime via `jax.experimental.multihost_utils`.
"""

from __future__ import annotations

import pickle
from typing import List, Sequence, Tuple

import numpy as np


def _exchange_mesh():
    """1-axis mesh with ONE device per process (the exchange granularity
    is processes; multi-device processes just funnel through their first
    chip — the host-side bin/unbin is per-process anyway)."""
    import jax
    from jax.sharding import Mesh

    per_proc = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    devs = [per_proc[p] for p in sorted(per_proc)]
    return Mesh(np.asarray(devs), axis_names=("proc",))


def allgather_object(obj) -> List:
    """Every process contributes one picklable object; all receive the
    list ordered by process index. Two fixed-shape device all-gathers
    (lengths, then padded bytes) — no host-side network path exists in
    the runtime, and none is needed."""
    import jax
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return [obj]
    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    sizes = multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64))
    cap = int(sizes.max())
    padded = np.zeros(cap, np.uint8)
    padded[:payload.size] = payload
    gathered = multihost_utils.process_allgather(padded)
    return [pickle.loads(gathered[p, :int(sizes[p, 0])].tobytes())
            for p in range(jax.process_count())]


def global_vocab(local_values: np.ndarray) -> np.ndarray:
    """Sorted union of every process's local distinct values — the
    deterministic global id assignment for partitioned loads (same ids on
    every process regardless of which shard saw which entity; the
    collective replacement for BiMap.scala:126's collect-to-driver)."""
    locals_ = allgather_object(np.unique(local_values))
    return np.unique(np.concatenate(locals_))


def exchange_rows(dest: np.ndarray, payload: np.ndarray) -> np.ndarray:
    """Redistribute host rows across processes by destination.

    dest: [n] int32 destination process per row. payload: [n, k] int32
    (bitcast other 4-byte dtypes through `.view(np.int32)`). Returns the
    [m, k] rows destined to THIS process, grouped by source process and
    preserving each source's local order within the group.

    Mechanics: bin rows by dest, pad bins to the global max (the exact
    per-(source, dest) counts ride one tiny metadata all-gather and
    delimit the unbinning — padding rows are simply never sliced in),
    and run one jitted shard_map all_to_all over the process mesh.
    Single-process: a pass-through reorder.
    """
    import jax

    payload = np.ascontiguousarray(payload, np.int32)
    n, k = payload.shape
    nproc = jax.process_count()
    order = np.argsort(dest, kind="stable")
    payload_s, dest_s = payload[order], dest[order]
    starts = np.searchsorted(dest_s, np.arange(nproc + 1))
    if nproc == 1:
        return payload_s

    me = jax.process_index()
    counts = np.diff(starts)                       # rows per destination
    all_counts = np.stack(allgather_object(counts))    # [P src, P dst]
    m = int(all_counts.max())

    send = np.zeros((nproc, m, k), np.int32)
    for d in range(nproc):
        lo, hi = int(starts[d]), int(starts[d + 1])
        send[d, :hi - lo] = payload_s[lo:hi]

    recv = _all_to_all(send)                       # [P src, m, k]
    rows = []
    for s in range(nproc):
        cnt = int(all_counts[s, me])
        rows.append(recv[s, :cnt])
    out = np.concatenate(rows) if rows else np.zeros((0, k), np.int32)
    assert out.shape[0] == int(all_counts[:, me].sum())
    return out


def _all_to_all(send: np.ndarray) -> np.ndarray:
    """One lax.all_to_all step: send[d] goes to process d; returns
    recv[s] = the block process s sent here."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from predictionio_tpu.ops.fn_cache import mesh_cached_fn

    mesh = _exchange_mesh()
    nproc, m, kk = send.shape

    def build():
        from predictionio_tpu.parallel.compat import shard_map

        def step(x):        # local block [1, nproc, m, kk]
            return jax.lax.all_to_all(
                x, "proc", split_axis=1, concat_axis=0)

        return jax.jit(shard_map(
            step, mesh=mesh, in_specs=P("proc"),
            out_specs=P(None, "proc"), check_vma=False))

    # cached per (mesh, shape): a per-call jit(shard_map(closure)) would
    # re-trace every exchange (the ops/fn_cache rule; Mesh hashes by
    # devices+axis names, so the freshly-built equal mesh still hits)
    run = mesh_cached_fn("shuffle_all_to_all", mesh, (nproc, m, kk), build)

    global_shape = (nproc, nproc, m, kk)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("proc")), send[None], global_shape)
    out = run(arr)
    # each process's addressable slice of the axis-1-sharded result is
    # exactly its received blocks [nproc, 1, m, kk]
    local = [s.data for s in out.addressable_shards]
    assert len(local) == 1
    return np.asarray(local[0]).reshape(nproc, m, kk)
