"""Device mesh construction.

Canonical axis names for the framework:
  * "data"  — batch/entity sharding (users, items, events, queries)
  * "model" — factor/parameter sharding (reserved for large-rank models)

Meshes default to 1D over all devices; engine variants request shapes via
runtime_conf (the sparkConf analog, workflow/context.py).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(shape: Optional[Sequence[int]] = None,
              axis_names: Optional[Sequence[str]] = None,
              devices=None):
    """Build a Mesh over the given (or all) devices.

    shape=None -> 1D ("data",) over every device. Multi-host: jax.devices()
    already spans all processes after initialize_distributed, so the same
    call shapes a global mesh whose collectives ride ICI intra-slice and DCN
    across slices.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
        axis_names = axis_names or (DATA_AXIS,)
    else:
        shape = tuple(shape)
        axis_names = tuple(axis_names) if axis_names else tuple(
            f"axis{i}" for i in range(len(shape)))
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, "
                         f"only {len(devices)} available")
    return Mesh(np.asarray(devices[:n]).reshape(shape), axis_names=axis_names)


def mesh_shape_from_conf(conf: dict) -> Tuple[Optional[list], Optional[list]]:
    """Parse runtime_conf {"mesh_shape": "4,2", "mesh_axes": "data,model"}."""
    shape = conf.get("mesh_shape")
    if isinstance(shape, str):
        shape = [int(x) for x in shape.split(",") if x]
    axes = conf.get("mesh_axes")
    if isinstance(axes, str):
        axes = [x for x in axes.split(",") if x]
    return shape, axes
