"""Named collective helpers for shard_map kernels.

Thin wrappers over XLA collectives (the C1 inventory of SURVEY.md section
2.9): psum / all_gather / reduce_scatter / ppermute ride ICI within a slice.
`ring_pass` implements the neighbor-exchange primitive used by ring
algorithms (ring all-reduce, ring attention-style pipelines): each device
forwards a block to the next device on the ring while processing its own.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def psum(x, axis: str):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis_name=axis)

def all_gather(x, axis: str, *, tiled: bool = True):
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str):
    return lax.psum_scatter(x, axis_name=axis, tiled=True)


def all_to_all(x, axis: str, split_axis: int = 0, concat_axis: int = 0):
    return lax.all_to_all(x, axis_name=axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ring_pass(x, axis: str, axis_size: int, reverse: bool = False):
    """Send x to the next device on the ring, receive from the previous.

    The building block of ring pipelines: combined with a lax.fori_loop a
    kernel can visit every peer's block in axis_size - 1 hops with only
    neighbor ICI traffic (no all-gather memory spike).
    """
    if reverse:
        perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]
    else:
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def ring_reduce(x, axis: str, axis_size: int, op=jnp.add):
    """All-reduce via explicit ring passes (didactic/reference path — prefer
    psum, which XLA lowers to the same ring on TPU)."""
    acc = x
    block = x

    def body(_, carry):
        acc, block = carry
        block = ring_pass(block, axis, axis_size)
        return op(acc, block), block

    acc, _ = lax.fori_loop(0, axis_size - 1, body, (acc, block))
    return acc
