"""JAX version compatibility shims for the parallel/kernel layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace (and grew the ``check_vma`` spelling of the
old ``check_rep`` flag) across the JAX versions this repo must run on.
Every kernel module imports it from here so the whole package tracks one
resolution order:

  1. ``jax.shard_map``                    (new API, ``check_vma``)
  2. ``jax.experimental.shard_map``       (older releases, ``check_rep``)

The wrapper translates the ``check_vma`` kwarg to ``check_rep`` when
falling back, so call sites can uniformly use the new spelling.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)

#: True when shard_map types device-varying values (the ``check_vma``
#: API); False on the experimental fallback, whose replication checker
#: cannot be satisfied by ``pcast_varying`` (an identity there) — bodies
#: that rely on the marking must disable the check instead.
HAS_VMA = "check_vma" in _PARAMS


def shard_map(f=None, /, **kwargs):
    """`jax.shard_map` with kwarg translation for older releases."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    if f is None:
        return lambda g: _shard_map(g, **kwargs)
    return _shard_map(f, **kwargs)


def pcast_varying(x, axis_names):
    """``jax.lax.pcast(x, names, to="varying")`` on releases that type
    device-varying values inside shard_map; identity on older releases,
    whose shard_map has no varying-axes type system to satisfy."""
    import jax

    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_names, to="varying")


__all__ = ["shard_map", "pcast_varying", "HAS_VMA"]

