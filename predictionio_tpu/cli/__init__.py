"""`pio` command-line interface (L7).

Rebuilds the reference's tools/console CLI surface
(tools/.../console/Console.scala:83-827) as a click application.
"""
