"""`pio` CLI entry point.

Command surface mirrors the reference console (Console.scala:134-623):
app/accesskey/channel management, train, deploy, eval, batchpredict,
eventserver, import/export, status. Training runs in-process (no
spark-submit analog; SURVEY.md section 7 design mapping).
"""

from __future__ import annotations

import json
import sys

import click

from predictionio_tpu import __version__


@click.group()
def cli():
    """predictionio_tpu — TPU-native ML server framework."""
    from predictionio_tpu.utils.config import honor_jax_platforms

    honor_jax_platforms()


@cli.command()
def version():
    """Print framework version (Console.scala:134)."""
    click.echo(__version__)


@cli.command()
@click.option("--fleet", "fleet_path", default=None,
              help="Show the merged fleet observability of a sharded run: "
                   "the <output>.fleet.json a batchpredict merge commits "
                   "(or the output path itself).")
def status(fleet_path):
    """Verify storage configuration (Console.scala:435, Management.scala:99);
    with --fleet, print a sharded run's merged per-process metric view."""
    if fleet_path:
        _print_fleet(fleet_path)
        return
    from predictionio_tpu.storage import Storage
    click.echo("[INFO] Inspecting predictionio_tpu installation...")
    click.echo(f"[INFO] Version {__version__}")
    try:
        Storage.verify_all_data_objects()
    except Exception as e:
        click.echo(f"[ERROR] Unable to connect to all storage backends: {e}")
        sys.exit(1)
    click.echo("[INFO] All storage backends are properly configured.")
    click.echo("[INFO] Your system is all ready to go.")


def _print_fleet(path):
    """The merged fleet view: per-process counters, exact fleet totals,
    and the trace ids spanning the run. A DIRECTORY path is read as a
    durable-telemetry root instead (obs/fleet.history_reader): the
    merged per-process tsdb stores, one summary line per series."""
    import os

    if os.path.isdir(path):
        _print_fleet_history(path)
        return
    if not path.endswith(".fleet.json") and not os.path.exists(path):
        path = f"{path}.fleet.json"
    elif os.path.isfile(f"{path}.fleet.json"):
        path = f"{path}.fleet.json"
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        click.echo(f"[ERROR] cannot read fleet view {path}: {e}")
        sys.exit(1)
    click.echo(f"[INFO] Fleet view {path}: "
               f"{len(doc.get('processes', []))} process(es) "
               f"{doc.get('processes')}")
    totals = doc.get("counterTotals", {})
    metrics = doc.get("metrics", {})
    for name in sorted(totals):
        click.echo(f"[INFO] {name} fleet total: {totals[name]:g}")
        for sample in metrics.get(name, {}).get("samples", []):
            labels = sample.get("labels", {})
            proc = labels.get("process", "?")
            rest = {k: v for k, v in labels.items() if k != "process"}
            suffix = f" {rest}" if rest else ""
            click.echo(f"[INFO]   process {proc}{suffix}: "
                       f"{sample.get('value'):g}")
    trace_ids = []
    for t in doc.get("traces", []):
        tid = t.get("traceId")
        if tid and tid not in trace_ids:
            trace_ids.append(tid)
    for tid in trace_ids:
        spans = [t for t in doc.get("traces", [])
                 if t.get("traceId") == tid]
        procs = sorted({t.get("process", "?") for t in spans})
        click.echo(f"[INFO] trace {tid}: {len(spans)} span(s) across "
                   f"processes {procs}")


def _print_fleet_history(root):
    """Fleet-wide history summary over a telemetry root: the merged
    per-process stores (one `process` label per service dir)."""
    from predictionio_tpu.obs import fleet

    reader = fleet.history_reader(root)
    by_process = {}
    for info in reader.series():
        if not info.points:
            continue
        proc = info.labels.get("process", "?")
        count, newest = by_process.get(proc, (0, 0))
        by_process[proc] = (count + 1,
                            max(newest, info.points[-1][0]))
    if not by_process:
        click.echo(f"[INFO] No telemetry stores under {root}.")
        return
    click.echo(f"[INFO] Telemetry root {root}: "
               f"{len(by_process)} process store(s)")
    import datetime as _dt

    for proc, (count, newest) in sorted(by_process.items()):
        when = _dt.datetime.fromtimestamp(newest / 1000.0)
        click.echo(f"[INFO]   {proc}: {count} series, newest sample "
                   f"{when.strftime('%Y-%m-%d %H:%M:%S')}")
    events = reader.events()[-10:]
    for _ts, e in events:
        click.echo(f"[INFO]   event {e.get('kind')} "
                   f"proc={e.get('process', '?')} "
                   f"trace={(e.get('traceId') or '-')[:12]}")


# ---------------------------------------------------------------------------
# app management (commands/App.scala:31-363)
# ---------------------------------------------------------------------------

@cli.group()
def app():
    """Manage apps (Console.scala:452-517)."""


@app.command("new")
@click.argument("name")
@click.option("--id", "app_id", type=int, default=0, help="Preferred app id.")
@click.option("--description", default=None)
@click.option("--access-key", default="", help="Use this access key instead of generating one.")
def app_new(name, app_id, description, access_key):
    from predictionio_tpu.storage import AccessKey, App, Storage
    apps = Storage.get_meta_data_apps()
    if apps.get_by_name(name):
        click.echo(f"[ERROR] App {name} already exists. Aborting.")
        sys.exit(1)
    new_id = apps.insert(App(id=app_id, name=name, description=description))
    if new_id is None:
        click.echo("[ERROR] Unable to create new app.")
        sys.exit(1)
    Storage.get_events().init_channel(new_id)
    key = Storage.get_meta_data_access_keys().insert(
        AccessKey(key=access_key, appid=new_id, events=()))
    if key is None:
        click.echo(f"[ERROR] Access key {access_key} already exists. Aborting.")
        Storage.get_events().remove_channel(new_id)
        Storage.get_meta_data_apps().delete(new_id)
        sys.exit(1)
    click.echo("[INFO] Created a new app:")
    click.echo(f"[INFO]         Name: {name}")
    click.echo(f"[INFO]           ID: {new_id}")
    click.echo(f"[INFO] Access Key: {key}")


@app.command("list")
def app_list():
    from predictionio_tpu.storage import Storage
    apps = Storage.get_meta_data_apps().get_all()
    keys = Storage.get_meta_data_access_keys()
    click.echo(f"[INFO] {'Name':<20} | {'ID':<4} | Access Key")
    for a in sorted(apps, key=lambda x: x.name):
        for k in keys.get_by_appid(a.id) or [None]:
            key = k.key if k else ""
            click.echo(f"[INFO] {a.name:<20} | {a.id:<4} | {key}")
    click.echo(f"[INFO] Finished listing {len(apps)} app(s).")


@app.command("show")
@click.argument("name")
def app_show(name):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(name)
    if a is None:
        click.echo(f"[ERROR] App {name} does not exist. Aborting.")
        sys.exit(1)
    click.echo(f"[INFO]     App Name: {a.name}")
    click.echo(f"[INFO]       App ID: {a.id}")
    click.echo(f"[INFO]  Description: {a.description or ''}")
    for k in Storage.get_meta_data_access_keys().get_by_appid(a.id):
        events = ",".join(k.events) if k.events else "(all)"
        click.echo(f"[INFO]   Access Key: {k.key} | {events}")
    for c in Storage.get_meta_data_channels().get_by_appid(a.id):
        click.echo(f"[INFO]      Channel: {c.name} ({c.id})")


@app.command("delete")
@click.argument("name")
@click.option("--force", "-f", is_flag=True)
def app_delete(name, force):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(name)
    if a is None:
        click.echo(f"[ERROR] App {name} does not exist. Aborting.")
        sys.exit(1)
    if not force and not click.confirm(
            f"Delete app {name} and ALL its data?"):
        click.echo("[INFO] Aborted.")
        return
    events = Storage.get_events()
    for c in Storage.get_meta_data_channels().get_by_appid(a.id):
        events.remove_channel(a.id, c.id)
        Storage.get_meta_data_channels().delete(c.id)
    events.remove_channel(a.id)
    for k in Storage.get_meta_data_access_keys().get_by_appid(a.id):
        Storage.get_meta_data_access_keys().delete(k.key)
    Storage.get_meta_data_apps().delete(a.id)
    click.echo(f"[INFO] App {name} deleted.")


@app.command("data-delete")
@click.argument("name")
@click.option("--channel", default=None)
@click.option("--all", "delete_all", is_flag=True)
@click.option("--force", "-f", is_flag=True)
def app_data_delete(name, channel, delete_all, force):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(name)
    if a is None:
        click.echo(f"[ERROR] App {name} does not exist. Aborting.")
        sys.exit(1)
    if not force and not click.confirm(f"Delete data of app {name}?"):
        click.echo("[INFO] Aborted.")
        return
    events = Storage.get_events()
    if delete_all or channel is None:
        events.remove_channel(a.id)
        events.init_channel(a.id)
        click.echo(f"[INFO] Deleted data of app {name} (default channel).")
    if channel is not None or delete_all:
        channels = Storage.get_meta_data_channels().get_by_appid(a.id)
        if channel is not None and channel not in [c.name for c in channels]:
            click.echo(f"[ERROR] Channel {channel} does not exist. Aborting.")
            sys.exit(1)
        for c in channels:
            if delete_all or c.name == channel:
                events.remove_channel(a.id, c.id)
                events.init_channel(a.id, c.id)
                click.echo(f"[INFO] Deleted data of channel {c.name}.")


@app.command("channel-new")
@click.argument("app_name")
@click.argument("channel_name")
def app_channel_new(app_name, channel_name):
    from predictionio_tpu.storage import Channel, Storage
    a = Storage.get_meta_data_apps().get_by_name(app_name)
    if a is None:
        click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
        sys.exit(1)
    try:
        cid = Storage.get_meta_data_channels().insert(
            Channel(id=0, name=channel_name, appid=a.id))
    except ValueError as e:
        click.echo(f"[ERROR] {e}")
        sys.exit(1)
    if cid is None:
        click.echo(f"[ERROR] Channel {channel_name} already exists.")
        sys.exit(1)
    Storage.get_events().init_channel(a.id, cid)
    click.echo(f"[INFO] Created channel {channel_name} ({cid}).")


@app.command("channel-delete")
@click.argument("app_name")
@click.argument("channel_name")
@click.option("--force", "-f", is_flag=True)
def app_channel_delete(app_name, channel_name, force):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(app_name)
    if a is None:
        click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
        sys.exit(1)
    matched = [c for c in Storage.get_meta_data_channels().get_by_appid(a.id)
               if c.name == channel_name]
    if not matched:
        click.echo(f"[ERROR] Channel {channel_name} does not exist.")
        sys.exit(1)
    if not force and not click.confirm(
            f"Delete channel {channel_name} and its data?"):
        click.echo("[INFO] Aborted.")
        return
    Storage.get_events().remove_channel(a.id, matched[0].id)
    Storage.get_meta_data_channels().delete(matched[0].id)
    click.echo(f"[INFO] Deleted channel {channel_name}.")


# ---------------------------------------------------------------------------
# accesskey management (commands/AccessKey.scala)
# ---------------------------------------------------------------------------

@cli.group()
def accesskey():
    """Manage access keys (Console.scala:554-592)."""


@accesskey.command("new")
@click.argument("app_name")
@click.option("--key", default="")
@click.option("--event", "events", multiple=True,
              help="Allowed event names (default: all).")
def accesskey_new(app_name, key, events):
    from predictionio_tpu.storage import AccessKey, Storage
    a = Storage.get_meta_data_apps().get_by_name(app_name)
    if a is None:
        click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
        sys.exit(1)
    k = Storage.get_meta_data_access_keys().insert(
        AccessKey(key=key, appid=a.id, events=tuple(events)))
    if k is None:
        click.echo("[ERROR] Unable to create access key.")
        sys.exit(1)
    click.echo(f"[INFO] Created new access key: {k}")


@accesskey.command("list")
@click.argument("app_name", required=False)
def accesskey_list(app_name):
    from predictionio_tpu.storage import Storage
    keys = Storage.get_meta_data_access_keys()
    if app_name:
        a = Storage.get_meta_data_apps().get_by_name(app_name)
        if a is None:
            click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
            sys.exit(1)
        listing = keys.get_by_appid(a.id)
    else:
        listing = keys.get_all()
    for k in listing:
        events = ",".join(k.events) if k.events else "(all)"
        click.echo(f"[INFO] {k.key} | app {k.appid} | {events}")
    click.echo(f"[INFO] Finished listing {len(listing)} access key(s).")


@accesskey.command("delete")
@click.argument("key")
def accesskey_delete(key):
    from predictionio_tpu.storage import Storage
    Storage.get_meta_data_access_keys().delete(key)
    click.echo(f"[INFO] Deleted access key {key}.")


# ---------------------------------------------------------------------------
# train / deploy / eval / batchpredict (commands/Engine.scala)
# ---------------------------------------------------------------------------

def _load_engine_variant(variant_path):
    """Read engine.json and resolve the factory + params
    (CreateWorkflow.scala:65 + WorkflowUtils.getEngine:53 parity)."""
    import os

    from predictionio_tpu.core.base import load_class

    if not os.path.exists(variant_path):
        click.echo(f"[ERROR] {variant_path} does not exist. Aborting.")
        sys.exit(1)
    with open(variant_path) as f:
        variant = json.load(f)
    factory_path = variant.get("engineFactory")
    if not factory_path:
        click.echo(f"[ERROR] {variant_path} has no engineFactory. Aborting.")
        sys.exit(1)
    factory = load_class(factory_path)
    engine = factory() if callable(factory) else factory.apply()
    engine_params = engine.engine_params_from_json(variant)
    return (engine, engine_params, factory_path,
            variant.get("id", "default"), variant)


@cli.command()
@click.option("--variant", "-v", default="engine.json",
              help="Engine variant JSON (engine.json).")
@click.option("--batch", default="", help="Batch label.")
@click.option("--skip-sanity-check", is_flag=True)
@click.option("--stop-after-read", is_flag=True)
@click.option("--stop-after-prepare", is_flag=True)
@click.option("--mesh-shape", default=None,
              help="Device mesh shape, e.g. 8 or 4,2.")
@click.option("--mesh-axes", default=None, help="Mesh axis names, e.g. data,model.")
@click.option("--checkpoint-dir", default=None,
              help="Mid-training checkpoint/resume directory.")
@click.option("--checkpoint-interval", default=10, type=int,
              help="Iterations/epochs between snapshots.")
def train(variant, batch, skip_sanity_check, stop_after_read,
          stop_after_prepare, mesh_shape, mesh_axes, checkpoint_dir,
          checkpoint_interval):
    """Train an engine instance (Console.scala:179, CoreWorkflow.runTrain)."""
    from predictionio_tpu.workflow import WorkflowParams, run_train

    engine, engine_params, factory_path, variant_id, _ = \
        _load_engine_variant(variant)
    # echo the resolved ALS training solver for every ALS-backed
    # algorithm (engine.json "solver" section + PIO_ALS_SOLVER /
    # PIO_ALS_BLOCK_SIZE overrides, README "Training kernel")
    from predictionio_tpu.utils.server_config import als_solver_config
    for algo_name, algo_params in engine_params.algorithm_params_list:
        if hasattr(algo_params, "solver"):
            try:
                mode, block = als_solver_config(
                    getattr(algo_params, "solver", None))
            except ValueError as e:
                click.echo(f"[ERROR] Algorithm '{algo_name}': {e}. "
                           "Aborting.")
                sys.exit(1)
            click.echo(f"[INFO] Algorithm '{algo_name}': ALS solver "
                       f"{mode} (block size {block}).")
    runtime_conf = {}
    if mesh_shape:
        runtime_conf["mesh_shape"] = mesh_shape
    if mesh_axes:
        runtime_conf["mesh_axes"] = mesh_axes
    if checkpoint_dir:
        runtime_conf["checkpoint_dir"] = checkpoint_dir
        runtime_conf["checkpoint_interval"] = str(checkpoint_interval)
    wp = WorkflowParams(
        batch=batch, skip_sanity_check=skip_sanity_check,
        stop_after_read=stop_after_read,
        stop_after_prepare=stop_after_prepare,
        runtime_conf=runtime_conf)
    from predictionio_tpu.core.engine import (
        StopAfterPrepareInterruption, StopAfterReadInterruption,
    )
    try:
        instance = run_train(engine, engine_params,
                             engine_factory=factory_path,
                             engine_variant=variant_id, workflow_params=wp)
    except StopAfterReadInterruption:
        click.echo("[INFO] Training interrupted by --stop-after-read.")
        return
    except StopAfterPrepareInterruption:
        click.echo("[INFO] Training interrupted by --stop-after-prepare.")
        return
    click.echo(f"[INFO] Training completed. Engine instance: {instance.id}")


@cli.command()
@click.option("--variant", "-v", default="engine.json")
@click.option("--ip", default="localhost")
@click.option("--port", default=8000, type=int)
@click.option("--engine-instance-id", default=None,
              help="Deploy a specific instance instead of the latest.")
@click.option("--release", "release_selector", default=None,
              help="Deploy a specific release (id, version number or vN) "
                   "from `pio releases`.")
@click.option("--feedback", is_flag=True, help="Record query/prediction events.")
@click.option("--event-server-app", default=None,
              help="App name for feedback events.")
@click.option("--accesskey", default=None,
              help="Key required for /stop, /reload and the deploy API.")
@click.option("--log-url", default=None,
              help="POST serving errors to this URL "
                   "(CreateServer remoteLog).")
@click.option("--log-prefix", default="",
              help="Prefix prepended to remote log payloads.")
def deploy(variant, ip, port, engine_instance_id, release_selector, feedback,
           event_server_app, accesskey, log_url, log_prefix):
    """Deploy the latest COMPLETED instance (Console.scala:260,
    CreateServer.scala:109), or a pinned release via --release."""
    from predictionio_tpu.deploy.releases import resolve_release
    from predictionio_tpu.server.query_server import run_query_server
    from predictionio_tpu.storage import Storage
    from predictionio_tpu.workflow.train import load_for_deploy

    engine, _, factory_path, variant_id, _vj = _load_engine_variant(variant)
    instances = Storage.get_meta_data_engine_instances()
    release = None
    if release_selector:
        release = resolve_release(Storage.get_meta_data_releases(),
                                  factory_path, "1", variant_id,
                                  release_selector)
        if release is None:
            click.echo(f"[ERROR] Release {release_selector} not found "
                       "(see `pio releases`). Aborting.")
            sys.exit(1)
        instance = instances.get(release.instance_id)
        if instance is None or instance.status != "COMPLETED":
            click.echo(f"[ERROR] Release v{release.version} points at "
                       f"instance {release.instance_id}, which is not "
                       "deployable. Aborting.")
            sys.exit(1)
    elif engine_instance_id:
        instance = instances.get(engine_instance_id)
        if instance is None or instance.status != "COMPLETED":
            click.echo(f"[ERROR] Engine instance {engine_instance_id} is not "
                       "deployable. Aborting.")
            sys.exit(1)
    else:
        instance = instances.get_latest_completed(
            factory_path, "1", variant_id)
        if instance is None:
            click.echo("[ERROR] No COMPLETED engine instance found. "
                       "Run `pio train` first. Aborting.")
            sys.exit(1)
    if release is None:
        release = _release_of_instance(factory_path, variant_id, instance.id)
    click.echo(f"[INFO] Deploying engine instance {instance.id}"
               + (f" (release v{release.version})" if release else "")
               + f" at {ip}:{port}")
    # online fold-in knobs: env > engine.json "foldin" > server.json
    from predictionio_tpu.utils.server_config import (
        foldin_config, scorer_config, telemetry_config,
    )
    fic = foldin_config((_vj or {}).get("foldin"))
    # durable telemetry rides the same chain (README "Fleet console")
    tcfg = telemetry_config((_vj or {}).get("telemetry"))
    # scoring-kernel knobs ride the same chain (README "Scoring kernel");
    # echoed like the ALS-solver line so the operator sees what the box
    # will actually serve with
    scfg = scorer_config((_vj or {}).get("scorer"))
    if scfg.mode == "exact":
        click.echo("[INFO] Scoring kernel exact (fused modes via "
                   'engine.json {"scorer": {"mode": ...}} or '
                   "PIO_SCORER_MODE)")
    else:
        click.echo(f"[INFO] Scoring kernel {scfg.mode} (tile "
                   f"{scfg.tile_items} items"
                   + (f", shortlist {scfg.shortlist}"
                      if scfg.mode == "twostage" else "")
                   + f", parity floor recall@10 >= {scfg.min_recall:g})")
    if fic.enabled:
        click.echo(f"[INFO] Online fold-in enabled: apply interval "
                   f"{fic.apply_interval_s:g}s, max pending "
                   f"{fic.max_pending} rows")
    else:
        click.echo("[INFO] Online fold-in disabled (enable via engine.json "
                   '{"foldin": {"enabled": true}} or PIO_FOLDIN=1)')
    result, ctx = load_for_deploy(engine, instance)
    run_query_server(engine, result, instance, ctx, ip=ip, port=port,
                     feedback=feedback, feedback_app_name=event_server_app,
                     access_key=accesskey, log_url=log_url,
                     log_prefix=log_prefix, release=release,
                     foldin_config=fic, scorer_config=scfg,
                     telemetry_config=tcfg)


@cli.command()
@click.option("--tenant", "-t", "tenant_specs", multiple=True, required=True,
              help="NAME=VARIANT_PATH, repeatable: co-host the latest "
                   "COMPLETED instance of each variant as tenant NAME.")
@click.option("--ip", default="localhost")
@click.option("--port", default=8800, type=int)
@click.option("--accesskey", default=None,
              help="Key guarding every tenant's /stop, /reload and "
                   "deploy API.")
def multiserve(tenant_specs, ip, port, accesskey):
    """Serve N engine variants from ONE process under one device-memory
    budget (server/multitenant.py): per-tenant routes at
    /t/NAME/queries.json, LRU warm eviction/reload under
    PIO_MT_DEVICE_BUDGET_BYTES, per-tenant int8/bf16 scorer residency,
    and SLO-burn admission control."""
    from predictionio_tpu.server.multitenant import (
        TenantSpec, run_multitenant_server,
    )
    from predictionio_tpu.storage import Storage
    from predictionio_tpu.utils.server_config import (
        foldin_config, scorer_config,
    )
    from predictionio_tpu.workflow.train import load_for_deploy

    specs = []
    instances = Storage.get_meta_data_engine_instances()
    for entry in tenant_specs:
        name, sep, variant_path = entry.partition("=")
        if not sep or not name or not variant_path:
            click.echo(f"[ERROR] --tenant wants NAME=VARIANT_PATH, got "
                       f"{entry!r}. Aborting.")
            sys.exit(1)
        engine, _, factory_path, variant_id, _vj = \
            _load_engine_variant(variant_path)
        instance = instances.get_latest_completed(
            factory_path, "1", variant_id)
        if instance is None:
            click.echo(f"[ERROR] Tenant {name!r}: no COMPLETED engine "
                       f"instance for {variant_path}. Run `pio train` "
                       "first. Aborting.")
            sys.exit(1)
        release = _release_of_instance(factory_path, variant_id, instance.id)
        scfg = scorer_config((_vj or {}).get("scorer"))
        result, ctx = load_for_deploy(engine, instance)
        click.echo(f"[INFO] Tenant {name!r}: instance {instance.id}"
                   + (f" (release v{release.version})" if release else "")
                   + f", scorer {scfg.mode}")
        specs.append(TenantSpec(
            name=name, engine=engine, train_result=result,
            instance=instance, ctx=ctx, release=release,
            scorer_config=scfg,
            foldin_config=foldin_config((_vj or {}).get("foldin")),
            slo=(_vj or {}).get("slo")))
    click.echo(f"[INFO] Hosting {len(specs)} tenant(s) at {ip}:{port}")
    run_multitenant_server(specs, ip=ip, port=port, access_key=accesskey)


@cli.command()
@click.option("--variant", "-v", default="engine.json")
@click.option("--ip", default="localhost")
@click.option("--port", default=None, type=int,
              help="Router port (default PIO_ROUTER_PORT / server.json).")
@click.option("--replicas", default=None, type=int,
              help="Query-server replicas to spawn (default "
                   "PIO_ROUTER_REPLICAS / server.json).")
@click.option("--replica-url", "replica_urls", multiple=True,
              help="Front an EXISTING replica instead of spawning "
                   "(repeatable); disables the spawner.")
@click.option("--accesskey", default=None,
              help="Key forwarded to spawned replicas' deploy APIs.")
def router(variant, ip, port, replicas, replica_urls, accesskey):
    """Serve a replicated fleet behind one router (server/router.py):
    spawn N `pio deploy` replicas via the worker-env contract (one
    trace id spans router -> replica -> device), spread queries with
    the error-diffusion splitter, sequence fleet cutovers, and
    autoscale on the SLO burn signal when server.json enables it."""
    import os
    import subprocess

    from predictionio_tpu.server.router import run_router
    from predictionio_tpu.utils.server_config import router_config

    cfg = router_config()
    if port is not None:
        cfg.port = port
    if replicas is not None:
        cfg.replicas = max(1, replicas)

    spawn = None
    if not replica_urls:
        from predictionio_tpu.parallel.distributed import worker_env

        def spawn(rank):
            """One replica = one `pio deploy` subprocess on
            base_port + rank, carrying the router's trace context and
            the PIO_PROCESS_ID/PIO_NUM_PROCESSES contract."""
            from predictionio_tpu.server.router import ReplicaHandle

            port_r = cfg.base_port + rank
            argv = [sys.executable, "-m", "predictionio_tpu.cli.main",
                    "deploy", "--variant", variant, "--ip", ip,
                    "--port", str(port_r)]
            if accesskey:
                argv += ["--accesskey", accesskey]
            env = worker_env(rank, max(cfg.replicas, rank + 1),
                             base=dict(os.environ))
            proc = subprocess.Popen(argv, env=env)
            click.echo(f"[INFO] Spawned replica {rank} (pid {proc.pid}) "
                       f"on {ip}:{port_r}")
            return ReplicaHandle(rank=rank,
                                 url=f"http://{ip}:{port_r}",
                                 proc=proc)

    click.echo(f"[INFO] Router starting at {ip}:{cfg.port} over "
               + (f"{len(replica_urls)} existing replica(s)"
                  if replica_urls else f"{cfg.replicas} replica(s)"))
    run_router(config=cfg, ip=ip, spawn=spawn,
               replica_urls=replica_urls)


@cli.command()
@click.option("--scenario", "-s", "scenario_path", default=None,
              help="Scenario JSON (loadtest/scenario.py schema); "
                   "omit to run the built-in example scenario.")
@click.option("--example", "show_example", is_flag=True,
              help="Print an example scenario file and exit.")
@click.option("--dir", "workdir", default=None,
              help="Fleet working directory (default: a temp dir, "
                   "removed afterwards).")
@click.option("--report", "report_path", default=None,
              help="Write the verdict JSON here (default "
                   "PIO_LOADTEST_REPORT_DIR/<scenario>.json when the "
                   "knob is set, else stdout only).")
@click.option("--json", "as_json", is_flag=True,
              help="Print the full report JSON instead of the summary.")
def loadtest(scenario_path, show_example, workdir, report_path, as_json):
    """Storm a full in-process fleet (loadtest/) with synthetic mixed
    traffic — events, queries, feedback — under a declarative scenario
    (Zipfian population, diurnal arrivals, injected incidents) and
    assert the runtime invariants live: no dropped acks, exactly-once
    ingest by post-run audit, one LIVE release, freshness SLO held.
    Exit status is the verdict."""
    import os
    import tempfile

    from predictionio_tpu.loadtest.scenario import (
        Scenario, ScenarioError, example_scenario,
    )

    if show_example:
        click.echo(json.dumps(example_scenario(), indent=2, sort_keys=True))
        return

    try:
        if scenario_path:
            sc = Scenario.load(scenario_path)
        else:
            sc = Scenario.from_dict(example_scenario())
    except ScenarioError as e:
        click.echo(f"[ERROR] bad scenario: {e}")
        sys.exit(1)

    from predictionio_tpu.loadtest.fleet import LocalFleet, MultiTenantFleet
    from predictionio_tpu.loadtest.simulator import (
        run_storm, run_tenant_storm, storm_report_json,
    )
    from predictionio_tpu.utils.server_config import loadtest_config

    knobs = loadtest_config()
    knobs.apply(sc)

    tmp = None
    if workdir is None:
        tmp = tempfile.TemporaryDirectory(prefix="pio-loadtest-")
        workdir = tmp.name
    try:
        if sc.tenants:
            click.echo(
                f"[INFO] Multi-tenant storm '{sc.name}': "
                f"{len(sc.tenants)} tenant(s) "
                f"[{', '.join(t.name for t in sc.tenants)}] "
                f"duration={sc.duration_s:g}s rate={sc.base_rate:g}/s "
                f"incidents={len(sc.incidents)}")
            fleet = MultiTenantFleet(workdir, sc.tenants)
            try:
                fleet.start()
                report = run_tenant_storm(sc, fleet)
            finally:
                fleet.stop()
        else:
            click.echo(
                f"[INFO] Storm '{sc.name}': population={sc.population} "
                f"duration={sc.duration_s:g}s rate={sc.base_rate:g}/s "
                f"replicas={sc.replicas} partitions={sc.partitions} "
                f"backend={sc.backend} incidents={len(sc.incidents)}")
            fleet = LocalFleet(workdir, replicas=sc.replicas,
                               partitions=sc.partitions,
                               backend=sc.backend)
            try:
                fleet.start()
                report = run_storm(sc, fleet)
            finally:
                fleet.stop()
    finally:
        if tmp is not None:
            tmp.cleanup()

    if report_path is None and knobs.report_dir:
        os.makedirs(knobs.report_dir, exist_ok=True)
        report_path = os.path.join(knobs.report_dir, f"{sc.name}.json")
    if report_path:
        tmp_report = f"{report_path}.tmp"
        with open(tmp_report, "w") as f:
            f.write(storm_report_json(report) + "\n")
        os.replace(tmp_report, report_path)
        click.echo(f"[INFO] Report written to {report_path}")

    if as_json:
        click.echo(storm_report_json(report))
    else:
        for lane, res in sorted(report.get("lanes", {}).items()):
            click.echo(
                f"[INFO] lane {lane}: offered={res['offered']} "
                f"acked={res['acked']} failed={res['failed']} "
                f"p99={res['ack_p99_ms']:.1f}ms")
        for name, res in sorted(report.get("tenants", {}).items()):
            click.echo(
                f"[INFO] tenant {name}: offered={res['offered']} "
                f"acked={res['acked']} failed={res['failed']} "
                f"rejected={res['rejections']} "
                f"p99={res['ack_p99_ms']:.1f}ms")
        for inv in report["invariants"]:
            mark = "ok " if inv["ok"] else "FAIL"
            click.echo(f"[{mark.upper().strip()}] {inv['name']}: "
                       f"{inv['detail']}")
    if not report["ok"]:
        click.echo("[ERROR] storm verdict: INVARIANT VIOLATED")
        sys.exit(1)
    arrivals = report.get(
        "arrivals",
        sum(r["offered"] for r in report.get("tenants", {}).values()))
    click.echo(f"[INFO] storm verdict: OK "
               f"({arrivals} arrivals, "
               f"{report['wall_s']:.1f}s wall)")


def _release_of_instance(engine_id, variant_id, instance_id):
    """The release manifest registered for an instance, if any (pre-
    release-registry instances deploy fine without one)."""
    from predictionio_tpu.storage import Storage

    try:
        for r in Storage.get_meta_data_releases().get_for_variant(
                engine_id, "1", variant_id):
            if r.instance_id == instance_id:
                return r
    except Exception:
        pass
    return None


@cli.command()
@click.option("--variant", "-v", default="engine.json")
@click.option("--status", "status_filter", default=None,
              help="Only releases in this status (REGISTERED, CANARY, "
                   "LIVE, RETIRED, ROLLED_BACK).")
def releases(variant, status_filter):
    """List release manifests for an engine variant (deploy/ registry)."""
    from predictionio_tpu.storage import Storage

    engine, _, factory_path, variant_id, _vj = _load_engine_variant(variant)
    listing = Storage.get_meta_data_releases().get_for_variant(
        factory_path, "1", variant_id)
    if status_filter:
        listing = [r for r in listing if r.status == status_filter.upper()]
    click.echo(f"[INFO] {'Ver':<5} | {'Status':<11} | "
               f"{'Instance':<32} | {'Created':<20} | Model")
    for r in listing:
        size = (f"{r.model_size_bytes / 1024:.0f}KiB"
                if r.model_size_bytes else "-")
        digest = r.model_digest[:12] if r.model_digest else "-"
        click.echo(f"[INFO] v{r.version:<4} | {r.status:<11} | "
                   f"{r.instance_id:<32} | "
                   f"{r.created_time.strftime('%Y-%m-%d %H:%M:%S'):<20} | "
                   f"{digest} {size}")
    click.echo(f"[INFO] Finished listing {len(listing)} release(s).")


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=8000, type=int)
@click.option("--accesskey", default=None)
def rollback(ip, port, accesskey):
    """Roll a live query server back to its previous release
    (POST /rollback.json against the deploy API)."""
    import urllib.error
    import urllib.request

    url = f"http://{ip}:{port}/rollback.json"
    if accesskey:
        url += f"?accessKey={accesskey}"
    try:
        with urllib.request.urlopen(
                urllib.request.Request(url, method="POST"),
                timeout=60) as r:
            out = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            message = json.loads(e.read().decode()).get("message", str(e))
        except Exception:
            message = str(e)
        click.echo(f"[ERROR] Rollback failed: {message}")
        sys.exit(1)
    except Exception as e:
        click.echo(f"[ERROR] Unable to reach query server: {e}")
        sys.exit(1)
    version = out.get("releaseVersion")
    click.echo(f"[INFO] {out.get('message', 'Rolled back')}: now serving "
               f"instance {out.get('engineInstanceId')}"
               + (f" (release v{version})" if version else ""))


def _parse_duration_s(text):
    """'30m' / '2h' / '45s' / '1d' / plain seconds -> float seconds."""
    text = str(text).strip().lower()
    units = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
    try:
        if text and text[-1] in units:
            return float(text[:-1]) * units[text[-1]]
        return float(text)
    except ValueError:
        raise click.BadParameter(
            f"{text!r} is not a duration (try 30m, 2h, 45s)")


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=8000, type=int)
@click.option("--trace-id", "trace_id", default=None,
              help="Only spans of this trace id.")
@click.option("--limit", type=int, default=20,
              help="Most recent N trace records (default 20).")
@click.option("--since", "since", default=None, metavar="30m",
              help="Only records newer than this (e.g. 45s, 30m, 2h) — "
                   "reaches back through the rings a restart reloaded "
                   "from the durable telemetry store.")
@click.option("--events", "show_events", is_flag=True,
              help="Also print lifecycle events (deploys, swaps, "
                   "fold-in applies, canary verdicts, SLO breaches).")
@click.option("--json", "as_json", is_flag=True,
              help="Raw /debug/traces.json body.")
def traces(ip, port, trace_id, limit, since, show_events, as_json):
    """Read a live server's flight recorder (GET /debug/traces.json):
    the bounded ring of recent traces + lifecycle events. Works against
    any server in the fleet (event server, query server, admin,
    dashboard)."""
    import urllib.parse
    import urllib.request

    params = {"limit": str(limit)}
    if trace_id:
        params["traceId"] = trace_id
    if since:
        params["sinceS"] = str(_parse_duration_s(since))
    url = (f"http://{ip}:{port}/debug/traces.json?"
           + urllib.parse.urlencode(params))
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            doc = json.loads(r.read().decode())
    except Exception as e:
        click.echo(f"[ERROR] Unable to read {url}: {e}")
        sys.exit(1)
    if as_json:
        click.echo(json.dumps(doc, indent=1, sort_keys=True))
        return
    for t in doc.get("traces", []):
        spans = " ".join(f"{k}={v * 1e3:.1f}ms"
                         for k, v in (t.get("spans") or {}).items())
        click.echo(f"[INFO] {t.get('traceId', '?')[:12]} "
                   f"{t.get('name')} {t.get('durationSec', 0) * 1e3:.1f}ms "
                   f"[{t.get('status')}] proc={t.get('process')}"
                   + (f" | {spans}" if spans else ""))
    if show_events:
        for e in doc.get("events", []):
            tid = (e.get("traceId") or "-")[:12]
            rest = {k: v for k, v in e.items()
                    if k not in ("kind", "ts", "traceId", "process")}
            click.echo(f"[INFO] event {e.get('kind')} trace={tid} {rest}")
    click.echo(f"[INFO] {len(doc.get('traces', []))} trace record(s), "
               f"{len(doc.get('events', []))} lifecycle event(s).")


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=8000, type=int)
@click.option("--accesskey", default=None)
@click.option("--seconds", type=float, default=2.0,
              help="Capture window (capped server-side at 60s).")
@click.option("--dir", "outdir", default=None,
              help="Trace output directory (server-side path; default a "
                   "fresh temp dir).")
def profile(ip, port, accesskey, seconds, outdir):
    """Capture a bounded on-demand device profile from a live query
    server (POST /debug/profile): a jax.profiler trace plus the
    per-compile-family dispatch-time attribution table."""
    import urllib.error
    import urllib.request

    url = f"http://{ip}:{port}/debug/profile"
    if accesskey:
        url += f"?accessKey={accesskey}"
    body = json.dumps({"seconds": seconds,
                       **({"dir": outdir} if outdir else {})}).encode()
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=seconds + 30) as r:
            out = json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        try:
            message = json.loads(e.read().decode()).get("message", str(e))
        except Exception:
            message = str(e)
        click.echo(f"[ERROR] Profile failed: {message}")
        sys.exit(1)
    except Exception as e:
        click.echo(f"[ERROR] Unable to reach query server: {e}")
        sys.exit(1)
    click.echo(f"[INFO] Captured {out.get('seconds')}s device profile "
               f"-> {out.get('traceDir')}")
    dispatch = out.get("dispatch") or {}
    if dispatch:
        click.echo("[INFO] Device seconds by compile family "
                   "(cumulative since process start):")
        for family, secs in dispatch.items():
            click.echo(f"[INFO]   {family:<24} {secs:.3f}s")
    else:
        click.echo("[INFO] No dispatch attribution recorded yet "
                   "(PIO_DISPATCH_ATTRIBUTION=0, or nothing dispatched).")


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=8000, type=int)
def slo(ip, port):
    """Read a live query server's SLO burn-rate evaluation (/slo.json)."""
    import urllib.request

    url = f"http://{ip}:{port}/slo.json"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            doc = json.loads(r.read().decode())
    except Exception as e:
        click.echo(f"[ERROR] Unable to read {url}: {e}")
        sys.exit(1)
    if not doc.get("enabled"):
        click.echo("[INFO] SLO engine disabled "
                   '(configure server.json {"slo": {...}}).')
        return
    state = "BREACHED" if doc.get("breached") else "ok"
    click.echo(f"[INFO] SLO status: {state}")
    for obj in doc.get("objectives", []):
        mark = "BREACHED" if obj.get("breached") else "ok"
        if obj.get("window") == "cold":
            mark += " (cold: history does not span the window yet)"
        windows = ", ".join(
            f"{int(w['seconds'])}s burn {w['burn']:.2f}/{w['burnThreshold']}"
            for w in obj.get("windows", []))
        click.echo(f"[INFO]   {obj['name']} ({obj['kind']}): {mark} "
                   f"[{windows}]")


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=8000, type=int)
@click.option("--json", "as_json", is_flag=True,
              help="Raw /capacity.json body.")
def capacity(ip, port, as_json):
    """Read a live server's device-memory ledger (GET /capacity.json):
    process-level device bytes / watermark / host RSS, plus per serving
    unit the resident factor, quantized-scorer and shortlist bytes.
    Works against any server in the fleet."""
    import urllib.request

    url = f"http://{ip}:{port}/capacity.json"
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            doc = json.loads(r.read().decode())
    except Exception as e:
        click.echo(f"[ERROR] Unable to read {url}: {e}")
        sys.exit(1)
    if as_json:
        click.echo(json.dumps(doc, indent=1, sort_keys=True))
        return

    def _mb(n):
        return f"{float(n or 0) / (1 << 20):.1f}MiB"

    proc = doc.get("process") or {}
    click.echo(f"[INFO] process: device {_mb(proc.get('deviceBytes'))} "
               f"across {int(proc.get('deviceArrays') or 0)} array(s), "
               f"watermark {_mb(proc.get('deviceWatermarkBytes'))}, "
               f"host RSS {_mb(proc.get('hostRssBytes'))}")
    units = doc.get("units") or []
    for u in units:
        click.echo(f"[INFO] unit {u.get('role')}: resident "
                   f"{_mb(u.get('residentBytes'))} (scorer "
                   f"{_mb(u.get('scorerBytes'))}) release "
                   f"v{u.get('release')} instance "
                   f"{u.get('engineInstanceId')}")
        for m in u.get("models") or []:
            click.echo(f"[INFO]   {m.get('model')}: factors "
                       f"{_mb(m.get('modelFactorBytes'))} + scorer "
                       f"{_mb(m.get('scorerFactorBytes'))} + shortlist "
                       f"{_mb(m.get('shortlistBytes'))}")
    if not units:
        click.echo("[INFO] no serving units reported (event server, "
                   "admin and dashboard answer process-level only).")


# ---------------------------------------------------------------------------
# durable telemetry (obs/tsdb.py + obs/telemetry.py)
# ---------------------------------------------------------------------------

@cli.group()
def metrics():
    """Query the durable local telemetry stores (metrics history that
    survives restarts; OBSERVABILITY.md "Durable telemetry")."""


def _history_reader(dirpath):
    from predictionio_tpu.obs import fleet
    from predictionio_tpu.utils.server_config import telemetry_config

    root = dirpath or telemetry_config().root_dir()
    reader = fleet.history_reader(root)
    return root, reader


@metrics.command("series")
@click.option("--dir", "dirpath", default=None,
              help="Telemetry root (default $PIO_HOME/telemetry or "
                   "PIO_TELEMETRY_DIR).")
@click.option("--name", default=None, help="Only this metric.")
def metrics_series(dirpath, name):
    """List the persisted series: name, labels, sample count, range."""
    root, reader = _history_reader(dirpath)
    listing = reader.series(name=name)
    for info in listing:
        if not info.points:
            continue
        span = (info.points[-1][0] - info.points[0][0]) / 1000.0
        click.echo(f"[INFO] {info.name} {info.labels} [{info.kind}] "
                   f"{len(info.points)} sample(s) over {span:.0f}s")
    click.echo(f"[INFO] {len(listing)} series in {root}.")


@metrics.command("query")
@click.argument("name")
@click.option("--since", default="1h", metavar="30m",
              help="Trailing window (e.g. 45s, 30m, 2h, 1d; default 1h).")
@click.option("--rate", "as_rate", is_flag=True,
              help="Per-second rate + increase over the window "
                   "(reset-adjusted: restarts never read negative).")
@click.option("--quantile", type=float, default=None,
              help="Histogram quantile over the window, e.g. 0.99.")
@click.option("--label", "label_filters", multiple=True,
              metavar="KEY=VALUE", help="Label filter (repeatable).")
@click.option("--dir", "dirpath", default=None,
              help="Telemetry root (default $PIO_HOME/telemetry or "
                   "PIO_TELEMETRY_DIR).")
@click.option("--json", "as_json", is_flag=True)
def metrics_query(name, since, as_rate, quantile, label_filters, dirpath,
                  as_json):
    """Range-query a metric's persisted history, fleet-merged across
    every local process's store (each labeled with its `process`)."""
    import time as _time

    root, reader = _history_reader(dirpath)
    since_ms = int((_time.time() - _parse_duration_s(since)) * 1000)
    labels = {}
    for spec in label_filters:
        if "=" not in spec:
            click.echo(f"[ERROR] --label expects KEY=VALUE, got {spec!r}")
            sys.exit(1)
        k, v = spec.split("=", 1)
        labels[k] = v
    labels = labels or None
    if quantile is not None:
        value = reader.quantile_over_time(name, quantile, labels=labels,
                                          since_ms=since_ms)
        if as_json:
            click.echo(json.dumps({"name": name, "quantile": quantile,
                                   "value": value}))
        elif value is None:
            click.echo(f"[INFO] no histogram data for {name} in the "
                       f"window (root {root}).")
        else:
            click.echo(f"[INFO] {name} p{quantile * 100:g} over {since}: "
                       f"{value:.6g}")
        return
    if as_rate:
        rates = reader.rate(name, labels=labels, since_ms=since_ms)
        if as_json:
            click.echo(json.dumps({"name": name, "series": rates}))
            return
        for r in rates:
            click.echo(f"[INFO] {name} {r['labels']}: "
                       f"{r['rate']:.4g}/s (+{r['increase']:.6g} over "
                       f"{r['seconds']:.0f}s)")
        if not rates:
            click.echo(f"[INFO] no data for {name} in the window "
                       f"(root {root}).")
        return
    series = reader.series(name=name, labels=labels, since_ms=since_ms)
    if as_json:
        out = []
        for info in series:
            points = ([[ts, sum(c), s] for ts, c, s in info.points]
                      if info.kind == "histogram"
                      else [[ts, v] for ts, v in info.points])
            out.append({"labels": info.labels, "kind": info.kind,
                        "points": points})
        click.echo(json.dumps({"name": name, "series": out}))
        return
    shown = 0
    for info in series:
        if not info.points:
            continue
        shown += 1
        if info.kind == "histogram":
            first, last = info.points[0], info.points[-1]
            click.echo(f"[INFO] {name} {info.labels} [histogram]: "
                       f"count {sum(first[1]):g} -> {sum(last[1]):g} "
                       f"over {len(info.points)} sample(s)")
        else:
            values = [p[1] for p in info.points]
            click.echo(f"[INFO] {name} {info.labels} [{info.kind}]: "
                       f"{values[0]:g} -> {values[-1]:g} over "
                       f"{len(values)} sample(s)")
    if not shown:
        click.echo(f"[INFO] no data for {name} in the window "
                   f"(root {root}).")


@cli.command()
@click.option("--path", "anatomy_path", default="serving",
              type=click.Choice(["serving", "ingest"]),
              help="Which critical path to analyze (default serving).")
@click.option("--since", default="1h", metavar="30m",
              help="Trailing window (e.g. 45s, 30m, 2h; default 1h).")
@click.option("--diff", "do_diff", is_flag=True,
              help="Two-window regression diff: the trailing window vs "
                   "the equal-length window before it; names the stage "
                   "the regression came from.")
@click.option("--dir", "dirpath", default=None,
              help="Telemetry root (default $PIO_HOME/telemetry or "
                   "PIO_TELEMETRY_DIR).")
@click.option("--json", "as_json", is_flag=True)
def analyze(anatomy_path, since, do_diff, dirpath, as_json):
    """Tail anatomy off the durable telemetry store: where p50 and p99
    requests spend their wall, per critical-path stage
    (pio_anatomy_stage_seconds), with an optional two-window diff that
    names the stage a latency regression came from."""
    import time as _time

    from predictionio_tpu.obs.anatomy import (
        composition, regression_diff, stage_stats,
    )

    root, reader = _history_reader(dirpath)
    window_ms = int(_parse_duration_s(since) * 1000)
    now_ms = int(_time.time() * 1000)
    since_ms = now_ms - window_ms
    stats = stage_stats(reader, anatomy_path, since_ms=since_ms)
    diff = None
    if do_diff:
        before = stage_stats(reader, anatomy_path,
                             since_ms=since_ms - window_ms,
                             until_ms=since_ms)
        if before and stats:
            diff = regression_diff(before, stats)
    if as_json:
        click.echo(json.dumps({
            "path": anatomy_path, "sinceMs": since_ms,
            "stages": stats,
            "p50Composition": composition(stats, anatomy_path, "p50"),
            "p99Composition": composition(stats, anatomy_path, "p99"),
            "diff": diff}, sort_keys=True))
        return
    if not stats:
        click.echo(f"[INFO] no anatomy history for path={anatomy_path} "
                   f"in the window (root {root}; is PIO_ANATOMY on and "
                   "telemetry persisting?).")
        return
    p50_comp = composition(stats, anatomy_path, "p50")
    p99_comp = composition(stats, anatomy_path, "p99")
    requests = max(s["count"] for s in stats.values())
    click.echo(f"[INFO] {anatomy_path} anatomy over {since} "
               f"({requests:g} request(s)):")
    click.echo(f"[INFO]   {'stage':<16} {'mean':>9} {'p50':>9} "
               f"{'p99':>9} {'p50 share':>10} {'p99 share':>10}")
    for stage, s in sorted(stats.items(), key=lambda kv: -kv[1]["p99"]):
        def _share(comp):
            return (f"{100.0 * comp[stage]:.0f}%"
                    if stage in comp else "-")
        click.echo(
            f"[INFO]   {stage:<16} {1e3 * s['mean']:>7.2f}ms "
            f"{1e3 * s['p50']:>7.2f}ms {1e3 * s['p99']:>7.2f}ms "
            f"{_share(p50_comp):>10} {_share(p99_comp):>10}")
    if do_diff:
        if diff is None:
            click.echo("[INFO] diff: not enough history in the "
                       "baseline window.")
        else:
            click.echo(
                f"[INFO] regression diff vs previous {since}: stage "
                f"'{diff['stage']}' moved most "
                f"({1e3 * diff['beforeMeanS']:.2f}ms -> "
                f"{1e3 * diff['afterMeanS']:.2f}ms mean, "
                f"{1e3 * diff['deltaMeanS']:+.2f}ms)")


@cli.command()
@click.option("--variant", "-v", default="engine.json")
@click.option("--once", is_flag=True,
              help="One trigger evaluation (and one cycle if it fires), "
                   "then exit.")
@click.option("--force", is_flag=True,
              help="Fire one manual cycle immediately (skips the "
                   "data-driven triggers and the cooldown window).")
@click.option("--cycles", type=int, default=None,
              help="Exit after this many completed cycles (default: "
                   "run forever).")
@click.option("--server", default=None, metavar="HOST:PORT",
              help="Drive a live query server's deploy API for the "
                   "canary phase (default: registry-only plane).")
@click.option("--accesskey", default=None)
@click.option("--state-dir", default=None,
              help="Crash-safe cycle-document directory (default "
                   "$PIO_HOME/orchestrator or PIO_ORCH_STATE_DIR).")
@click.option("--eval-class", default=None,
              help="Dotted Evaluation path for the eval-gate phase "
                   "(skipped when absent, like `pio eval`'s argument).")
def orchestrate(variant, once, force, cycles, server, accesskey,
                state_dir, eval_class):
    """Continuous-training orchestrator: the closed Lambda loop.

    Recurring train -> eval-gate -> batchpredict smoke -> SLO-judged
    canary -> promote over the release registry, with crash-safe phase
    state (kill it anywhere; the next start converges), data-driven
    retrain triggers (ingest volume, fold-in pressure, SLO burn) and
    jittered backoff on failure. README "Continuous training".
    """
    import os

    from predictionio_tpu.deploy.orchestrator import build_orchestrator

    if not os.path.exists(variant):
        click.echo(f"[ERROR] {variant} does not exist. Aborting.")
        sys.exit(1)
    orch = build_orchestrator(variant, eval_path=eval_class,
                              server=server, access_key=accesskey,
                              state_dir=state_dir)
    cfg = orch.cfg
    click.echo(f"[INFO] Orchestrating {orch.engine_id}/"
               f"{orch.engine_variant} (state in {orch.store.state_dir})")
    click.echo(f"[INFO] Triggers: ingest>={cfg.min_ingest_events or 'off'}"
               f" foldin>={cfg.foldin_pending_max or 'off'}"
               f" slo={'on' if cfg.slo_trigger else 'off'}; "
               f"cooldown {cfg.cooldown_s:g}s, check every "
               f"{cfg.interval_s:g}s")
    click.echo(f"[INFO] Canary plane: "
               + (f"live server {server}" if server
                  else "release registry"))
    if once or force:
        action = orch.recover()
        if action:
            click.echo(f"[INFO] Recovery: {action}")
        doc = orch.tick(force=force)
        if doc is None:
            click.echo("[INFO] No trigger fired; nothing to do.")
            return
        _echo_cycle(doc)
        if doc.outcome != "promoted":
            sys.exit(1)
        return
    try:
        done = orch.run(cycles=cycles)
    except KeyboardInterrupt:
        click.echo("[INFO] Orchestrator stopped.")
        return
    click.echo(f"[INFO] Orchestrator exiting after {done} cycle(s).")


def _echo_cycle(doc) -> None:
    click.echo(f"[INFO] Cycle {doc.cycle_id} ({doc.trigger}): "
               f"{doc.outcome} — {doc.reason}")
    if doc.candidate_release_version:
        click.echo(f"[INFO]   candidate release "
                   f"v{doc.candidate_release_version}"
                   + (f" | eval score {doc.eval_score}"
                      if doc.eval_score is not None else ""))
    trace = (doc.trace or ":").split(":")[0]
    click.echo(f"[INFO]   trace id {trace} (follow with `pio traces "
               f"--trace-id {trace}` on a live server)")


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=8000, type=int)
@click.option("--accesskey", default=None)
def undeploy(ip, port, accesskey):
    """Stop a deployed query server (Console.scala:318)."""
    import urllib.request

    url = f"http://{ip}:{port}/stop"
    if accesskey:
        url += f"?accessKey={accesskey}"
    try:
        with urllib.request.urlopen(
                urllib.request.Request(url, method="POST"), timeout=10) as r:
            click.echo(f"[INFO] {r.read().decode()}")
    except Exception as e:
        click.echo(f"[ERROR] Unable to undeploy: {e}")
        sys.exit(1)


@cli.command("eval")
@click.argument("evaluation_path")
@click.argument("params_generator_path", required=False)
@click.option("--batch", default="")
@click.option("--grid", "grid_specs", multiple=True, metavar="NAME=V1,V2",
              help="Cross-product override on the algorithm params, e.g. "
                   "--grid rank=8,12 --grid reg=0.01,0.1 (repeatable).")
@click.option("--k-fold", "k_fold", type=int, default=None,
              help="Override the datasource's kFold eval param.")
@click.option("--query-num", "query_num", type=int, default=None,
              help="Override the datasource's queryNum eval param.")
@click.option("--sequential", is_flag=True,
              help="Force the per-candidate sequential loop instead of "
                   "the device-batched sweep.")
def eval_cmd(evaluation_path, params_generator_path, batch, grid_specs,
             k_fold, query_num, sequential):
    """Run an evaluation sweep (Console.scala:232).

    EVALUATION_PATH: dotted path to an Evaluation object/factory;
    PARAMS_GENERATOR_PATH: dotted path to an EngineParamsGenerator (optional
    when the Evaluation carries its own params list).

    With --grid flags the supported engines execute the whole grid as a
    few device programs (folds become zero-weight masks over one shared
    data build; one XLA compile per distinct rank).
    """
    import dataclasses as _dc
    import os

    from predictionio_tpu.core.base import load_class
    from predictionio_tpu.core.evaluation import (
        VECTORIZE_ENV, Evaluation, expand_param_grid,
    )
    from predictionio_tpu.workflow import WorkflowParams, run_evaluation

    evaluation = load_class(evaluation_path)
    if isinstance(evaluation, type):
        evaluation = evaluation()          # Evaluation subclass
    elif callable(evaluation) and not isinstance(evaluation, Evaluation):
        evaluation = evaluation()          # factory function
    params_list = None
    if params_generator_path:
        gen = load_class(params_generator_path)
        if isinstance(gen, type):
            gen = gen()
        elif callable(gen) and not hasattr(gen, "engine_params_list"):
            gen = gen()
        params_list = list(gen.engine_params_list)
    if params_list is None:
        params_list = list(getattr(evaluation, "engine_params_list", []))
    if not params_list:
        click.echo("[ERROR] No engine params to evaluate. Aborting.")
        sys.exit(1)
    try:
        params_list = expand_param_grid(params_list, grid_specs)
    except ValueError as e:
        click.echo(f"[ERROR] {e}. Aborting.")
        sys.exit(1)
    if k_fold is not None or query_num is not None:
        overrides = {}
        if k_fold is not None:
            overrides["kFold"] = k_fold
        if query_num is not None:
            overrides["queryNum"] = query_num
        patched = []
        for ep in params_list:
            ds = ep.data_source_params
            if not hasattr(ds, "eval_params"):
                click.echo("[ERROR] --k-fold/--query-num need a datasource "
                           "with eval_params. Aborting.")
                sys.exit(1)
            ds = _dc.replace(ds, eval_params={**(ds.eval_params or {}),
                                              **overrides})
            patched.append(_dc.replace(ep, data_source_params=ds))
        params_list = patched
    old_vectorize = os.environ.get(VECTORIZE_ENV)
    if sequential:
        os.environ[VECTORIZE_ENV] = "0"
    try:
        result = run_evaluation(
            evaluation, params_list,
            evaluation_class=evaluation_path,
            params_generator_class=params_generator_path or "",
            workflow_params=WorkflowParams(batch=batch))
    finally:
        if sequential:
            if old_vectorize is None:
                os.environ.pop(VECTORIZE_ENV, None)
            else:
                os.environ[VECTORIZE_ENV] = old_vectorize
    sweep = result.sweep or {}
    if sweep.get("mode") == "batched":
        click.echo(f"[INFO] Sweep ran device-batched: "
                   f"{len(params_list)} candidates in "
                   f"{sweep.get('compileGroups')} compile group(s), "
                   f"batch sizes {sweep.get('batchSizes')}")
    for i, detail in enumerate(result.candidate_details):
        _ep, score, _others = result.engine_params_scores[i]
        click.echo(f"[INFO]   #{i}: score={score} "
                   f"wall={detail.get('wallTimeS')}s "
                   f"group={detail.get('group')}"
                   + (" <- best" if i == result.best_idx else ""))
    click.echo(f"[INFO] {result.to_one_liner()}")
    click.echo("[INFO] Evaluation completed.")


@cli.command()
@click.option("--variant", "-v", default="engine.json")
@click.option("--input", "input_path", required=True,
              help="Queries: one JSON object per line, or a .parquet "
                   "table (a 'query' JSON column or one column per "
                   "query field).")
@click.option("--output", "output_path", required=True,
              help="Predictions: JSON-lines, or .parquet when the path "
                   "(or --output-format) says so.")
@click.option("--engine-instance-id", default=None)
@click.option("--release", "release_selector", default=None,
              help="Score with a specific release (id, version number "
                   "or vN) from `pio releases`, like `pio deploy`.")
@click.option("--chunk-size", type=int, default=None,
              help="Maximal scoring bucket (default from server.json "
                   "batchpredict section / PIO_BATCHPREDICT_CHUNK_SIZE; "
                   "1024 out of the box).")
@click.option("--output-format", "output_format",
              type=click.Choice(["jsonl", "parquet"]), default=None,
              help="Force the output format instead of inferring from "
                   "the --output extension.")
@click.option("--input-format", "input_format",
              type=click.Choice(["jsonl", "parquet"]), default=None)
def batchpredict(variant, input_path, output_path, engine_instance_id,
                 release_selector, chunk_size, output_format, input_format):
    """Offline batch scoring (Console.scala:331, BatchPredict.scala:71):
    pipelined reader->scorer->writer over the engine's bucketed batch
    path. Multi-process sharding rides the PIO_PROCESS_ID /
    PIO_NUM_PROCESSES env contract: run one `pio batchpredict` per
    shard and the last to finish merges the fragments."""
    from predictionio_tpu.deploy.releases import resolve_release
    from predictionio_tpu.storage import Storage
    from predictionio_tpu.workflow.batch_predict import run_batch_predict

    engine, _, factory_path, variant_id, variant_json = \
        _load_engine_variant(variant)
    variant_conf = variant_json.get("batchpredict")
    # offline scoring honors the same scorer-mode chain as serving, so
    # batchpredict parity runs compare like against like
    from predictionio_tpu.ops.scoring import set_process_scorer_config
    from predictionio_tpu.utils.server_config import scorer_config

    scfg = scorer_config(variant_json.get("scorer"))
    set_process_scorer_config(scfg)
    if scfg.mode != "exact":
        click.echo(f"[INFO] Scoring kernel {scfg.mode} (tile "
                   f"{scfg.tile_items} items)")
    instances = Storage.get_meta_data_engine_instances()
    if release_selector:
        release = resolve_release(Storage.get_meta_data_releases(),
                                  factory_path, "1", variant_id,
                                  release_selector)
        if release is None:
            click.echo(f"[ERROR] Release {release_selector} not found "
                       "(see `pio releases`). Aborting.")
            sys.exit(1)
        instance = instances.get(release.instance_id)
        if instance is not None and instance.status == "COMPLETED":
            click.echo(f"[INFO] Scoring with release v{release.version} "
                       f"(instance {release.instance_id})")
    elif engine_instance_id:
        instance = instances.get(engine_instance_id)
    else:
        instance = instances.get_latest_completed(
            factory_path, "1", variant_id)
    if instance is None or instance.status != "COMPLETED":
        click.echo("[ERROR] No COMPLETED engine instance found. Aborting.")
        sys.exit(1)
    report = run_batch_predict(
        engine, instance, input_path, output_path, chunk_size=chunk_size,
        output_format=output_format, input_format=input_format,
        variant_conf=variant_conf)
    if report.merged:
        click.echo(f"[INFO] Wrote {report.total_written} predictions to "
                   f"{report.output_path}")
        if report.fleet:
            totals = report.fleet.get("counterTotals", {})
            scored = totals.get("pio_batchpredict_queries_total")
            click.echo(
                f"[INFO] Fleet view ({len(report.fleet.get('processes', []))}"
                f" process(es)) -> {report.output_path}.fleet.json"
                + (f"; fleet queries scored {scored:g}"
                   if scored is not None else "")
                + "; inspect with `pio status --fleet "
                + f"{report.output_path}`")
    else:
        rank, size = report.worker
        click.echo(f"[INFO] Shard {rank}/{size} wrote {report.written} "
                   f"predictions to fragment {report.output_path} "
                   "(awaiting merge by the last shard)")
    if report.invalid or (report.total_invalid or 0):
        n_bad = report.total_invalid if report.merged else report.invalid
        click.echo(f"[WARN] Skipped {n_bad} invalid queries "
                   f"-> {report.errors_path}")
    if report.trace_id:
        click.echo(f"[INFO] Trace id {report.trace_id} "
                   "(follow with `pio traces --trace-id ...` on a live "
                   "server, or in the .fleet.json)")


# ---------------------------------------------------------------------------
# import / export (commands/{Import,Export}.scala)
# ---------------------------------------------------------------------------

@cli.command("import")
@click.option("--appid", type=int, default=None)
@click.option("--appname", default=None)
@click.option("--channel", default=None)
@click.option("--input", "input_path", required=True,
              help="JSON-lines file of events (FileToEvents.scala:40).")
def import_cmd(appid, appname, channel, input_path):
    """Import events from a JSON-lines file (Console.scala:623)."""
    from predictionio_tpu.data.event import Event, validate_event
    from predictionio_tpu.data.eventstore import resolve_app
    from predictionio_tpu.storage import Storage, StorageError

    if appname:
        try:
            app_id, channel_id = resolve_app(appname, channel)
        except StorageError as e:
            click.echo(f"[ERROR] {e}. Aborting.")
            sys.exit(1)
    elif appid is not None:
        app_id, channel_id = appid, None
    else:
        click.echo("[ERROR] --appid or --appname is required.")
        sys.exit(1)
    store = Storage.get_events()
    store.init_channel(app_id, channel_id)
    BATCH = 5000
    batch, total = [], 0
    with open(input_path) as f:  # streamed: memory stays one batch deep
        for line in f:
            line = line.strip()
            if not line:
                continue
            e = Event.from_json(line)
            validate_event(e)
            batch.append(e)
            if len(batch) >= BATCH:
                store.insert_batch(batch, app_id, channel_id)
                total += len(batch)
                batch = []
    if batch:
        store.insert_batch(batch, app_id, channel_id)
        total += len(batch)
    click.echo(f"[INFO] Imported {total} events.")


@cli.command("export")
@click.option("--appid", type=int, default=None)
@click.option("--appname", default=None)
@click.option("--channel", default=None)
@click.option("--output", "output_path", required=True)
@click.option("--format", "fmt", type=click.Choice(["json", "parquet"]),
              default="json")
def export_cmd(appid, appname, channel, output_path, fmt):
    """Export events to a file (Console.scala:606, EventsToFile.scala:40)."""
    import os

    from predictionio_tpu.data.eventstore import resolve_app
    from predictionio_tpu.storage import Storage, StorageError

    if appname:
        try:
            app_id, channel_id = resolve_app(appname, channel)
        except StorageError as e:
            click.echo(f"[ERROR] {e}. Aborting.")
            sys.exit(1)
    elif appid is not None:
        app_id, channel_id = appid, None
    else:
        click.echo("[ERROR] --appid or --appname is required.")
        sys.exit(1)
    store = Storage.get_events()
    # temp-write + rename: an interrupted export must never leave a
    # truncated file that looks like a complete dump (the import side
    # has no way to tell "all the events" from "the first half")
    tmp = f"{output_path}.tmp-{os.getpid()}"
    try:
        if fmt == "parquet":
            import pyarrow.parquet as pq

            table = store.find_columnar(app_id, channel_id)
            pq.write_table(table, tmp)
            n = table.num_rows
        else:
            n = 0
            with open(tmp, "w") as f:
                for e in store.find(app_id, channel_id):
                    f.write(e.to_json() + "\n")
                    n += 1
        os.replace(tmp, output_path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    click.echo(f"[INFO] Exported {n} events to {output_path}.")


@cli.command("compact")
@click.option("--appid", type=int, default=None)
@click.option("--appname", default=None)
@click.option("--channel", default=None)
@click.option("--ttl-days", type=float, default=None,
              help="Also drop events older than this many days "
                   "(per-app retention sweep).")
def compact_cmd(appid, appname, channel, ttl_days):
    """Event-store maintenance: fold deletes, merge fragments, apply
    retention. Crash-safe on parquet (write-new-then-remove-old behind an
    atomically committed manifest); a retention DELETE on SQL backends.
    Run one compactor per app namespace at a time."""
    from predictionio_tpu.data.eventstore import resolve_app
    from predictionio_tpu.storage import Storage, StorageError

    if appname:
        try:
            app_id, channel_id = resolve_app(appname, channel)
        except StorageError as e:
            click.echo(f"[ERROR] {e}. Aborting.")
            sys.exit(1)
    elif appid is not None:
        app_id, channel_id = appid, None
        if channel is not None:
            # compaction is destructive: never silently fall back to the
            # default channel when the named one cannot be resolved
            matched = [c for c in Storage.get_meta_data_channels()
                       .get_by_appid(appid) if c.name == channel]
            if not matched:
                click.echo(f"[ERROR] app {appid} has no channel "
                           f"'{channel}'. Aborting.")
                sys.exit(1)
            channel_id = matched[0].id
    else:
        click.echo("[ERROR] --appid or --appname is required.")
        sys.exit(1)
    store = Storage.get_events()
    try:
        stats = store.compact(app_id, channel_id, ttl_days=ttl_days)
    except StorageError as e:
        click.echo(f"[ERROR] compaction failed: {e}")
        sys.exit(1)
    click.echo(f"[INFO] Compacted app {app_id}"
               + (f" channel {channel_id}" if channel_id is not None else "")
               + ": " + json.dumps(stats, sort_keys=True))


@cli.command("reshard")
@click.option("--partitions", "-p", type=int, required=True,
              help="New partition count for the event store.")
def reshard_cmd(partitions):
    """Change the partitioned event store's partition count.

    Copies every app/channel namespace into a new generation of
    partition stores (idempotent inserts, original event ids), commits
    the partition map atomically, then collects the old generation —
    exactly-once at every crash point; an interrupted run can simply be
    re-run. Offline maintenance: stop event servers first (like
    `pio compact`, one operator at a time)."""
    from predictionio_tpu.storage import Storage, StorageError

    store = Storage.get_events()
    if not hasattr(store, "reshard"):
        click.echo(
            "[ERROR] the configured event store is not partitioned. "
            "Set PIO_INGEST_PARTITIONS>1 on a sqlite or parquet "
            "EVENTDATA source to create one.")
        sys.exit(1)
    apps = []
    for app in Storage.get_meta_data_apps().get_all():
        apps.append((app.id, None))
        for ch in Storage.get_meta_data_channels().get_by_appid(app.id):
            apps.append((app.id, ch.id))
    try:
        stats = store.reshard(partitions, apps)
    except StorageError as e:
        click.echo(f"[ERROR] reshard failed (safe to re-run): {e}")
        sys.exit(1)
    click.echo(f"[INFO] Resharded {len(apps)} namespace(s): "
               + json.dumps(stats, sort_keys=True))


# ---------------------------------------------------------------------------
# servers
# ---------------------------------------------------------------------------

@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=7070, type=int)
@click.option("--stats", is_flag=True, help="Enable hourly ingest statistics.")
def eventserver(ip, port, stats):
    """Launch the Event Server (Console.scala:384, EventServer.scala:552)."""
    from predictionio_tpu.server.event_server import run_event_server
    click.echo(f"[INFO] Creating Event Server at {ip}:{port}")
    run_event_server(ip=ip, port=port, stats=stats)


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=7071, type=int)
def adminserver(ip, port):
    """Launch the admin API (Console.scala:399, AdminAPI.scala:45)."""
    from predictionio_tpu.server.admin import run_admin_server
    click.echo(f"[INFO] Creating Admin API at {ip}:{port}")
    run_admin_server(ip=ip, port=port)


@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=9000, type=int)
def dashboard(ip, port):
    """Launch the evaluation dashboard (Console.scala:371, Dashboard.scala:45)."""
    from predictionio_tpu.server.dashboard import run_dashboard
    click.echo(f"[INFO] Creating Dashboard at {ip}:{port}")
    run_dashboard(ip=ip, port=port)


@cli.command()
def shell():
    """Interactive REPL with the framework preloaded (bin/pio-shell analog)."""
    import code

    from predictionio_tpu.data.eventstore import EventStoreClient
    from predictionio_tpu.storage import Storage
    from predictionio_tpu.workflow import WorkflowContext

    banner = ("predictionio_tpu shell\n"
              "preloaded: Storage, EventStoreClient (PEventStore/LEventStore"
              " analog), WorkflowContext")
    local = {"Storage": Storage, "EventStoreClient": EventStoreClient,
             "WorkflowContext": WorkflowContext}
    try:
        import IPython

        IPython.start_ipython(argv=[], user_ns=local)
    except ImportError:
        code.interact(banner=banner, local=local)


@cli.group()
def template():
    """Engine template helpers (Console.scala:595-605)."""


@template.command("list")
def template_list():
    """List built-in engine templates."""
    templates = {
        "recommendation": "predictionio_tpu.engines.recommendation:engine",
        "similarproduct": "predictionio_tpu.engines.similarproduct:engine",
        "classification": "predictionio_tpu.engines.classification:engine",
        "ecommerce": "predictionio_tpu.engines.ecommerce:engine",
        "sessionrec": "predictionio_tpu.engines.sessionrec:engine",
        "recommendeduser": "predictionio_tpu.engines.recommended_user:engine",
    }
    for name, factory in templates.items():
        click.echo(f"[INFO] {name:<16} {factory}")


@template.command("get")
@click.argument("name")
@click.argument("directory", required=False)
def template_get(name, directory):
    """Scaffold an engine.json for a built-in template."""
    import os

    factories = {
        "recommendation": ("predictionio_tpu.engines.recommendation:engine",
                           {"app_name": "MyApp"},
                           [{"name": "als",
                             "params": {"rank": 10, "num_iterations": 20,
                                        "reg": 0.01, "seed": 3}}]),
        "similarproduct": ("predictionio_tpu.engines.similarproduct:engine",
                           {"app_name": "MyApp"},
                           [{"name": "als",
                             "params": {"rank": 10, "num_iterations": 20}}]),
        "classification": ("predictionio_tpu.engines.classification:engine",
                           {"app_name": "MyApp"},
                           [{"name": "naive", "params": {"reg": 1.0}}]),
        "ecommerce": ("predictionio_tpu.engines.ecommerce:engine",
                      {"app_name": "MyApp"},
                      [{"name": "ecomm",
                        "params": {"app_name": "MyApp", "rank": 10}}]),
        "sessionrec": ("predictionio_tpu.engines.sessionrec:engine",
                       {"app_name": "MyApp"},
                       [{"name": "seqrec",
                         "params": {"d_model": 64, "n_heads": 2,
                                    "n_layers": 2, "max_len": 32,
                                    "epochs": 10}}]),
        "recommendeduser": (
            "predictionio_tpu.engines.recommended_user:engine",
            {"app_name": "MyApp"},
            [{"name": "als",
              "params": {"rank": 10, "num_iterations": 20}}]),
    }
    if name not in factories:
        click.echo(f"[ERROR] Unknown template {name}. "
                   f"Known: {', '.join(factories)}")
        sys.exit(1)
    factory, ds_params, algos = factories[name]
    target_dir = directory or name
    os.makedirs(target_dir, exist_ok=True)
    target = os.path.join(target_dir, "engine.json")
    # temp-write + rename: engine.json is the deploy surface — a crash
    # here must leave the previous template or nothing, never half a file
    tmp = f"{target}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump({
                "id": "default",
                "description": f"{name} engine",
                "engineFactory": factory,
                "datasource": {"params": ds_params},
                "algorithms": algos,
            }, f, indent=2)
        os.replace(tmp, target)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    click.echo(f"[INFO] Engine template {name} written to {target}")


@cli.command()
@click.argument("paths", nargs=-1)
@click.option("--rule", "-r", "rules", multiple=True,
              help="Run only these rule ids (repeatable), e.g. -r PIO002.")
@click.option("--json", "as_json", is_flag=True,
              help="Machine-readable report on stdout.")
@click.option("--baseline", "baseline_path", default=None,
              help="Baseline file of grandfathered findings "
                   "(default: conf/pio_check_baseline.json when present).")
@click.option("--write-baseline", is_flag=True,
              help="Rewrite the baseline to absorb every current finding.")
@click.option("--no-baseline", is_flag=True,
              help="Report every finding, ignoring any baseline.")
@click.option("--list-rules", is_flag=True,
              help="List the shipped rule ids and exit.")
def check(paths, rules, as_json, baseline_path, write_baseline,
          no_baseline, list_rules):
    """Static analysis: enforce the fleet's safety invariants.

    Scans predictionio_tpu/ plus bench.py (or just PATHS, root-relative)
    with the checker engine; exits 1 when any finding is not covered by
    the committed baseline or an inline `# pio: ignore[RULE]: reason`.
    """
    import pathlib

    import predictionio_tpu
    from predictionio_tpu.analysis import Baseline, Project, run_check
    from predictionio_tpu.analysis.engine import DEFAULT_BASELINE, all_rules

    if list_rules:
        for rid, title in sorted(all_rules().items()):
            click.echo(f"{rid}  {title}")
        return
    if write_baseline and (rules or paths):
        # a partial run would rewrite the baseline WITHOUT the entries
        # the filtered-out rules/files still need, silently un-
        # grandfathering them
        click.echo("[ERROR] --write-baseline regenerates the whole "
                   "baseline; it cannot be combined with --rule or PATHS.")
        sys.exit(2)
    root = pathlib.Path(predictionio_tpu.__file__).resolve().parent.parent
    # ALWAYS parse the full tree: whole-program rules (committer
    # reachability, builder routing, docs drift) need it; PATHS only
    # filters which files findings are reported for
    project = Project.from_root(root)
    scanned = {f.path for f in project.files}
    norm_paths = []
    for p in paths:
        # PATHS are project-root-relative; normalize `./`, `..`, and
        # absolute spellings to the scanned form so a mistyped path can
        # never silently filter every finding away and report clean
        base = pathlib.Path(p) if pathlib.Path(p).is_absolute() \
            else root / p
        try:
            norm = base.resolve().relative_to(root).as_posix()
        except ValueError:
            click.echo(f"[ERROR] {p} is outside the project root {root}.")
            sys.exit(2)
        if not any(s == norm or s.startswith(norm + "/")
                   for s in scanned):
            click.echo(f"[ERROR] {p} matches no scanned file "
                       "(paths are relative to the project root, e.g. "
                       "predictionio_tpu/deploy/foldin.py).")
            sys.exit(2)
        norm_paths.append(norm)
    baseline = Baseline()
    resolved = pathlib.Path(baseline_path) if baseline_path \
        else root / DEFAULT_BASELINE
    if not no_baseline and not write_baseline and resolved.is_file():
        baseline = Baseline.load(resolved)
    try:
        report = run_check(project, rules=rules or None, baseline=baseline,
                           paths=norm_paths or None)
    except ValueError as e:
        click.echo(f"[ERROR] {e}")
        sys.exit(2)
    if write_baseline:
        Baseline.from_findings(
            report.findings + report.baselined).save(resolved)
        click.echo(f"[INFO] baseline written to {resolved} "
                   f"({len(report.findings) + len(report.baselined)} "
                   "findings absorbed)")
        return
    if as_json:
        click.echo(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        click.echo(report.render())
    if not report.ok:
        sys.exit(1)


@cli.command()
@click.argument("main_module")
@click.argument("args", nargs=-1)
def run(main_module, args):
    """Run a module's main() in the framework environment (Console.scala:412)."""
    import runpy

    sys.argv = [main_module, *args]
    runpy.run_module(main_module, run_name="__main__")


def main():
    cli()


if __name__ == "__main__":
    main()
