"""`pio` CLI entry point.

Command surface mirrors the reference console (Console.scala:134-623):
app/accesskey/channel management, train, deploy, eval, batchpredict,
eventserver, import/export, status. Training runs in-process (no
spark-submit analog; SURVEY.md section 7 design mapping).
"""

from __future__ import annotations

import json
import sys

import click

from predictionio_tpu import __version__


@click.group()
def cli():
    """predictionio_tpu — TPU-native ML server framework."""


@cli.command()
def version():
    """Print framework version (Console.scala:134)."""
    click.echo(__version__)


@cli.command()
def status():
    """Verify storage configuration (Console.scala:435, Management.scala:99)."""
    from predictionio_tpu.storage import Storage
    click.echo("[INFO] Inspecting predictionio_tpu installation...")
    click.echo(f"[INFO] Version {__version__}")
    try:
        Storage.verify_all_data_objects()
    except Exception as e:
        click.echo(f"[ERROR] Unable to connect to all storage backends: {e}")
        sys.exit(1)
    click.echo("[INFO] All storage backends are properly configured.")
    click.echo("[INFO] Your system is all ready to go.")


# ---------------------------------------------------------------------------
# app management (commands/App.scala:31-363)
# ---------------------------------------------------------------------------

@cli.group()
def app():
    """Manage apps (Console.scala:452-517)."""


@app.command("new")
@click.argument("name")
@click.option("--id", "app_id", type=int, default=0, help="Preferred app id.")
@click.option("--description", default=None)
@click.option("--access-key", default="", help="Use this access key instead of generating one.")
def app_new(name, app_id, description, access_key):
    from predictionio_tpu.storage import AccessKey, App, Storage
    apps = Storage.get_meta_data_apps()
    if apps.get_by_name(name):
        click.echo(f"[ERROR] App {name} already exists. Aborting.")
        sys.exit(1)
    new_id = apps.insert(App(id=app_id, name=name, description=description))
    if new_id is None:
        click.echo("[ERROR] Unable to create new app.")
        sys.exit(1)
    Storage.get_events().init_channel(new_id)
    key = Storage.get_meta_data_access_keys().insert(
        AccessKey(key=access_key, appid=new_id, events=()))
    if key is None:
        click.echo(f"[ERROR] Access key {access_key} already exists. Aborting.")
        Storage.get_events().remove_channel(new_id)
        Storage.get_meta_data_apps().delete(new_id)
        sys.exit(1)
    click.echo("[INFO] Created a new app:")
    click.echo(f"[INFO]         Name: {name}")
    click.echo(f"[INFO]           ID: {new_id}")
    click.echo(f"[INFO] Access Key: {key}")


@app.command("list")
def app_list():
    from predictionio_tpu.storage import Storage
    apps = Storage.get_meta_data_apps().get_all()
    keys = Storage.get_meta_data_access_keys()
    click.echo(f"[INFO] {'Name':<20} | {'ID':<4} | Access Key")
    for a in sorted(apps, key=lambda x: x.name):
        for k in keys.get_by_appid(a.id) or [None]:
            key = k.key if k else ""
            click.echo(f"[INFO] {a.name:<20} | {a.id:<4} | {key}")
    click.echo(f"[INFO] Finished listing {len(apps)} app(s).")


@app.command("show")
@click.argument("name")
def app_show(name):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(name)
    if a is None:
        click.echo(f"[ERROR] App {name} does not exist. Aborting.")
        sys.exit(1)
    click.echo(f"[INFO]     App Name: {a.name}")
    click.echo(f"[INFO]       App ID: {a.id}")
    click.echo(f"[INFO]  Description: {a.description or ''}")
    for k in Storage.get_meta_data_access_keys().get_by_appid(a.id):
        events = ",".join(k.events) if k.events else "(all)"
        click.echo(f"[INFO]   Access Key: {k.key} | {events}")
    for c in Storage.get_meta_data_channels().get_by_appid(a.id):
        click.echo(f"[INFO]      Channel: {c.name} ({c.id})")


@app.command("delete")
@click.argument("name")
@click.option("--force", "-f", is_flag=True)
def app_delete(name, force):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(name)
    if a is None:
        click.echo(f"[ERROR] App {name} does not exist. Aborting.")
        sys.exit(1)
    if not force and not click.confirm(
            f"Delete app {name} and ALL its data?"):
        click.echo("[INFO] Aborted.")
        return
    events = Storage.get_events()
    for c in Storage.get_meta_data_channels().get_by_appid(a.id):
        events.remove_channel(a.id, c.id)
        Storage.get_meta_data_channels().delete(c.id)
    events.remove_channel(a.id)
    for k in Storage.get_meta_data_access_keys().get_by_appid(a.id):
        Storage.get_meta_data_access_keys().delete(k.key)
    Storage.get_meta_data_apps().delete(a.id)
    click.echo(f"[INFO] App {name} deleted.")


@app.command("data-delete")
@click.argument("name")
@click.option("--channel", default=None)
@click.option("--all", "delete_all", is_flag=True)
@click.option("--force", "-f", is_flag=True)
def app_data_delete(name, channel, delete_all, force):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(name)
    if a is None:
        click.echo(f"[ERROR] App {name} does not exist. Aborting.")
        sys.exit(1)
    if not force and not click.confirm(f"Delete data of app {name}?"):
        click.echo("[INFO] Aborted.")
        return
    events = Storage.get_events()
    if delete_all or channel is None:
        events.remove_channel(a.id)
        events.init_channel(a.id)
        click.echo(f"[INFO] Deleted data of app {name} (default channel).")
    if channel is not None or delete_all:
        channels = Storage.get_meta_data_channels().get_by_appid(a.id)
        if channel is not None and channel not in [c.name for c in channels]:
            click.echo(f"[ERROR] Channel {channel} does not exist. Aborting.")
            sys.exit(1)
        for c in channels:
            if delete_all or c.name == channel:
                events.remove_channel(a.id, c.id)
                events.init_channel(a.id, c.id)
                click.echo(f"[INFO] Deleted data of channel {c.name}.")


@app.command("channel-new")
@click.argument("app_name")
@click.argument("channel_name")
def app_channel_new(app_name, channel_name):
    from predictionio_tpu.storage import Channel, Storage
    a = Storage.get_meta_data_apps().get_by_name(app_name)
    if a is None:
        click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
        sys.exit(1)
    try:
        cid = Storage.get_meta_data_channels().insert(
            Channel(id=0, name=channel_name, appid=a.id))
    except ValueError as e:
        click.echo(f"[ERROR] {e}")
        sys.exit(1)
    if cid is None:
        click.echo(f"[ERROR] Channel {channel_name} already exists.")
        sys.exit(1)
    Storage.get_events().init_channel(a.id, cid)
    click.echo(f"[INFO] Created channel {channel_name} ({cid}).")


@app.command("channel-delete")
@click.argument("app_name")
@click.argument("channel_name")
@click.option("--force", "-f", is_flag=True)
def app_channel_delete(app_name, channel_name, force):
    from predictionio_tpu.storage import Storage
    a = Storage.get_meta_data_apps().get_by_name(app_name)
    if a is None:
        click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
        sys.exit(1)
    matched = [c for c in Storage.get_meta_data_channels().get_by_appid(a.id)
               if c.name == channel_name]
    if not matched:
        click.echo(f"[ERROR] Channel {channel_name} does not exist.")
        sys.exit(1)
    if not force and not click.confirm(
            f"Delete channel {channel_name} and its data?"):
        click.echo("[INFO] Aborted.")
        return
    Storage.get_events().remove_channel(a.id, matched[0].id)
    Storage.get_meta_data_channels().delete(matched[0].id)
    click.echo(f"[INFO] Deleted channel {channel_name}.")


# ---------------------------------------------------------------------------
# accesskey management (commands/AccessKey.scala)
# ---------------------------------------------------------------------------

@cli.group()
def accesskey():
    """Manage access keys (Console.scala:554-592)."""


@accesskey.command("new")
@click.argument("app_name")
@click.option("--key", default="")
@click.option("--event", "events", multiple=True,
              help="Allowed event names (default: all).")
def accesskey_new(app_name, key, events):
    from predictionio_tpu.storage import AccessKey, Storage
    a = Storage.get_meta_data_apps().get_by_name(app_name)
    if a is None:
        click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
        sys.exit(1)
    k = Storage.get_meta_data_access_keys().insert(
        AccessKey(key=key, appid=a.id, events=tuple(events)))
    if k is None:
        click.echo("[ERROR] Unable to create access key.")
        sys.exit(1)
    click.echo(f"[INFO] Created new access key: {k}")


@accesskey.command("list")
@click.argument("app_name", required=False)
def accesskey_list(app_name):
    from predictionio_tpu.storage import Storage
    keys = Storage.get_meta_data_access_keys()
    if app_name:
        a = Storage.get_meta_data_apps().get_by_name(app_name)
        if a is None:
            click.echo(f"[ERROR] App {app_name} does not exist. Aborting.")
            sys.exit(1)
        listing = keys.get_by_appid(a.id)
    else:
        listing = keys.get_all()
    for k in listing:
        events = ",".join(k.events) if k.events else "(all)"
        click.echo(f"[INFO] {k.key} | app {k.appid} | {events}")
    click.echo(f"[INFO] Finished listing {len(listing)} access key(s).")


@accesskey.command("delete")
@click.argument("key")
def accesskey_delete(key):
    from predictionio_tpu.storage import Storage
    Storage.get_meta_data_access_keys().delete(key)
    click.echo(f"[INFO] Deleted access key {key}.")


# ---------------------------------------------------------------------------
# servers
# ---------------------------------------------------------------------------

@cli.command()
@click.option("--ip", default="localhost")
@click.option("--port", default=7070, type=int)
@click.option("--stats", is_flag=True, help="Enable hourly ingest statistics.")
def eventserver(ip, port, stats):
    """Launch the Event Server (Console.scala:384, EventServer.scala:552)."""
    from predictionio_tpu.server.event_server import run_event_server
    click.echo(f"[INFO] Creating Event Server at {ip}:{port}")
    run_event_server(ip=ip, port=port, stats=stats)


def main():
    cli()


if __name__ == "__main__":
    main()
