"""`pio` CLI entry point.

Command surface mirrors the reference console (Console.scala:134-623):
app/accesskey/channel management, train, deploy, eval, batchpredict,
eventserver, import/export, status. Commands are registered incrementally as
the corresponding subsystems land; `pio version` and `pio status` work first.
"""

from __future__ import annotations

import click

from predictionio_tpu import __version__


@click.group()
def cli():
    """predictionio_tpu — TPU-native ML server framework."""


@cli.command()
def version():
    """Print framework version (Console.scala:134)."""
    click.echo(__version__)


def main():
    cli()


if __name__ == "__main__":
    main()
