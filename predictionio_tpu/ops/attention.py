"""Long-context attention with sequence parallelism over a device mesh.

The reference has no attention models (SURVEY.md §5: "long-context /
sequence parallelism — absent"), but this framework treats long-context and
distributed execution as first-class: engines that embed sequence models
(session-based recommendation, event-stream encoders) need attention that
scales past a single chip's HBM. Three strategies, one contract:

* ``mha`` — dense reference implementation (single device, or fully
  replicated); the numerical ground truth the parallel paths are tested
  against.
* ``ring_attention`` — sequence parallelism: Q/K/V sharded along the
  sequence axis of a ``Mesh``; K/V blocks rotate around the ring via
  ``lax.ppermute`` while each device accumulates its queries' output with
  the flash-attention running-max/denominator recurrence. HBM per device is
  O(L/p); comms ride ICI neighbor-to-neighbor, overlapping with the block
  matmuls (the Ring Attention construction, cf. PAPERS.md).
* ``ulysses_attention`` — all-to-all sequence↔head resharding: each device
  gathers the FULL sequence for H/p heads (two ``all_to_all``s), runs dense
  attention locally, and reshards back. Cheaper comms volume than ring for
  moderate L; requires heads % devices == 0.

All paths use the same [batch, seq, heads, head_dim] layout, jit/shard_map
compile to static shapes, and keep the softmax in float32 regardless of
input dtype (bfloat16 QKV with f32 accumulation is the TPU-native recipe:
matmuls hit the MXU in bf16, the recurrence stays stable in f32).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.compat import HAS_VMA, pcast_varying, shard_map

NEG_INF = -1e30    # large-negative instead of -inf: avoids NaN in exp(m - m)


def _causal_mask(scores: jax.Array, q_off, k_off) -> jax.Array:
    """Mask scores [..., Lq, Lk] so query i attends to keys j with
    global_j <= global_i, where globals are local indices + offsets."""
    lq, lk = scores.shape[-2], scores.shape[-1]
    qi = q_off + jnp.arange(lq)[:, None]
    kj = k_off + jnp.arange(lk)[None, :]
    return jnp.where(kj <= qi, scores, NEG_INF)


def mha(q: jax.Array, k: jax.Array, v: jax.Array,
        causal: bool = False,
        key_mask: Optional[jax.Array] = None) -> jax.Array:
    """Dense multi-head attention. q,k,v: [B, L, H, D] -> [B, L, H, D].
    key_mask: optional [B, Lk] bool, False = key is padding (ignored)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, 0, 0)
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked query rows (e.g. pad queries) output 0, not mean-of-V
    p = p * (s.max(axis=-1, keepdims=True) > NEG_INF / 2)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _flash_step(q, k_j, v_j, o, m, l, q_off, k_off, causal: bool,
                scale: float, key_mask_j=None):
    """One flash-attention accumulation step: fold K/V block (k_j, v_j) at
    global key offset k_off into the running (o, m, l) state for queries q
    at global offset q_off. Shared by the single-device blockwise kernel
    and the ring (the only difference between them is where the next block
    comes from)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_j,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, q_off, k_off)
    if key_mask_j is not None:
        s = jnp.where(key_mask_j[:, None, None, :], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    # explicit zero for masked scores: with the finite NEG_INF sentinel,
    # exp(s - m_new) would be 1 (not 0) in all-masked rows
    p = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new[..., None]), 0.0)
    l = l * alpha + p.sum(axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_j.astype(jnp.float32))
    return o, m_new, l


def _flash_finish(o, l, dtype):
    out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(dtype)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_k: int = 512, causal: bool = False,
                        key_mask: Optional[jax.Array] = None) -> jax.Array:
    """Flash-style single-device attention: stream over K/V blocks with the
    running-max/denominator recurrence so the [Lq, Lk] score matrix never
    materializes. O(L * block_k) memory; exact (not approximate).
    key_mask: optional [B, Lk] bool, False = key is padding (ignored).
    Sequence lengths that are not a block_k multiple are handled by padding
    K/V up to one and masking the pad keys out."""
    b, lq, h, d = q.shape
    lk = k.shape[1]
    block_k = min(block_k, lk)
    # non-divisible lengths: pad K/V up to a block multiple and mask the
    # pad keys out (cheaper than shrinking the block and re-tiling)
    pad = -lk % block_k
    if key_mask is None:
        key_mask = jnp.ones((b, lk), bool)
    if pad:
        zeros = jnp.zeros((b, pad, h, d), k.dtype)
        k = jnp.concatenate([k, zeros], axis=1)
        v = jnp.concatenate([v, zeros], axis=1)
        key_mask = jnp.concatenate(
            [key_mask, jnp.zeros((b, pad), bool)], axis=1)
    n_blocks = (lk + pad) // block_k
    scale = d ** -0.5
    kb = k.reshape(b, n_blocks, block_k, h, d)
    vb = v.reshape(b, n_blocks, block_k, h, d)
    mb = key_mask.reshape(b, n_blocks, block_k)

    def step(carry, xs):
        j, k_j, v_j, m_j = xs
        o, m, l = _flash_step(q, k_j, v_j, *carry, 0, j * block_k,
                              causal, scale, key_mask_j=m_j)
        return (o, m, l), None

    o0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    (o, _, l), _ = jax.lax.scan(
        step, (o0, m0, l0),
        (jnp.arange(n_blocks), jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0),
         jnp.moveaxis(mb, 1, 0)))
    return _flash_finish(o, l, q.dtype)


def _ring_attention_local(q, k, v, key_mask, *, axis: str, causal: bool,
                          batch_axis: Optional[str] = None):
    """shard_map body: q/k/v are the LOCAL sequence shards [B, L/p, H, D];
    key_mask the matching [B, L/p] bool shard (False = padding key). With
    a batch_axis, B is also the local batch shard (dp x sp)."""
    p_size = jax.lax.psum(1, axis)
    r = jax.lax.axis_index(axis)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = d ** -0.5
    q_off = r * lq

    def step(carry, t):
        o, m, l, k_t, v_t, km_t = carry
        # device r holds the kv block originally on device (r + t) mod p
        k_off = ((r + t) % p_size) * lk
        o, m, l = _flash_step(q, k_t, v_t, o, m, l, q_off, k_off,
                              causal, scale, key_mask_j=km_t)
        # rotate: receive the next block from the right neighbor
        perm = [(i, (i - 1) % p_size) for i in range(p_size)]
        k_t = jax.lax.ppermute(k_t, axis, perm)
        v_t = jax.lax.ppermute(v_t, axis, perm)
        km_t = jax.lax.ppermute(km_t, axis, perm)
        return (o, m, l, k_t, v_t, km_t), None

    # zero-init carries must be marked device-varying over every mesh axis
    # the inputs vary over (the ring axis, plus the batch axis under
    # dp x sp) or scan rejects the carry type under shard_map
    vary_axes = (axis,) if batch_axis is None else (axis, batch_axis)

    def _vary(x):
        return pcast_varying(x, vary_axes)

    o0 = _vary(jnp.zeros((b, h, lq, d), jnp.float32))
    m0 = _vary(jnp.full((b, h, lq), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, h, lq), jnp.float32))
    (o, _, l, _, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v, key_mask), jnp.arange(p_size))
    return _flash_finish(o, l, q.dtype)


def _batch_axis_of(mesh: Mesh, seq_axis: str) -> Optional[str]:
    """The mesh axis to shard the BATCH dim over inside the ring/Ulysses
    shard_map — "data" when present (dp composes with sp: each data row
    runs its own ring), else replicated."""
    return "data" if ("data" in mesh.axis_names
                      and seq_axis != "data") else None


def _check_seq_divisible(q, mesh, axis):
    if q.shape[1] % mesh.shape[axis]:
        raise ValueError(
            f"seq len {q.shape[1]} not divisible by mesh axis "
            f"'{axis}' size {mesh.shape[axis]}")


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis: str = "seq", causal: bool = False,
                   key_mask: Optional[jax.Array] = None) -> jax.Array:
    """Sequence-parallel exact attention over ``mesh[axis]``.

    Inputs [B, L, H, D] are (re)sharded along L; each of the p devices keeps
    its L/p query rows and streams all p K/V blocks through the flash
    recurrence, passing blocks around the ring with ``ppermute`` — peak HBM
    is O(L/p * D) per device, enabling sequences p× longer than one chip
    holds. Returns output sharded the same way. (Host-level entry: places
    the operands, then delegates to ``ring_attention_traced``.)
    """
    _check_seq_divisible(q, mesh, axis)
    ba = _batch_axis_of(mesh, axis)
    sharding = NamedSharding(mesh, P(ba, axis, None, None))
    if key_mask is None:
        key_mask = jnp.ones(q.shape[:2], bool)
    km = jax.device_put(key_mask, NamedSharding(mesh, P(ba, axis)))
    return ring_attention_traced(
        jax.device_put(q, sharding), jax.device_put(k, sharding),
        jax.device_put(v, sharding), mesh, axis, causal, km)


def ring_attention_traced(q: jax.Array, k: jax.Array, v: jax.Array,
                          mesh: Mesh, axis: str = "seq",
                          causal: bool = False,
                          key_mask: Optional[jax.Array] = None) -> jax.Array:
    """`ring_attention` callable from INSIDE a jitted program (a training
    step): no host-side device_put — the shard_map in_specs act as
    sharding constraints and GSPMD inserts the reshard. The batch dim
    shards over "data" when the mesh has one (dp x sp composition). Used
    by the sessionrec train step's sp path (models/seqrec.py)."""
    _check_seq_divisible(q, mesh, axis)
    if key_mask is None:
        key_mask = jnp.ones(q.shape[:2], bool)
    fn = _sharded_fn(_ring_attention_local, mesh, axis, causal,
                     _batch_axis_of(mesh, axis))
    return fn(q, k, v, key_mask)


def _sharded_fn(local_fn, mesh: Mesh, axis: str, causal: bool,
                batch_axis: Optional[str] = None):
    """Cache the jitted shard_map wrapper per (mesh, axis, causal,
    batch_axis) so repeated calls reuse the compiled executable instead
    of re-tracing. Routed through the ops/fn_cache ledger (was a private
    lru_cache) so attention wrapper builds count into
    ``pio_jax_compile_total{family=attention_<impl>}`` and get dispatch
    attribution like every other compiled family. `batch_axis`
    additionally shards the batch dim (dp composed with the sequence
    collective, which only spans `axis`)."""
    from predictionio_tpu.ops.fn_cache import mesh_cached_fn

    def build():
        spec = P(batch_axis, axis, None, None)
        mask_spec = P(batch_axis, axis)
        return jax.jit(shard_map(
            functools.partial(local_fn, axis=axis, causal=causal,
                              batch_axis=batch_axis),
            mesh=mesh, in_specs=(spec, spec, spec, mask_spec),
            out_specs=spec,
            # the vma marking (pcast_varying on the scan carries)
            # satisfies the new checker; the old replication checker has
            # no equivalent
            check_vma=HAS_VMA))

    return mesh_cached_fn(f"attention_{local_fn.__name__.strip('_')}",
                          mesh, (axis, causal, batch_axis), build)


def _ulysses_local(q, k, v, key_mask, *, axis: str, causal: bool,
                   batch_axis=None):  # batch_axis: spec-only, unused here
    """shard_map body: reshard seq-sharded -> head-sharded, dense attention
    on the full sequence for the local head group, reshard back. The key
    mask is all-gathered to full length (tiny: [B, L] bool)."""
    # [B, L/p, H, D] --all_to_all--> [B, L, H/p, D]
    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    full_mask = jax.lax.all_gather(key_mask, axis, axis=1, tiled=True)
    out = mha(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
              causal=causal, key_mask=full_mask)
    return heads_to_seq(out)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                      axis: str = "seq", causal: bool = False,
                      key_mask: Optional[jax.Array] = None) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses construction):
    two ``all_to_all``s swap the sharded dimension seq↔heads so each device
    runs dense attention over the FULL sequence for H/p heads. Requires
    heads divisible by the axis size. Same sharded [B, L, H, D] contract as
    ``ring_attention``."""
    p_size = mesh.shape[axis]
    if q.shape[2] % p_size:
        raise ValueError(
            f"heads {q.shape[2]} not divisible by mesh axis size {p_size}")
    if q.shape[1] % p_size:
        raise ValueError(
            f"seq len {q.shape[1]} not divisible by mesh axis size {p_size}")
    fn = _sharded_fn(_ulysses_local, mesh, axis, causal)
    sharding = NamedSharding(mesh, P(None, axis, None, None))
    if key_mask is None:
        key_mask = jnp.ones(q.shape[:2], bool)
    km = jax.device_put(key_mask, NamedSharding(mesh, P(None, axis)))
    return fn(jax.device_put(q, sharding), jax.device_put(k, sharding),
              jax.device_put(v, sharding), km)
