"""Fused low-precision top-k scoring for large catalogs.

The serving cost of every ALS-backed surface (query server micro-batches,
``pio batchpredict``, fold-in warm-up) is one ``[B,K] @ [K,N]`` matmul
followed by a top-k — and the exact implementation materializes the full
``[B,N]`` score matrix with float32 factors resident. At 10M-item
catalogs that is an HBM-bandwidth wall, not a FLOP wall (ROADMAP item 4).
This module is the kernel layer that replaces it:

* **Quantized factor residency** — item factors stored ``bfloat16`` or
  ``int8`` (per-row scales, f32 accumulation in the matmul). ALX
  (arXiv:2112.02194) demonstrates bf16 factor storage at quality parity
  on TPU; int8 halves it again. The f32 copy stays on HOST (the model
  already holds it) — device factor bytes drop 2-4x.

* **Tiled streaming top-k** (modes ``fused``/``fused_bf16``/
  ``fused_int8``) — item tiles of ``tile_items`` rows are dequantized,
  matmul'd and folded into a per-query *running* top-k carried through a
  ``lax.scan``, so the ``[B,N]`` score matrix never exists; the seen-item
  mask folds into each tile as a ``-inf`` sentinel, so masked and
  unmasked queries ride one kernel family.

* **Two-stage scan→rescore** (mode ``twostage``) — for catalogs where
  even fused-exact is too slow: the factors are rotated into the
  eigenbasis of ``V^T V`` (exactness-preserving — scores are invariant
  under a shared orthogonal rotation) and the scan reads only the
  leading principal columns that carry ``ENERGY_TARGET`` of the spectrum,
  quantized int8. Each tile emits its local top-c into a shortlist, and
  the shortlist alone is rescored EXACTLY in f32 from the host factor
  copy — final scores are exact; only shortlist membership is
  approximate. This is the heavy-offline/light-online split of
  parallel-and-stream (arXiv:2111.00032) applied inside one query.

Every compile registers in the ``ops/fn_cache`` families
``scoring_fused`` / ``scoring_shortlist``, so the ledger stays bounded by
the bucket ladder x scorer-mode families. Every non-exact scorer is
gated at build time (i.e. at deploy warm-up, which drives the first
batch) on recall@k parity against the exact scorer: a build whose probe
recall falls under ``min_recall`` FALLS BACK to exact serving and counts
``pio_scoring_parity_fallback_total`` — a bad quantization can never
silently degrade answers.

Mode selection rides the established knob chain (env > engine.json
``"scorer"`` > server.json ``"scorer"``): ``PIO_SCORER_MODE``,
``PIO_SCORER_TILE_ITEMS``, ``PIO_SCORER_SHORTLIST`` — resolved by
:func:`predictionio_tpu.utils.server_config.scorer_config` and pinned
per process via :func:`set_process_scorer_config` (``pio deploy`` /
``pio batchpredict`` pass the engine.json-aware config through).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.ops.bucketing import bucket_size
from predictionio_tpu.ops.fn_cache import shape_cached_fn
from predictionio_tpu.ops.topk import host_topk, merge_topk

logger = logging.getLogger("pio.scoring")

#: selectable scoring kernels, weakest-assumption first. "exact" is the
#: materialize-then-top_k path (models/als.py); everything else routes
#: through this module.
SCORER_MODES = ("exact", "fused", "fused_bf16", "fused_int8", "twostage")

#: compile-ledger family of the running-top-k streaming kernel: one
#: entry per (quant dtype, batch bucket, k bucket, tile grid, rank,
#: masked) program — bounded by the bucket ladders x modes, never by
#: traffic
FUSED_FAMILY = "scoring_fused"
#: compile-ledger family of the two-stage shortlist scan (k-independent:
#: the final top-k runs on host after the exact rescore)
TWOSTAGE_FAMILY = "scoring_shortlist"

#: spectrum fraction the two-stage scan's truncated principal columns
#: must carry. ALS factor Gramians decay (the data is low-rank plus
#: noise); on a flat-spectrum matrix this keeps nearly every column and
#: the mode degrades gracefully to fused-int8 + exact rescore.
ENERGY_TARGET = 0.96

#: queries in the build-time parity probe (rows sampled from the catalog
#: itself — item-to-item scoring, the similarproduct case, and a span
#: the user rows live in). Small because the exact side runs on host
#: BLAS over the full catalog.
PARITY_PROBE_QUERIES = 8
PARITY_PROBE_K = 10

#: factor rows sampled for the quantization-error gauge (the full-matrix
#: error would re-touch all N*K bytes for a number a sample pins down)
QUANT_ERROR_SAMPLE_ROWS = 4096

#: quantized fused scans carry OVERFETCH*k candidates (min FUSED_MIN_CARRY)
#: through the running top-k and exact-rescore them on host: the true
#: top-k only has to land in the quantized top-(OVERFETCH*k), which
#: quantization noise essentially cannot prevent, instead of surviving
#: near-tie reorderings inside the top-k itself
FUSED_OVERFETCH = 4
FUSED_MIN_CARRY = 32


# ---------------------------------------------------------------------------
# process-level scorer selection
# ---------------------------------------------------------------------------

_PROCESS_CFG = None
_CFG_LOCK = threading.Lock()


def set_process_scorer_config(cfg) -> None:
    """Pin the resolved scorer knobs for this process (``pio deploy`` /
    ``pio batchpredict`` / the query server pass the engine.json-aware
    config through; ``None`` resets to lazy env>server.json resolution —
    the test hook)."""
    global _PROCESS_CFG
    with _CFG_LOCK:
        _PROCESS_CFG = cfg


def process_scorer_config():
    """The scorer knobs every model in this process scores under.

    Resolved lazily from env > server.json when nothing pinned one
    (standalone model use, tests); servers pin the engine.json-aware
    config at startup."""
    global _PROCESS_CFG
    with _CFG_LOCK:
        if _PROCESS_CFG is None:
            from predictionio_tpu.utils.server_config import scorer_config

            _PROCESS_CFG = scorer_config(None)
        return _PROCESS_CFG


def holder_scorer_config(holder):
    """The scorer knobs THIS holder scores under: a per-holder override
    stamped by the multi-tenant server (``_scorer_cfg_override``) beats
    the process pin — one process can keep tenant A's factors int8 and
    tenant B's bf16, each tenant's residency chosen to fit the shared
    device-memory budget."""
    override = getattr(holder, "_scorer_cfg_override", None)
    return override if override is not None else process_scorer_config()


# ---------------------------------------------------------------------------
# streaming kernels (module-level jits shared across shapes; the
# shape_cached_fn wrappers below are the per-bucket compile ledger)
# ---------------------------------------------------------------------------

def _tile_scores(u, v_tile, s_tile):
    """One tile's [B, T] f32 scores: dequantize + matmul with f32
    accumulation. ``s_tile is None`` means the tile needs no scale
    (f32/bf16 storage); int8 tiles carry per-row scales."""
    if v_tile.dtype == jnp.bfloat16:
        # bf16 x bf16 -> f32 accumulation (the MXU-native ALX layout);
        # u is tiny, so casting it costs nothing while the tile read —
        # the bandwidth hog — stays half-width
        sc = jax.lax.dot_general(
            u.astype(jnp.bfloat16), v_tile, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    elif v_tile.dtype == jnp.int8:
        sc = u @ v_tile.T.astype(jnp.float32)
    else:
        sc = u @ v_tile.T
    if s_tile is not None:
        sc = sc * s_tile[None, :]
    return sc


def _scan_xs(b, v_tiles, scales, mask, tile):
    """Assemble one tile-scan's xs tuple: factor tiles, optional scales,
    the optional mask re-laid [B, n_pad] -> [n_tiles, B, T] so each scan
    step carries one tile of mask alongside one tile of factors, and the
    per-tile id bases."""
    n_tiles = v_tiles.shape[0]
    xs = [v_tiles]
    if scales is not None:
        xs.append(scales)
    if mask is not None:
        xs.append(jnp.moveaxis(mask.reshape(b, n_tiles, tile), 1, 0))
    xs.append(jnp.arange(n_tiles, dtype=jnp.int32) * tile)
    return tuple(xs)


def _step_scores(u, xs, has_scales: bool, has_mask: bool, n_items):
    """Unpack one scan step's xs (as `_scan_xs` packed them) into the
    tile's sentineled [B, T] scores + global ids: dequantize + matmul,
    then ``-inf`` out padding rows (ids >= n_items) and masked items —
    the single definition of the sentinel rule both scans share."""
    parts = list(xs)
    v_tile = parts.pop(0)
    s_tile = parts.pop(0) if has_scales else None
    m_tile = parts.pop(0) if has_mask else None
    base = parts.pop(0)
    sc = _tile_scores(u, v_tile, s_tile)
    ids = base + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
    sentinel = ids >= n_items
    if m_tile is not None:
        sentinel = sentinel | m_tile
    return jnp.where(sentinel, -jnp.inf, sc), ids


@functools.partial(jax.jit, static_argnames=("num", "tile"))
def _fused_topk_scan(u, v_tiles, scales, n_items, mask, num: int,
                     tile: int):
    """Streaming top-k: scan item tiles, fold each into a per-query
    running top-``num`` — the [B, N] score matrix never exists. ``mask``
    (optional [B, n_pad] bool, True = excluded) folds into each tile as
    a ``-inf`` sentinel, so masked and unmasked queries share this one
    program family."""
    b = u.shape[0]
    has_scales, has_mask = scales is not None, mask is not None

    def step(carry, xs):
        vals, idx = carry
        sc, ids = _step_scores(u, xs, has_scales, has_mask, n_items)
        cv = jnp.concatenate([vals, sc], axis=1)
        ci = jnp.concatenate([idx, ids], axis=1)
        tv, ti = jax.lax.top_k(cv, num)
        return (tv, jnp.take_along_axis(ci, ti, axis=1)), None

    init = (jnp.full((b, num), -jnp.inf, jnp.float32),
            jnp.full((b, num), -1, jnp.int32))
    (tv, ti), _ = jax.lax.scan(step, init,
                               _scan_xs(b, v_tiles, scales, mask, tile))
    return tv, ti


@functools.partial(jax.jit, static_argnames=("cand", "tile"))
def _shortlist_scan(u, v_tiles, scales, n_items, mask, cand: int,
                    tile: int):
    """Two-stage stage 1: each tile emits its LOCAL top-``cand``
    (approximate scores) — no cross-tile merge, which the exact rescore
    makes unnecessary: the shortlist only has to CONTAIN the true top-k,
    and a true winner is in its own tile's local top-c long before it is
    in the global top-S. Output is [B, n_tiles * cand] candidate ids."""
    b = u.shape[0]
    has_scales, has_mask = scales is not None, mask is not None

    def step(_, xs):
        sc, ids = _step_scores(u, xs, has_scales, has_mask, n_items)
        tv, ti = jax.lax.top_k(sc, cand)
        return None, (tv, jnp.take_along_axis(ids, ti, axis=1))

    _, (tv, ti) = jax.lax.scan(step, None,
                               _scan_xs(b, v_tiles, scales, mask, tile))
    # [n_tiles, B, c] -> [B, n_tiles * c]
    return (jnp.moveaxis(tv, 0, 1).reshape(b, -1),
            jnp.moveaxis(ti, 0, 1).reshape(b, -1))


# ---------------------------------------------------------------------------
# quantization + packing
# ---------------------------------------------------------------------------

def _pow2_tile(tile_items: int, n_items: int) -> int:
    """The static tile width: the configured tile rounded up to a power
    of two, shrunk to one tile for small catalogs — the tile grid is
    part of the compile key, so the rounding rule must be a single
    definition (the bucketing discipline applied to the item axis)."""
    t = bucket_size(max(1, tile_items))
    return min(t, bucket_size(n_items))


def _pack_tiles(arr: np.ndarray, tile: int):
    """[N, K] -> ([n_tiles, tile, K], n_pad): pad item rows up to a
    whole tile grid (pad rows are sentineled by id inside the kernels,
    so their values never matter)."""
    n = arr.shape[0]
    n_pad = -(-n // tile) * tile
    if n_pad != n:
        arr = np.concatenate(
            [arr, np.zeros((n_pad - n,) + arr.shape[1:], arr.dtype)])
    return arr.reshape(n_pad // tile, tile, *arr.shape[1:]), n_pad


def _quantize_int8(v: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8: q = round(v / s), s = row-max / 127.
    Zero rows get scale 1 so dequantization stays finite."""
    s = np.abs(v).max(axis=1) / 127.0
    s = np.where(s == 0, 1.0, s).astype(np.float32)
    q = np.clip(np.rint(v / s[:, None]), -127, 127).astype(np.int8)
    return q, s


def _principal_rotation(v: np.ndarray) -> Tuple[np.ndarray, int]:
    """Eigenbasis of V^T V (descending eigenvalue) and the column count
    carrying ``ENERGY_TARGET`` of the spectrum. Scores are invariant
    under rotating BOTH sides by W (orthogonal), which is what lets the
    stage-1 scan truncate to the leading columns without approximating
    anything except the discarded tail's contribution."""
    g = (v.T @ v).astype(np.float64)
    w, vecs = np.linalg.eigh(g)
    order = np.argsort(w)[::-1]
    w, vecs = np.maximum(w[order], 0.0), vecs[:, order]
    total = w.sum()
    if total <= 0:
        return vecs.astype(np.float32), v.shape[1]
    energy = np.cumsum(w) / total
    dims = int(np.searchsorted(energy, ENERGY_TARGET) + 1)
    # round up to 8 (lane-friendly) and clamp into [8, K]
    dims = min(v.shape[1], max(8, -(-dims // 8) * 8))
    return vecs.astype(np.float32), dims


# ---------------------------------------------------------------------------
# the scorer
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ItemScorer:
    """Device-resident (possibly quantized) item factors plus the tiled
    streaming top-k over them, for ONE factor matrix identity.

    Built lazily on the first device-scored batch (which, through the
    deploy warm-up ladder, means at deploy time, off the serving path)
    and cached per V identity by the model — a fold-in apply that swaps
    V requantizes by rebuilding, exactly like the resident f32 copy.
    ``active_mode`` is the mode actually serving: the build-time parity
    probe demotes a scorer whose recall@10 against the exact path falls
    under ``min_recall`` to ``"exact"`` (the caller then routes down the
    legacy materialized path), so a catalog that quantizes badly keeps
    its exact answers.
    """

    mode: str                 # requested mode
    active_mode: str          # mode after the parity gate
    n_items: int
    rank: int
    tile: int
    n_tiles: int
    scan_rank: int            # truncated rank of the stage-1 scan
    shortlist: int            # candidates per query (twostage; else 0)
    cand_per_tile: int        # local top-c per tile (twostage; else 0)
    quantization: str         # "float32" | "bfloat16" | "int8"
    factor_bytes: int         # device-resident factor + scale bytes
    exact_bytes: int          # the f32 baseline those bytes replace
    recall_probe: float       # build-time probe recall@PARITY_PROBE_K
    quant_error: float        # sampled max relative dequantization error
    _tiles: Optional[jax.Array] = None      # [n_tiles, T, scan_rank]
    _scales: Optional[jax.Array] = None     # [n_tiles, T] (int8 only)
    _v_host: Optional[np.ndarray] = None    # f32 rescore source
    _rotation: Optional[np.ndarray] = None  # [K, scan_rank] (twostage)

    @property
    def active(self) -> bool:
        """False when the parity gate demoted this scorer to exact."""
        return self.active_mode != "exact"

    # -- scoring -------------------------------------------------------------

    def topk(self, u_batch: np.ndarray, k: int,
             mask: Optional[np.ndarray] = None
             ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-``k`` (scores, ids) for ``u_batch`` [B, K] f32 rows;
        ``mask`` [B, n_items] bool excludes items (True = excluded).
        Batch and k are bucketed internally (ops/bucketing), so the
        compile ledger stays on the power-of-two ladder; results come
        back trimmed to [B, k]."""
        from predictionio_tpu.obs.scoring_stats import scoring_metrics

        if not self.active:
            raise RuntimeError(
                "scorer was parity-demoted to exact and holds no device "
                "residency — callers must check .active and route the "
                "exact path")
        b = u_batch.shape[0]
        k = min(k, self.n_items)
        b_pad = bucket_size(b)
        u = np.zeros((b_pad, self.rank), np.float32)
        u[:b] = u_batch
        mask_pad = None
        if mask is not None:
            n_pad = self.n_tiles * self.tile
            mask_pad = np.ones((b_pad, n_pad), bool)
            mask_pad[:b, :self.n_items] = mask
        m = scoring_metrics()
        m.batches.inc(mode=self.active_mode)
        m.tiles.inc(self.n_tiles)
        if self.active_mode == "twostage":
            scores, idx = self._topk_twostage(u, k, mask_pad)
        else:
            scores, idx = self._topk_fused(u, k, mask_pad)
        return scores[:b, :k], idx[:b, :k]

    def _topk_fused(self, u: np.ndarray, k: int,
                    mask_pad: Optional[np.ndarray]):
        quantized = self.quantization != "float32"
        # quantized scans OVERFETCH the running carry: quantization noise
        # (~0.2-0.4% relative) reorders near-ties, so the true top-k is
        # asked to sit in the quantized top-(OVERFETCH*k) — a far weaker
        # requirement — and the small carried set is rescored EXACTLY in
        # f32 from the host factor copy. Final scores are exact; only
        # carry membership is approximate (the FAISS-style rescore
        # discipline). f32 tiles need neither.
        want = max(k, 1) if not quantized else max(FUSED_OVERFETCH * k,
                                                   FUSED_MIN_CARRY)
        k_pad = min(bucket_size(want), self.n_items)
        key = (self.quantization, u.shape, k_pad, self.n_tiles,
               self.tile, self.scan_rank, self.n_items,
               mask_pad is not None)
        # shape_cached_fn returns the SAME shared jit (executables live
        # in jit's cache); its build counter is the per-bucket compile
        # ledger pio_jax_compile_total{family=scoring_fused} reads
        fn = shape_cached_fn(FUSED_FAMILY, key, lambda: _fused_topk_scan)
        out = fn(jnp.asarray(u), self._tiles, self._scales,
                 jnp.int32(self.n_items),
                 jnp.asarray(mask_pad) if mask_pad is not None else None,
                 k_pad, self.tile)
        scores, idx = jax.device_get(out)    # one fetch
        if not quantized:
            return scores, idx
        return self._rescore_exact(np.asarray(u, np.float32),
                                   np.asarray(scores), np.asarray(idx), k)

    def _rescore_exact(self, u: np.ndarray, approx: np.ndarray,
                       cand: np.ndarray, k: int):
        """Exact f32 rescore of per-query candidate ids from the host
        factor copy + host top-k. Candidates the scan sentineled to
        -inf (masked / padding / carry inits) stay -inf."""
        valid = np.isfinite(approx) & (cand >= 0) & (cand < self.n_items)
        safe = np.where(valid, cand, 0)
        sc = np.einsum("bk,bsk->bs", u, self._v_host[safe],
                       dtype=np.float32, casting="same_kind")
        sc = np.where(valid, sc, -np.inf)
        # the shared shortlist merge (ops/topk): one candidate set is
        # just a 1-way merge, which buys the deterministic id tie-break
        # the cross-shard path relies on
        return merge_topk([(sc, np.where(valid, cand, -1))], k)

    def _topk_twostage(self, u: np.ndarray, k: int,
                       mask_pad: Optional[np.ndarray]):
        from predictionio_tpu.obs.scoring_stats import scoring_metrics

        u_scan = u if self._rotation is None else \
            np.ascontiguousarray((u @ self._rotation).astype(np.float32))
        # a request wanting more than the configured shortlist widens
        # the per-tile candidate count for THIS call (bucketed to the
        # power-of-two ladder so the widened shapes stay ledger-bounded)
        # — the rescore can only return ids the scan emitted, so the
        # candidate set must always be at least k wide
        cand = self.cand_per_tile
        if self.n_tiles * cand < k:
            cand = min(self.tile, bucket_size(-(-k // self.n_tiles)))
        if mask_pad is not None:
            # masked batches widen to k candidates PER TILE: a
            # concentrated mask (a whitelist whose survivors share one
            # tile) leaves every other tile fully sentineled, so the
            # per-tile-containment argument the configured shortlist
            # relies on — and the unmasked parity probe validates —
            # does not hold under masking
            cand = max(cand, min(self.tile, bucket_size(k)))
        key = (u.shape, cand, self.n_tiles, self.tile,
               self.scan_rank, self.n_items, mask_pad is not None)
        fn = shape_cached_fn(TWOSTAGE_FAMILY, key,
                             lambda: _shortlist_scan)
        out = fn(jnp.asarray(u_scan), self._tiles, self._scales,
                 jnp.int32(self.n_items),
                 jnp.asarray(mask_pad) if mask_pad is not None else None,
                 cand, self.tile)
        approx, cand = (np.asarray(a) for a in jax.device_get(out))
        m = scoring_metrics()
        m.shortlist.observe(float(cand.shape[1]))
        m.rescore_fraction.observe(cand.shape[1] / max(1, self.n_items))
        # stage 2: EXACT f32 rescore of the shortlist — final scores are
        # exact, only membership is approximate; candidates the scan
        # sentineled (masked items, padding ids) carry -inf approx
        # scores and stay -inf
        return self._rescore_exact(u, approx, cand, k)

    # -- status --------------------------------------------------------------

    def status(self) -> dict:
        """The /deploy/status.json + bench echo block."""
        return {
            "mode": self.mode,
            "activeMode": self.active_mode,
            "quantization": self.quantization,
            "items": self.n_items,
            "rank": self.rank,
            "scanRank": self.scan_rank,
            "tileItems": self.tile,
            "tiles": self.n_tiles,
            "shortlist": self.shortlist,
            "factorBytes": self.factor_bytes,
            "exactBytes": self.exact_bytes,
            "recallProbe": round(self.recall_probe, 4),
            "quantError": round(self.quant_error, 6),
        }


def build_scorer(V: np.ndarray, cfg=None,
                 min_recall: Optional[float] = None,
                 device=None) -> ItemScorer:
    """Build an :class:`ItemScorer` over item factors ``V`` [N, K] f32
    under the resolved scorer knobs, running the parity gate before it
    may serve. ``cfg`` defaults to the process scorer config.
    ``device`` pins the quantized residency to one device of the mesh
    (the model-parallel sharded path); None keeps jax's default."""
    if cfg is None:
        cfg = process_scorer_config()
    mode = cfg.mode
    if mode not in SCORER_MODES:
        raise ValueError(f"unknown scorer mode {mode!r}: expected one of "
                         f"{'|'.join(SCORER_MODES)}")
    if mode == "exact":
        raise ValueError("exact mode never builds an ItemScorer — the "
                         "caller serves the legacy materialized path")
    v = np.ascontiguousarray(np.asarray(V), np.float32)
    n_items, rank = v.shape
    tile = _pow2_tile(cfg.tile_items, n_items)
    exact_bytes = v.nbytes
    rotation = None
    scan_rank = rank
    quant_error = 0.0

    if mode == "twostage":
        rot, dims = _principal_rotation(v)
        rotation = np.ascontiguousarray(rot[:, :dims])
        scan_rank = dims
        v_scan = np.ascontiguousarray((v @ rotation).astype(np.float32))
        q, s = _quantize_int8(v_scan)
        quant_error = _sampled_quant_error(v_scan, q, s)
        tiles, _ = _pack_tiles(q, tile)
        scales, _ = _pack_tiles(s, tile)
        quantization = "int8"
    elif mode == "fused_int8":
        q, s = _quantize_int8(v)
        quant_error = _sampled_quant_error(v, q, s)
        tiles, _ = _pack_tiles(q, tile)
        scales, _ = _pack_tiles(s, tile)
        quantization = "int8"
    elif mode == "fused_bf16":
        vb = v.astype(jnp.bfloat16)
        quant_error = _sampled_quant_error(
            v, np.asarray(vb, np.float32), None)
        tiles, _ = _pack_tiles(np.asarray(vb), tile)
        scales = None
        quantization = "bfloat16"
    else:   # fused (f32, tiled — memory unchanged, [B,N] never built)
        tiles, _ = _pack_tiles(v, tile)
        scales = None
        quantization = "float32"

    n_tiles = tiles.shape[0]
    shortlist = 0
    cand_per_tile = 0
    if mode == "twostage":
        shortlist = max(1, int(cfg.shortlist))
        cand_per_tile = min(tile, max(1, -(-shortlist // n_tiles)))
        shortlist = cand_per_tile * n_tiles

    tiles_dev = (jax.device_put(tiles, device) if device is not None
                 else jax.device_put(tiles))
    scales_dev = None
    if scales is not None:
        scales_dev = (jax.device_put(scales, device) if device is not None
                      else jax.device_put(scales))
    factor_bytes = int(tiles.nbytes
                       + (scales.nbytes if scales is not None else 0))
    scorer = ItemScorer(
        mode=mode, active_mode=mode, n_items=n_items, rank=rank,
        tile=tile, n_tiles=n_tiles, scan_rank=scan_rank,
        shortlist=shortlist, cand_per_tile=cand_per_tile,
        quantization=quantization, factor_bytes=factor_bytes,
        exact_bytes=exact_bytes, recall_probe=1.0,
        quant_error=quant_error,
        _tiles=tiles_dev, _scales=scales_dev, _v_host=v,
        _rotation=rotation)
    _parity_gate(scorer, v,
                 cfg.min_recall if min_recall is None else min_recall)
    _observe_build(scorer)
    return scorer


def _sampled_quant_error(v: np.ndarray, q: np.ndarray,
                         s: Optional[np.ndarray]) -> float:
    """Max relative dequantization error over a row sample — the
    ``pio_scoring_quant_error`` gauge (a sample: the full-matrix number
    would re-touch every byte the quantization just wrote)."""
    n = v.shape[0]
    rows = np.linspace(0, n - 1,
                       num=min(QUANT_ERROR_SAMPLE_ROWS, n)).astype(int)
    vv = v[rows]
    deq = (q[rows].astype(np.float32) * s[rows, None] if s is not None
           else q[rows].astype(np.float32))
    denom = max(float(np.abs(vv).max()), 1e-30)
    return float(np.abs(deq - vv).max() / denom)


def _parity_gate(scorer: ItemScorer, v: np.ndarray,
                 min_recall: float) -> None:
    """Recall@k parity probe vs the exact scorer: catalog rows as probe
    queries, exact side on host BLAS. Runs ONCE per scorer build — at
    deploy warm-up, since the warm-up ladder drives the first batch —
    and demotes a failing scorer to exact."""
    n = scorer.n_items
    k = min(PARITY_PROBE_K, n)
    if k == 0:
        return
    rows = np.linspace(0, n - 1,
                       num=min(PARITY_PROBE_QUERIES, n)).astype(int)
    probe = np.ascontiguousarray(v[rows])
    _, exact_idx = host_topk(probe @ v.T, k)
    _, got_idx = scorer.topk(probe, k)
    hits = sum(len(set(a.tolist()) & set(b.tolist()))
               for a, b in zip(exact_idx, got_idx))
    recall = hits / float(exact_idx.shape[0] * k)
    scorer.recall_probe = recall
    if recall < min_recall:
        from predictionio_tpu.obs.scoring_stats import scoring_metrics

        logger.warning(
            "scorer parity gate failed: mode=%s recall@%d=%.4f < %.4f "
            "on a %dx%d catalog — falling back to exact serving",
            scorer.mode, k, recall, min_recall, scorer.n_items,
            scorer.rank)
        scoring_metrics().parity_fallback.inc(mode=scorer.mode)
        scorer.active_mode = "exact"
        # drop the device residency: a demoted scorer must not hold
        # quantized copies nobody will read
        scorer._tiles = None
        scorer._scales = None
        scorer.factor_bytes = 0


def _observe_build(scorer: ItemScorer) -> None:
    from predictionio_tpu.obs.scoring_stats import scoring_metrics

    m = scoring_metrics()
    m.quant_error.set(scorer.quant_error, mode=scorer.mode)
    m.parity_recall.set(scorer.recall_probe, mode=scorer.mode)


# ---------------------------------------------------------------------------
# model-parallel sharded scorer (ALX-style: factors past one device's HBM)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShardedScorer:
    """Item factors sharded row-wise over the device mesh, one
    :class:`ItemScorer` per shard, merged on host.

    The shard map is ``parallel/distributed.contiguous_range`` — the
    same contiguous disjoint row ranges batchpredict's shard->merge
    shape uses — so shard ``r`` of ``S`` owns rows ``[lo, hi)`` of ``V``
    and its residency lands on device ``r % n_devices``. Each shard runs
    the configured kernel over ITS rows only and emits a local top-k
    shortlist with exact f32 scores (quantized shards exact-rescore from
    their host slice, exactly as unsharded); shard-local ids shift by
    ``lo`` into catalog ids and :func:`ops.topk.merge_topk` folds the
    shortlists into the global top-k. Because every shard's scores are
    exact and every catalog row belongs to exactly one shard, a global
    top-k winner is necessarily inside its own shard's local top-k — so
    the merge is exact whenever the per-shard kernels are (mode
    ``exact``/``fused``: always; quantized modes: whenever shortlist
    membership holds, the same recall contract the unsharded scorer is
    parity-gated on).

    Mode ``exact`` shards the host BLAS matmul instead of device
    residency (the dispatch-crossover discipline: exact mode never held
    device factors to begin with); a shard whose parity gate demoted it
    likewise serves exact host BLAS over its own rows — per-shard
    fallback, never a silent whole-catalog degrade.
    """

    mode: str                  # requested mode
    active_mode: str           # mode when ALL shards serve it, else "exact"
    n_items: int
    rank: int
    n_shards: int
    ranges: tuple              # ((lo, hi), ...) per shard
    shards: tuple              # per-shard ItemScorer; None = exact serving
    factor_bytes: int          # device-resident bytes across all shards
    max_shard_factor_bytes: int   # the per-device budget a shard must fit
    exact_bytes: int
    recall_probe: float
    _v_shards: tuple = ()      # per-shard host f32 slices

    @property
    def active(self) -> bool:
        """A sharded scorer always serves — a demoted shard falls back
        to exact host BLAS over its own rows, not to the caller."""
        return True

    def topk(self, u_batch: np.ndarray, k: int,
             mask: Optional[np.ndarray] = None
             ) -> Tuple[np.ndarray, np.ndarray]:
        """Global top-``k`` (scores, catalog ids): per-shard local
        shortlists merged via the shared k-way merge. ``mask``
        [B, n_items] slices per shard columns, so a whitelist
        concentrated in one shard sentinels every other shard entirely
        and the merge keeps only real survivors."""
        b = u_batch.shape[0]
        k = min(k, self.n_items)
        if k <= 0:
            empty = np.zeros((b, 0))
            return empty.astype(np.float32), empty.astype(np.int64)
        u = np.ascontiguousarray(np.asarray(u_batch, np.float32))
        shortlists = []
        for (lo, hi), scorer, v_shard in zip(
                self.ranges, self.shards, self._v_shards):
            m = None
            if mask is not None:
                m = np.ascontiguousarray(mask[:, lo:hi])
            k_s = min(k, hi - lo)
            if scorer is not None and scorer.active:
                vals, ids = scorer.topk(u, k_s, mask=m)
            else:
                sc = u @ v_shard.T
                if m is not None:
                    sc = np.where(m, -np.inf, sc)
                vals, ids = host_topk(sc, k_s)
            shortlists.append((vals, np.asarray(ids, np.int64) + lo))
        return merge_topk(shortlists, k)

    def status(self) -> dict:
        """The /deploy/status.json + bench echo block (sharded form)."""
        return {
            "mode": self.mode,
            "activeMode": self.active_mode,
            "sharded": True,
            "shards": self.n_shards,
            "ranges": [list(r) for r in self.ranges],
            "items": self.n_items,
            "rank": self.rank,
            "factorBytes": self.factor_bytes,
            "maxShardFactorBytes": self.max_shard_factor_bytes,
            "exactBytes": self.exact_bytes,
            "recallProbe": round(self.recall_probe, 4),
            "shardStatus": [s.status() for s in self.shards
                            if s is not None],
        }


def build_sharded_scorer(V: np.ndarray, cfg=None,
                         min_recall: Optional[float] = None,
                         shards: Optional[int] = None) -> ShardedScorer:
    """Build a :class:`ShardedScorer` over ``V`` [N, K] f32: row-shard
    via ``contiguous_range``, build one per-shard kernel under the same
    config (each parity-gated against ITS shard's exact top-k), then
    probe the MERGED result against the global exact top-k for the
    status block's recall figure."""
    from predictionio_tpu.parallel.distributed import contiguous_range

    if cfg is None:
        cfg = process_scorer_config()
    if shards is None:
        shards = int(getattr(cfg, "shards", 1) or 1)
    v = np.ascontiguousarray(np.asarray(V), np.float32)
    n_items, rank = v.shape
    shards = max(1, min(shards, n_items))
    devices = jax.devices()
    ranges, shard_scorers, v_shards = [], [], []
    for r in range(shards):
        lo, hi = contiguous_range(n_items, r, shards)
        v_shard = np.ascontiguousarray(v[lo:hi])
        scorer = None
        if cfg.mode != "exact":
            scorer = build_scorer(v_shard, cfg, min_recall,
                                  device=devices[r % len(devices)])
        ranges.append((lo, hi))
        shard_scorers.append(scorer)
        v_shards.append(v_shard)
    all_active = all(s is not None and s.active for s in shard_scorers)
    factor_bytes = sum(s.factor_bytes for s in shard_scorers
                       if s is not None)
    max_shard = max((s.factor_bytes for s in shard_scorers
                     if s is not None), default=0)
    out = ShardedScorer(
        mode=cfg.mode,
        active_mode=cfg.mode if (all_active and cfg.mode != "exact")
        else "exact",
        n_items=n_items, rank=rank, n_shards=shards,
        ranges=tuple(ranges), shards=tuple(shard_scorers),
        factor_bytes=factor_bytes, max_shard_factor_bytes=max_shard,
        exact_bytes=v.nbytes, recall_probe=1.0,
        _v_shards=tuple(v_shards))
    # global probe: merged shortlists vs whole-catalog exact top-k (the
    # per-shard gates already ran inside build_scorer; this one feeds
    # the status block AND catches a merge regression outright)
    n = n_items
    k = min(PARITY_PROBE_K, n)
    if k > 0:
        rows = np.linspace(0, n - 1,
                           num=min(PARITY_PROBE_QUERIES, n)).astype(int)
        probe = np.ascontiguousarray(v[rows])
        _, exact_idx = host_topk(probe @ v.T, k)
        _, got_idx = out.topk(probe, k)
        hits = sum(len(set(a.tolist()) & set(b.tolist()))
                   for a, b in zip(exact_idx, got_idx))
        out.recall_probe = hits / float(exact_idx.shape[0] * k)
    return out


# ---------------------------------------------------------------------------
# model-side cache + status helpers
# ---------------------------------------------------------------------------

#: serializes scorer BUILDS (not lookups): a cold cache under the query
#: server's multi-threaded predict executor would otherwise pay N
#: duplicate multi-second quantize+probe builds of the SAME factor
#: matrix at once — and transiently hold N device copies
_BUILD_LOCK = threading.Lock()


def scorer_for(holder, V: np.ndarray) -> Optional[ItemScorer]:
    """The cached :class:`ItemScorer` for ``holder``'s factor matrix
    ``V`` under the CURRENT process scorer config, (re)building when V's
    identity or the config changed — the ``V_device`` residency
    discipline applied to quantized copies, which is also what makes a
    fold-in apply requantize: an item fold swaps V, the identity check
    misses, and the next scored batch (the fold-in controller's pre-swap
    warm drive) rebuilds from the updated rows. Returns ``None`` in
    unsharded exact mode (callers keep the legacy path); with
    ``shards > 1`` every mode — exact included — routes through the
    model-parallel :class:`ShardedScorer`. A per-holder
    ``_scorer_cfg_override`` (multi-tenant serving) beats the process
    pin, so co-hosted tenants can hold different quantized residencies."""
    cfg = holder_scorer_config(holder)
    shards = int(getattr(cfg, "shards", 1) or 1)
    if cfg.mode == "exact" and shards <= 1:
        return None
    key = cfg.cache_key()
    cached = getattr(holder, "_scorer_cache", None)
    if cached is not None and cached[0] is V and cached[1] == key:
        return cached[2]
    with _BUILD_LOCK:
        cached = getattr(holder, "_scorer_cache", None)   # lost the race?
        if cached is None or cached[0] is not V or cached[1] != key:
            built = (build_sharded_scorer(V, cfg) if shards > 1
                     else build_scorer(V, cfg))
            cached = (V, key, built)
            holder._scorer_cache = cached
    return cached[2]


def unit_scorer_status(result) -> list:
    """Per-model scorer echo for /deploy/status.json: the status dict of
    every model in a TrainResult that has built a scorer (quantized
    residency is lazy, so a unit that never scored on device reports
    none)."""
    out = []
    for model in getattr(result, "models", ()) or ():
        cached = getattr(model, "_scorer_cache", None)
        if cached is not None:
            out.append(cached[2].status())
    return out


# ---------------------------------------------------------------------------
# Pallas variant (TPU): fused dequantize -> matmul -> local top-c
# ---------------------------------------------------------------------------

def pallas_available() -> bool:
    """The Pallas shortlist kernel runs only on a real TPU backend; the
    lax.scan kernels above are the portable lowering everywhere else
    (and the numerics oracle the interpret-mode test checks against)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def build_pallas_shortlist(tile: int, cand: int, interpret: bool = False):
    """Build the Pallas stage-1 kernel: grid over item tiles, each
    program dequantizing its [T, R] int8 tile in VMEM, scoring it on the
    MXU with f32 accumulation, and emitting the tile's local top-c by
    iterated masked argmax (top_k is not a Pallas primitive; c is small,
    so c passes over the [B, T] tile stay cheap VPU work).

    Returns ``fn(u [B,R] f32, tiles [nt,T,R] int8, scales [nt,T] f32,
    n_items) -> (vals [nt,B,c], ids [nt,B,c])`` or raises ImportError
    where Pallas is unavailable. ``interpret=True`` runs the kernel on
    the CPU interpreter (the parity test path)."""
    from jax.experimental import pallas as pl

    def kernel(n_ref, u_ref, v_ref, s_ref, vals_ref, ids_ref):
        t = pl.program_id(0)
        u = u_ref[...]                                   # [B, R] f32
        v = v_ref[0].astype(jnp.float32)                 # [T, R]
        sc = jax.lax.dot_general(
            u, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)          # [B, T]
        sc = sc * s_ref[0][None, :]
        base = t * tile
        ids = base + jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        sc = jnp.where(ids >= n_ref[0], -jnp.inf, sc)

        def body(j, carry):
            sc_c = carry
            m = jnp.max(sc_c, axis=1)                    # [B]
            am = jnp.argmax(sc_c, axis=1).astype(jnp.int32)
            vals_ref[0, :, j] = m
            ids_ref[0, :, j] = base + am
            # knock the winner out for the next pass
            hit = (jax.lax.broadcasted_iota(jnp.int32, sc_c.shape, 1)
                   == am[:, None])
            return jnp.where(hit, -jnp.inf, sc_c)

        jax.lax.fori_loop(0, cand, body, sc)

    def fn(u, tiles, scales, n_items):
        nt, t, r = tiles.shape
        b = u.shape[0]
        n_arr = jnp.full((1,), n_items, jnp.int32)
        return pl.pallas_call(
            kernel,
            grid=(nt,),
            in_specs=[
                pl.BlockSpec((1,), lambda i: (0,)),
                pl.BlockSpec((b, r), lambda i: (0, 0)),
                pl.BlockSpec((1, t, r), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, t), lambda i: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, b, cand), lambda i: (i, 0, 0)),
                pl.BlockSpec((1, b, cand), lambda i: (i, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((nt, b, cand), jnp.float32),
                jax.ShapeDtypeStruct((nt, b, cand), jnp.int32),
            ],
            interpret=interpret,
        )(n_arr, u, tiles, scales)

    return fn
