"""JAX compute kernels (the rebuild's "native layer").

The reference's kernel layer is Spark MLlib invoked from engine templates
(SURVEY.md intro); here it is hand-written JAX designed for the TPU:
segment-sum Gramians feeding the MXU-batched Cholesky solves of ALS,
vectorized counting for NaiveBayes, optax-driven LogReg, and sparse
cooccurrence counting. `attention` adds the long-context layer: flash-style
blockwise attention plus ring / Ulysses sequence parallelism over a Mesh.
"""

from predictionio_tpu.ops.attention import (   # noqa: F401
    blockwise_attention, mha, ring_attention, ulysses_attention,
)
