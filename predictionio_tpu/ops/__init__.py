"""JAX compute kernels (the rebuild's "native layer").

The reference's kernel layer is Spark MLlib invoked from engine templates
(SURVEY.md intro); here it is hand-written JAX designed for the TPU:
segment-sum Gramians feeding the MXU-batched Cholesky solves of ALS,
vectorized counting for NaiveBayes, optax-driven LogReg, and sparse
cooccurrence counting.
"""
