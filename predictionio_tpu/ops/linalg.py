"""Batched dense linear algebra for the MXU/VPU.

Batched positive-definite solves: the per-segment normal equations of ALS
([S, K, K] @ x = [S, K]) — the direct-solve step MLlib ALS performs per
user/item block inside `ALS.run` (invoked by the reference templates at
examples/.../ALSAlgorithm.scala:85). K is small (the factor rank, 10-128)
and S is huge (one system per user/item), a shape XLA's LAPACK-style
`cho_factor` handles poorly on TPU: it loops over K with batched
dynamic-slice updates that round-trip HBM every step.

Three implementations, fastest selected automatically:

- ``cholesky_solve_xla``    — jax.scipy cho_factor/cho_solve (reference).
- ``cholesky_solve_vec``    — K-step right-looking Cholesky hand-vectorized
  over the batch: every step is one fused VPU pass over [S, K, K]. ~27x
  faster than cho_solve at ML-20M shape (S=140k, K=10) on v5e.
- ``cholesky_solve_pallas`` — Pallas TPU kernel; each batch tile of 128
  systems lives in VMEM for the whole factorization in a batch-in-lanes
  [K, K, T] layout (batch dim = vector lanes), so the K-step recurrence
  never touches HBM. The layout is not expressible through XLA's batched
  linalg, which is the point of hand-writing it.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

#: ranks up to this use the Pallas kernel on TPU ([K,K,128] tiles stay
#: well under VMEM and the unrolled program stays small)
_PALLAS_MAX_K = 64
_PALLAS_TILE = 128


def _is_tpu_backend() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover - no devices at all
        return False


# ---------------------------------------------------------------------------
# XLA reference path
# ---------------------------------------------------------------------------

@jax.jit
def cholesky_solve_xla(A: jax.Array, b: jax.Array) -> jax.Array:
    """Solve SPD A[s] x = b[s] via jax.scipy (the XLA-library path)."""
    chol, lower = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve((chol, lower), b)


# ---------------------------------------------------------------------------
# Batch-vectorized path (pure JAX)
# ---------------------------------------------------------------------------

def _vec_cholesky(A: jax.Array) -> jax.Array:
    """Right-looking Cholesky, one fused batch-wide update per column."""
    k = A.shape[-1]
    rows = jnp.arange(k)

    def body(j, L):
        d = jax.lax.rsqrt(jnp.maximum(L[:, j, j], 1e-30))       # [S]
        col = L[:, :, j] * d[:, None]                           # [S, K]
        col = jnp.where((rows >= j)[None, :], col, 0.0)
        upd = col[:, :, None] * col[:, None, :]                 # [S, K, K]
        L = L - jnp.where((rows > j)[None, None, :], upd, 0.0)
        return L.at[:, :, j].set(col)

    return jax.lax.fori_loop(0, k, body, A)


def _vec_solve_tri(L: jax.Array, b: jax.Array) -> jax.Array:
    """x = (L L^T)^{-1} b by forward+backward substitution over columns."""
    k = b.shape[-1]

    def fwd(j, y):
        yj = (b[:, j] - jnp.einsum("sk,sk->s", L[:, j, :], y)) / L[:, j, j]
        return y.at[:, j].set(yj)

    y = jax.lax.fori_loop(0, k, fwd, jnp.zeros_like(b))

    def bwd(i, x):
        j = k - 1 - i
        xj = (y[:, j] - jnp.einsum("sk,sk->s", L[:, :, j], x)) / L[:, j, j]
        return x.at[:, j].set(xj)

    return jax.lax.fori_loop(0, k, bwd, jnp.zeros_like(b))


@jax.jit
def cholesky_solve_vec(A: jax.Array, b: jax.Array) -> jax.Array:
    """Solve SPD A[s] x = b[s], vectorized over the batch dimension."""
    L = _vec_cholesky(A)
    return _vec_solve_tri(L, b)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------

def _spd_solve_kernel(a_ref, b_ref, x_ref):
    """One batch tile: factorize + solve T systems entirely in VMEM.

    Layout: [K, K, T] / [K, T] — the batch dim maps to vector lanes, so
    every step of the K-recurrence is a full-width VPU op and no lane sits
    idle on the K x K structure.
    """
    k = a_ref.shape[1]
    A = jnp.transpose(a_ref[...], (1, 2, 0))      # [K, K, T]
    rhs = jnp.transpose(b_ref[...], (1, 0))       # [K, T]
    row1 = jax.lax.broadcasted_iota(jnp.int32, (k, 1), 0)       # [K, 1]
    row3 = jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1), 0)    # [K, 1, 1]
    col3 = jax.lax.broadcasted_iota(jnp.int32, (1, k, 1), 1)    # [1, K, 1]

    # unrolled right-looking Cholesky; j is static so the masks are iota
    # compares. All column extraction is done as masked full-array
    # reductions — Mosaic has no scatter lowering and rejects sublane
    # reductions over offset-layout slices, so no A[:, j, :]-style slicing.
    for j in range(k):
        diag = jnp.sum(jnp.where((row3 == j) & (col3 == j), A, 0.0),
                       axis=(0, 1))                             # [T]
        d = jax.lax.rsqrt(jnp.maximum(diag, 1e-30))
        col = jnp.sum(jnp.where(col3 == j, A, 0.0), axis=1)     # [K, T]
        col = jnp.where(row1 >= j, col * d[None, :], 0.0)
        outer = col[:, None, :] * col[None, :, :]               # [K, K, T]
        A = jnp.where(col3 > j, A - outer, A)
        A = jnp.where(col3 == j, col[:, None, :], A)

    L = jnp.where(row3 >= col3, A, 0.0)
    Ld = jnp.sum(jnp.where(row3 == col3, A, 0.0), axis=1)       # [K, T] diag

    # forward substitution L y = rhs: each step recomputes every row's dot
    # product (full-width VPU op); only row j's result is committed, and
    # rows > j see zeros for the not-yet-solved entries.
    y = jnp.zeros_like(rhs)
    for j in range(k):
        acc = jnp.sum(L * y[None, :, :], axis=1)                # [K, T]
        y = jnp.where(row1 == j, (rhs - acc) / Ld, y)

    # backward substitution L^T x = y (row j of L^T = column j of L)
    x = jnp.zeros_like(rhs)
    for j in range(k - 1, -1, -1):
        acc = jnp.sum(L * x[:, None, :], axis=0)                # [K, T]
        x = jnp.where(row1 == j, (y - acc) / Ld, x)

    x_ref[...] = jnp.transpose(x, (1, 0))                       # [T, K]


@functools.partial(jax.jit, static_argnames=("interpret",))
def cholesky_solve_pallas(A: jax.Array, b: jax.Array,
                          interpret: bool = False) -> jax.Array:
    """Solve SPD A[s] x = b[s] with the VMEM-resident Pallas kernel."""
    from jax.experimental import pallas as pl

    s, k, _ = A.shape
    t = _PALLAS_TILE
    s_pad = max(t, ((s + t - 1) // t) * t)
    if s_pad != s:
        # pad with identity systems (x = 0 for b = 0)
        eye = jnp.broadcast_to(jnp.eye(k, dtype=A.dtype), (s_pad - s, k, k))
        A = jnp.concatenate([A, eye], axis=0)
        b = jnp.concatenate([b, jnp.zeros((s_pad - s, k), b.dtype)], axis=0)

    out = pl.pallas_call(
        _spd_solve_kernel,
        out_shape=jax.ShapeDtypeStruct((s_pad, k), A.dtype),
        grid=(s_pad // t,),
        in_specs=[
            pl.BlockSpec((t, k, k), lambda i: (i, 0, 0)),
            pl.BlockSpec((t, k), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((t, k), lambda i: (i, 0)),
        interpret=interpret,
    )(A, b)
    return out[:s]


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def batched_spd_solve(A: jax.Array, b: jax.Array,
                      jitter: float = 1e-6) -> jax.Array:
    """Solve A[s] x[s] = b[s] for SPD A, [S, K, K] x [S, K] -> [S, K].

    A small diagonal jitter keeps empty segments (A ~ 0) from producing
    NaNs; their rhs is 0 so the solution stays 0. Method selection:
    ``PIO_TPU_SOLVE`` env var (``pallas`` | ``vec`` | ``xla``) overrides;
    default is the Pallas kernel on TPU for K <= 64, else the vectorized
    JAX path.
    """
    k = A.shape[-1]
    A = A + jitter * jnp.eye(k, dtype=A.dtype)
    method = os.environ.get("PIO_TPU_SOLVE", "auto").strip().lower()
    if method not in ("auto", "xla", "vec", "pallas"):
        raise ValueError(
            f"PIO_TPU_SOLVE={method!r}: expected auto|xla|vec|pallas")
    if method == "xla":
        return cholesky_solve_xla(A, b)
    if method == "vec":
        return cholesky_solve_vec(A, b)
    on_tpu = _is_tpu_backend()
    if method == "pallas":
        # explicit override off-TPU runs the kernel in interpreter mode
        return cholesky_solve_pallas(A, b, interpret=not on_tpu)
    if k <= _PALLAS_MAX_K and on_tpu:
        return cholesky_solve_pallas(A, b)
    return cholesky_solve_vec(A, b)
