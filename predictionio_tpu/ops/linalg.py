"""Batched dense linear algebra for the MXU.

Batched positive-definite solves: the per-segment normal equations of ALS
([S, K, K] @ x = [S, K]) solved with Cholesky, the shape XLA tiles onto the
MXU as batched K x K matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def batched_spd_solve(A: jax.Array, b: jax.Array,
                      jitter: float = 1e-6) -> jax.Array:
    """Solve A[s] x[s] = b[s] for SPD A, [S, K, K] x [S, K] -> [S, K].

    A small diagonal jitter keeps empty segments (A ~ 0) from producing
    NaNs; their rhs is 0 so the solution stays 0.
    """
    k = A.shape[-1]
    A = A + jitter * jnp.eye(k, dtype=A.dtype)
    chol, lower = jax.scipy.linalg.cho_factor(A)
    return jax.scipy.linalg.cho_solve((chol, lower), b)
