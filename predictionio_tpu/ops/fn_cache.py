"""Shared cache for mesh-closed compiled functions.

jit's own cache keys on function identity, so any wrapper built per call
(`jax.jit(shard_map(closure, ...))`) re-traces every time. Model modules
register their builders here instead: one bounded LRU per family, keyed
on the (hashable) Mesh plus whatever static parameters shape the program.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, Hashable

_CACHES: Dict[str, "OrderedDict" ] = {}

MAX_PER_FAMILY = 8


def mesh_cached_fn(family: str, mesh, static_key: Hashable,
                   build: Callable[[], Callable]) -> Callable:
    """The compiled fn for (family, mesh, static_key), building it on
    first use. `mesh` participates in the key directly (jax.sharding.Mesh
    is hashable by devices+axis names — no id() aliasing). Bounded LRU
    per family so long-lived servers retraining on growing data don't
    accumulate executables forever."""
    cache = _CACHES.setdefault(family, OrderedDict())
    key = (mesh, static_key)
    fn = cache.get(key)
    if fn is None:
        fn = build()
        from predictionio_tpu.obs.jax_stats import compile_counter

        # a climbing pio_jax_compile_total on a serving box flags a
        # retrace leak — exactly what this cache exists to prevent
        compile_counter().inc(family=family)
        cache[key] = fn
        while len(cache) > MAX_PER_FAMILY:
            cache.popitem(last=False)
    else:
        cache.move_to_end(key)
    return fn
