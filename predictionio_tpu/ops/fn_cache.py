"""Shared cache for mesh-closed compiled functions.

jit's own cache keys on function identity, so any wrapper built per call
(`jax.jit(shard_map(closure, ...))`) re-traces every time. Model modules
register their builders here instead: one bounded LRU per family, keyed
on the (hashable) Mesh plus whatever static parameters shape the program.

The same machinery doubles as the serving-side compile ledger:
`shape_cached_fn` keys on static SHAPES alone (no mesh) so batch scorers
can register one entry per shape bucket — the build counter then reads
as "distinct compiled batch shapes per family", the number the bucketed
micro-batch hot path bounds at ``bucketing.bucket_count(max_batch)``
(``log2(max_batch) + 1`` for the power-of-two default).
"""

from __future__ import annotations

import functools
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, Hashable, List, Tuple

_CACHES: Dict[str, "OrderedDict"] = {}
#: serving scorers register entries from executor threads; the training
#: paths were loop-single-threaded but the ledger no longer is
_LOCK = threading.Lock()

MAX_PER_FAMILY = 8


def _attributed(family: str, fn: Callable) -> Callable:
    """Per-family dispatch-time attribution (obs/profiler.py): each call
    of a cached compiled function adds its dispatch wall time to
    ``pio_device_dispatch_seconds_total{family}`` — the "which compiled
    family is eating the device" answer — and, when a micro-batch is
    live, into that batch's anatomy breakdown so requests get their
    amortized device-dispatch share (obs/anatomy.py). One perf_counter
    pair + a counter add + a contextvar read per dispatch; with both
    PIO_DISPATCH_ATTRIBUTION=0 and PIO_ANATOMY=0 the wrap is skipped
    entirely (zero overhead)."""
    from predictionio_tpu.obs import anatomy
    from predictionio_tpu.obs.profiler import (
        dispatch_attribution_enabled, dispatch_counter,
    )

    attributed = dispatch_attribution_enabled()
    if not attributed and not anatomy.anatomy_enabled():
        return fn
    counter = dispatch_counter() if attributed else None

    @functools.wraps(fn)
    def dispatch(*args, **kwargs):
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            dt = time.perf_counter() - t0
            if counter is not None:
                counter.inc(dt, family=family)
            anatomy.note_dispatch(dt)
    return dispatch


def _cached(family: str, key: Hashable, build: Callable[[], Callable],
            max_entries: int) -> Callable:
    with _LOCK:
        cache = _CACHES.setdefault(family, OrderedDict())
        fn = cache.get(key)
        if fn is not None:
            cache.move_to_end(key)
            return fn
    fn = _attributed(family, build())
    from predictionio_tpu.obs.jax_stats import compile_counter

    with _LOCK:
        cache = _CACHES.setdefault(family, OrderedDict())
        if key not in cache:
            # a climbing pio_jax_compile_total on a serving box flags a
            # retrace leak — exactly what this cache exists to prevent
            compile_counter().inc(family=family)
            cache[key] = fn
            while len(cache) > max_entries:
                cache.popitem(last=False)
        else:
            fn = cache[key]
            cache.move_to_end(key)
    return fn


def mesh_cached_fn(family: str, mesh, static_key: Hashable,
                   build: Callable[[], Callable]) -> Callable:
    """The compiled fn for (family, mesh, static_key), building it on
    first use. `mesh` participates in the key directly (jax.sharding.Mesh
    is hashable by devices+axis names — no id() aliasing). Bounded LRU
    per family so long-lived servers retraining on growing data don't
    accumulate executables forever."""
    return _cached(family, (mesh, static_key), build, MAX_PER_FAMILY)


def shape_cached_fn(family: str, static_key: Hashable,
                    build: Callable[[], Callable],
                    max_entries: int = 256) -> Callable:
    """Mesh-free variant for serving scorers keyed on shape buckets.

    `build` may return a SHARED jitted function (jit's own cache then
    holds the executables), in which case this cache exists purely to
    count the first sighting of each shape key into
    ``pio_jax_compile_total{family=...}``. Keys usually combine the
    batch bucket with the other static shapes (k-bucket, catalog size,
    rank), so the per-family bound is ``bucket_count(max_batch)`` PER
    distinct (k-bucket, catalog) combination — a handful in practice.
    The default `max_entries` is deliberately far above any realistic
    live-key count: entries are cheap references, and evicting one would
    double-count its next sighting, faking the very retrace leak the
    counter exists to expose."""
    return _cached(family, static_key, build, max_entries)


def family_keys(family: str) -> List[Tuple]:
    """Snapshot of a family's live cache keys (introspection/tests)."""
    with _LOCK:
        return list(_CACHES.get(family, ()))
