"""Device-resident input cache.

The framework's steady-state rule is "commit host data to the mesh once,
then let every step consume resident arrays" (models/als.py ALSData.put).
This module extends that rule to ad-hoc inputs (classifier matrices,
incidence matrices): `resident()` keys a device array on the IDENTITY of
the host arrays it was built from, so back-to-back train/predict calls
over the same host data transfer it once.

Why identity and not content: hashing 100MB+ inputs would cost as much
as the transfer it avoids. Identity keying assumes callers do not mutate
training arrays in place between calls — the same contract jit's
donate_argnums and ALSData already rely on. Entries evict automatically
when any source array is garbage-collected (weakref finalizers), so the
cache cannot outlive the host data and cannot grow past the number of
live distinct inputs.

This matters doubly over a tunneled chip (the axon relay): a host->device
transfer issued after an executable launch pays a pipeline-flush stall
measured in hundreds of ms, so avoiding the re-upload also avoids the
stall (measured r5: NB train went 1.6s -> ~70ms on cache hits).
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, Sequence, Tuple

import numpy as np

_lock = threading.Lock()
_cache: Dict[Tuple, Any] = {}


def _key_of(arrays: Sequence[np.ndarray], extra: Tuple) -> Tuple:
    return tuple((id(a), a.shape, str(a.dtype)) for a in arrays) + (extra,)


def is_resident(arrays: Sequence[np.ndarray], extra: Tuple) -> bool:
    """True when `resident(arrays, extra, ...)` would hit the cache —
    the public residency probe for dispatch-aware routing (callers must
    not poke the key/lock internals)."""
    with _lock:
        return _key_of(arrays, extra) in _cache


def resident(arrays: Sequence[np.ndarray], extra: Tuple,
             build: Callable[[], Any]) -> Any:
    """Return `build()`'s result, cached until any of `arrays` is GC'd.

    `arrays` are the host ndarrays the device value derives from (the
    cache key + lifetime anchors). `extra` distinguishes different device
    layouts of the same data (mesh id, sharding spec, dtype, padding).
    """
    key = _key_of(arrays, extra)
    with _lock:
        hit = _cache.get(key)
    if hit is not None:
        return hit[0]
    val = build()
    # weakref.ref with a callback (not finalize): eviction must not keep
    # the source arrays alive, and np arrays support weakrefs
    refs = []
    for a in arrays:
        try:
            refs.append(weakref.ref(a, lambda _r, k=key: _evict(k)))
        except TypeError:        # non-weakref-able (e.g. scalar) — skip
            pass
    with _lock:
        _cache[key] = (val, refs)
    return val


def _evict(key: Tuple) -> None:
    with _lock:
        _cache.pop(key, None)


def clear() -> None:
    """Drop every cached device buffer (tests; post-train teardown)."""
    with _lock:
        _cache.clear()


def size() -> int:
    with _lock:
        return len(_cache)
