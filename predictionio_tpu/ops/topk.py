"""Host-side top-k.

The host counterpart of `jax.lax.top_k` for the dispatch-latency-aware
paths (serving in models/als.py, single-device-CPU cooccurrence): when a
model is small enough that one device round-trip costs more than the
whole scoring matmul, the top-k runs on host BLAS output instead.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def host_topk(scores: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row descending top-k: [B, N] -> (values [B, k], idx [B, k]).

    k is clamped to N. argpartition against the (n-k)th element + a
    descending sort of the k-suffix — the O(N + k log k) idiom numpy
    lacks a primitive for, WITHOUT materializing a negated [B, N] copy:
    when k << N the only full-width pass is the partition itself, and
    the negation (numpy sorts ascending) touches just the [B, k] slice.
    """
    n = scores.shape[1]
    k = min(k, n)
    if k <= 0:
        empty = np.zeros((scores.shape[0], 0))
        return empty.astype(scores.dtype), empty.astype(np.int64)
    if k >= n:
        idx = np.argsort(-scores, axis=1)
    else:
        part = np.argpartition(scores, n - k, axis=1)[:, n - k:]
        order = np.argsort(-np.take_along_axis(scores, part, axis=1),
                           axis=1)
        idx = np.take_along_axis(part, order, axis=1)
    return np.take_along_axis(scores, idx, axis=1), idx
