"""Host-side top-k + the k-way shortlist merge.

The host counterpart of `jax.lax.top_k` for the dispatch-latency-aware
paths (serving in models/als.py, single-device-CPU cooccurrence): when a
model is small enough that one device round-trip costs more than the
whole scoring matmul, the top-k runs on host BLAS output instead.

`merge_topk` is the one tested implementation of "several per-source
top-k shortlists -> one global top-k": the cross-shard merge of the
model-parallel scorer (ops/scoring.ShardedScorer), the exact-rescore
tail of the fused/two-stage kernels, and any batchpredict-style
shard->merge consumer all route here instead of re-deriving the
sort-and-slice. Ties break deterministically (score descending, then
item id ascending), so a merged result never depends on shard order or
argpartition internals.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


def host_topk(scores: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row descending top-k: [B, N] -> (values [B, k], idx [B, k]).

    k is clamped to N. argpartition against the (n-k)th element + a
    descending sort of the k-suffix — the O(N + k log k) idiom numpy
    lacks a primitive for, WITHOUT materializing a negated [B, N] copy:
    when k << N the only full-width pass is the partition itself, and
    the negation (numpy sorts ascending) touches just the [B, k] slice.
    """
    n = scores.shape[1]
    k = min(k, n)
    if k <= 0:
        empty = np.zeros((scores.shape[0], 0))
        return empty.astype(scores.dtype), empty.astype(np.int64)
    if k >= n:
        idx = np.argsort(-scores, axis=1)
    else:
        part = np.argpartition(scores, n - k, axis=1)[:, n - k:]
        order = np.argsort(-np.take_along_axis(scores, part, axis=1),
                           axis=1)
        idx = np.take_along_axis(part, order, axis=1)
    return np.take_along_axis(scores, idx, axis=1), idx


def merge_topk(shortlists: Sequence[Tuple[np.ndarray, np.ndarray]],
               k: int) -> Tuple[np.ndarray, np.ndarray]:
    """K-way merge of per-source top-k shortlists into one global top-k.

    ``shortlists`` is a sequence of ``(values [B, k_i], ids [B, k_i])``
    pairs — ragged widths are fine (a small shard legitimately emits a
    narrower shortlist than its siblings), but every pair must agree on
    ``B``. Returns ``(values [B, k], ids [B, k])`` sorted score
    descending with ties broken by ascending id — deterministic, so the
    merged result is independent of shard order and of whatever
    tie-order the per-source top-k used. Non-finite values and negative
    ids mark invalid candidates (mask sentinels, padding): they sort
    last, and rows with fewer than ``k`` valid candidates pad out with
    ``(-inf, -1)``. ``k <= 0`` (and an all-empty input) returns empty
    ``[B, 0]`` arrays.
    """
    if not shortlists:
        raise ValueError("merge_topk needs at least one shortlist")
    b = shortlists[0][0].shape[0]
    for vals, ids in shortlists:
        if vals.shape != ids.shape or vals.ndim != 2:
            raise ValueError(
                f"shortlist shapes must match and be 2-D, got values "
                f"{vals.shape} ids {ids.shape}")
        if vals.shape[0] != b:
            raise ValueError(
                f"ragged batch: shortlist rows {vals.shape[0]} != {b}")
    vals = np.concatenate([np.asarray(v, np.float32)
                           for v, _ in shortlists], axis=1)
    ids = np.concatenate([np.asarray(i, np.int64)
                          for _, i in shortlists], axis=1)
    if k <= 0 or vals.shape[1] == 0:
        empty = np.zeros((b, 0))
        return empty.astype(np.float32), empty.astype(np.int64)
    # invalid candidates (NaN scores, sentinel ids) become (-inf, -1) so
    # one rule sorts them last AND makes the short-row padding visible
    valid = np.isfinite(vals) & (ids >= 0)
    vals = np.where(valid, vals, -np.inf)
    ids = np.where(valid, ids, np.int64(-1))
    # -inf maps to +inf under negation, so invalids sort last; id is the
    # secondary key, except invalids where id -1 would wrongly win ties
    # against valid candidates — lift them to the max id instead
    tie_ids = np.where(valid, ids, np.iinfo(np.int64).max)
    order = np.lexsort((tie_ids, -vals), axis=1)[:, :k]
    out_v = np.take_along_axis(vals, order, axis=1)
    out_i = np.take_along_axis(ids, order, axis=1)
    if out_v.shape[1] < k:
        pad = k - out_v.shape[1]
        out_v = np.concatenate(
            [out_v, np.full((b, pad), -np.inf, out_v.dtype)], axis=1)
        out_i = np.concatenate(
            [out_i, np.full((b, pad), -1, out_i.dtype)], axis=1)
    return out_v, out_i
