"""Host-side top-k.

The host counterpart of `jax.lax.top_k` for the dispatch-latency-aware
paths (serving in models/als.py, single-device-CPU cooccurrence): when a
model is small enough that one device round-trip costs more than the
whole scoring matmul, the top-k runs on host BLAS output instead.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def host_topk(scores: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row descending top-k: [B, N] -> (values [B, k], idx [B, k]).

    k is clamped to N. argpartition + argsort of the k-prefix, the
    O(N + k log k) idiom numpy lacks a primitive for.
    """
    n = scores.shape[1]
    k = min(k, n)
    if k >= n:
        idx = np.argsort(-scores, axis=1)
    else:
        part = np.argpartition(-scores, k, axis=1)[:, :k]
        order = np.argsort(-np.take_along_axis(scores, part, axis=1), axis=1)
        idx = np.take_along_axis(part, order, axis=1)
    return np.take_along_axis(scores, idx, axis=1), idx
