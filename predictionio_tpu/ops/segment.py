"""Chunked segment reductions for normal-equation assembly.

The TPU-native replacement for MLlib ALS's shuffle-based rating-block
aggregation (invoked from the reference templates at
examples/.../ALSAlgorithm.scala:85): for every segment (user or item) we
accumulate the Gramian sum_j w_j f_j f_j^T and right-hand side
sum_j v_j f_j over that segment's ratings.

Design for the hardware (SURVEY.md section 2.9 P3/P4): ratings are packed
into padded per-segment rows (the ALX layout, built host-side in
models/als.py) so each chunk's Gramians are ONE batched MXU matmul; rows are
processed in fixed-size chunks under lax.scan so buffers stay bounded at any
dataset size, and per-segment combines scatter row-granularity partials with
sorted indices.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_count(seg_idx: jax.Array, weights: jax.Array,
                  num_segments: int) -> jax.Array:
    return jnp.zeros((num_segments,), weights.dtype).at[seg_idx].add(weights)


@functools.partial(
    jax.jit, static_argnames=("num_segments", "chunk_rows"))
def rows_gram_rhs(
    factors: jax.Array,     # [F, K] factor matrix indexed by row_tgt
    row_tgt: jax.Array,     # [R, L] factor row per rating (padded)
    row_seg: jax.Array,     # [R] segment of each row (sorted)
    row_val: jax.Array,     # [R, L] rating values
    row_w: jax.Array,       # [R, L] weights (0 = padding)
    num_segments: int,
    chunk_rows: int = 8192,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Padded-row Gramian assembly — the MXU path (ALX layout, PAPERS.md).

    Each row holds up to L of one segment's ratings; heavy segments span
    multiple rows. Per chunk the Gramian of every row is ONE batched matmul
    einsum('clk,cln->ckn') on the MXU, and the per-segment combine scatters
    only ~nnz/L + S rows instead of nnz — two orders of magnitude less
    scatter traffic than rating-granularity segment sums at L=128+.
    Returns (gram [S, K, K], rhs [S, K], count [S]).
    """
    k = factors.shape[-1]
    r, l = row_tgt.shape
    chunk_rows = min(chunk_rows, max(r, 1))  # never pad past the real rows
    num_chunks = max(1, (r + chunk_rows - 1) // chunk_rows)
    padded = num_chunks * chunk_rows
    if padded != r:
        pad = padded - r
        # weight-0 rows aimed at the LAST segment keep row_seg sorted
        row_tgt = jnp.concatenate(
            [row_tgt, jnp.zeros((pad, l), row_tgt.dtype)])
        row_seg = jnp.concatenate(
            [row_seg, jnp.full((pad,), num_segments - 1, row_seg.dtype)])
        row_val = jnp.concatenate(
            [row_val, jnp.zeros((pad, l), row_val.dtype)])
        row_w = jnp.concatenate([row_w, jnp.zeros((pad, l), row_w.dtype)])

    tgt_c = row_tgt.reshape(num_chunks, chunk_rows, l)
    seg_c = row_seg.reshape(num_chunks, chunk_rows)
    val_c = row_val.reshape(num_chunks, chunk_rows, l)
    w_c = row_w.reshape(num_chunks, chunk_rows, l)

    def body(carry, chunk):
        gram, rhs, count = carry
        tgt, seg, val, w = chunk
        f = factors[tgt]                                  # [C, L, K]
        fw = f * w[..., None]
        gram_rows = jnp.einsum("clk,cln->ckn", fw, f)     # batched MXU matmul
        rhs_rows = jnp.einsum("clk,cl->ck", fw, val)
        gram = gram.at[seg].add(gram_rows, indices_are_sorted=True)
        rhs = rhs.at[seg].add(rhs_rows, indices_are_sorted=True)
        count = count.at[seg].add(w.sum(axis=1), indices_are_sorted=True)
        return (gram, rhs, count), None

    init = (jnp.zeros((num_segments, k, k), factors.dtype),
            jnp.zeros((num_segments, k), factors.dtype),
            jnp.zeros((num_segments,), factors.dtype))
    (gram, rhs, count), _ = jax.lax.scan(
        body, init, (tgt_c, seg_c, val_c, w_c))
    return gram, rhs, count
