"""Chunked segment reductions for normal-equation assembly.

The TPU-native replacement for MLlib ALS's shuffle-based rating-block
aggregation (invoked from the reference templates at
examples/.../ALSAlgorithm.scala:85): for every segment (user or item) we
accumulate the Gramian sum_j w_j f_j f_j^T and right-hand side
sum_j v_j f_j over that segment's ratings.

Design for the hardware (SURVEY.md section 2.9 P3/P4):
  * ratings arrive pre-sorted by segment id -> scatter-adds are
    indices_are_sorted and XLA lowers them to efficient sorted-segment sums
  * nnz is processed in fixed-size chunks under lax.scan so the temporary
    outer-product buffer (chunk x K x K) stays bounded regardless of dataset
    size (20M ratings never materialize a [nnz, K, K] tensor)
  * all shapes are static: nnz is padded to a chunk multiple with weight-0
    rows pointing at a scratch segment
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def pad_to_multiple(arr: np.ndarray, multiple: int, fill) -> np.ndarray:
    n = arr.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple if n else multiple
    if target == n:
        return arr
    pad = np.full((target - n,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


@functools.partial(
    jax.jit, static_argnames=("num_segments", "chunk_size"))
def segment_gram_rhs(
    factors: jax.Array,       # [F, K] factor matrix indexed by tgt_idx
    tgt_idx: jax.Array,       # [N] which factor row each rating touches
    seg_idx: jax.Array,       # [N] which segment each rating belongs to (sorted)
    values: jax.Array,        # [N] rating values (rhs weights)
    weights: jax.Array,       # [N] confidence/validity weights (0 = padding)
    num_segments: int,
    chunk_size: int = 16384,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (gram [S, K, K], rhs [S, K], count [S]).

    gram[s]  = sum_{j in s} w_j f_j f_j^T
    rhs[s]   = sum_{j in s} w_j v_j f_j
    count[s] = sum_{j in s} w_j
    """
    k = factors.shape[-1]
    n = tgt_idx.shape[0]
    num_chunks = max(1, (n + chunk_size - 1) // chunk_size)
    padded = num_chunks * chunk_size
    if padded != n:
        # weight-0 padding rows scatter into segment 0 harmlessly
        pad = padded - n
        tgt_idx = jnp.concatenate([tgt_idx, jnp.zeros(pad, tgt_idx.dtype)])
        seg_idx = jnp.concatenate([seg_idx, jnp.zeros(pad, seg_idx.dtype)])
        values = jnp.concatenate([values, jnp.zeros(pad, values.dtype)])
        weights = jnp.concatenate([weights, jnp.zeros(pad, weights.dtype)])

    tgt_c = tgt_idx.reshape(num_chunks, chunk_size)
    seg_c = seg_idx.reshape(num_chunks, chunk_size)
    val_c = values.reshape(num_chunks, chunk_size)
    w_c = weights.reshape(num_chunks, chunk_size)

    def body(carry, chunk):
        gram, rhs, count = carry
        tgt, seg, val, w = chunk
        f = factors[tgt]                                   # [C, K] gather
        fw = f * w[:, None]
        outer = jnp.einsum("ck,cl->ckl", fw, f)            # [C, K, K]
        gram = gram.at[seg].add(outer, indices_are_sorted=False)
        rhs = rhs.at[seg].add(f * (val * w)[:, None])
        count = count.at[seg].add(w)
        return (gram, rhs, count), None

    init = (jnp.zeros((num_segments, k, k), factors.dtype),
            jnp.zeros((num_segments, k), factors.dtype),
            jnp.zeros((num_segments,), factors.dtype))
    (gram, rhs, count), _ = jax.lax.scan(
        body, init, (tgt_c, seg_c, val_c, w_c))
    return gram, rhs, count


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_count(seg_idx: jax.Array, weights: jax.Array,
                  num_segments: int) -> jax.Array:
    return jnp.zeros((num_segments,), weights.dtype).at[seg_idx].add(weights)
