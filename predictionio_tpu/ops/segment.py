"""Chunked segment reductions for normal-equation assembly.

The TPU-native replacement for MLlib ALS's shuffle-based rating-block
aggregation (invoked from the reference templates at
examples/.../ALSAlgorithm.scala:85): for every segment (user or item) we
accumulate the Gramian sum_j w_j f_j f_j^T and right-hand side
sum_j v_j f_j over that segment's ratings.

Design for the hardware (SURVEY.md section 2.9 P3/P4): ratings are packed
into padded per-segment rows (the ALX layout, built host-side in
models/als.py) so each chunk's Gramians are ONE batched MXU matmul; rows are
processed in fixed-size chunks under lax.scan so buffers stay bounded at any
dataset size, and per-segment combines scatter row-granularity partials with
sorted indices.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_count(seg_idx: jax.Array, weights: jax.Array,
                  num_segments: int) -> jax.Array:
    return jnp.zeros((num_segments,), weights.dtype).at[seg_idx].add(weights)


def _pad_rows_sorted(row_tgt, row_seg, extra, num_segments, chunk_rows):
    """Pad padded-row inputs to a chunk multiple. Padding rows aim at the
    LAST segment (keeps row_seg sorted) and every `extra` array is padded
    with zeros (weight-0 rows contribute nothing). Returns the padded
    (row_tgt, row_seg, *extra) plus the chunk count."""
    r, l = row_tgt.shape
    chunk = min(chunk_rows, max(r, 1))
    num_chunks = max(1, (r + chunk - 1) // chunk)
    padded = num_chunks * chunk
    if padded != r:
        pad = padded - r
        row_tgt = jnp.concatenate(
            [row_tgt, jnp.zeros((pad, l), row_tgt.dtype)])
        row_seg = jnp.concatenate(
            [row_seg, jnp.full((pad,), num_segments - 1, row_seg.dtype)])
        extra = tuple(
            jnp.concatenate([a, jnp.zeros((pad, l), a.dtype)])
            for a in extra)
    return row_tgt, row_seg, extra, num_chunks, chunk


@functools.partial(
    jax.jit, static_argnames=("num_segments", "chunk_rows"))
def rows_gram_rhs(
    factors: jax.Array,     # [F, K] factor matrix indexed by row_tgt
    row_tgt: jax.Array,     # [R, L] factor row per rating (padded)
    row_seg: jax.Array,     # [R] segment of each row (sorted)
    row_val: jax.Array,     # [R, L] rating values
    row_w: jax.Array,       # [R, L] weights (0 = padding)
    num_segments: int,
    chunk_rows: int = 8192,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Padded-row Gramian assembly — the MXU path (ALX layout, PAPERS.md).

    Each row holds up to L of one segment's ratings; heavy segments span
    multiple rows. Per chunk the Gramian of every row is ONE batched matmul
    einsum('clk,cln->ckn') on the MXU, and the per-segment combine scatters
    only ~nnz/L + S rows instead of nnz — two orders of magnitude less
    scatter traffic than rating-granularity segment sums at L=128+.
    Returns (gram [S, K, K], rhs [S, K], count [S]).
    """
    k = factors.shape[-1]
    l = row_tgt.shape[1]
    # weight-0 rows aimed at the LAST segment keep row_seg sorted
    row_tgt, row_seg, (row_val, row_w), num_chunks, chunk_rows = \
        _pad_rows_sorted(row_tgt, row_seg, (row_val, row_w),
                         num_segments, chunk_rows)

    tgt_c = row_tgt.reshape(num_chunks, chunk_rows, l)
    seg_c = row_seg.reshape(num_chunks, chunk_rows)
    val_c = row_val.reshape(num_chunks, chunk_rows, l)
    w_c = row_w.reshape(num_chunks, chunk_rows, l)

    def body(carry, chunk):
        gram, rhs, count = carry
        tgt, seg, val, w = chunk
        f = factors[tgt]                                  # [C, L, K]
        fw = f * w[..., None]
        gram_rows = jnp.einsum("clk,cln->ckn", fw, f)     # batched MXU matmul
        rhs_rows = jnp.einsum("clk,cl->ck", fw, val)
        gram = gram.at[seg].add(gram_rows, indices_are_sorted=True)
        rhs = rhs.at[seg].add(rhs_rows, indices_are_sorted=True)
        count = count.at[seg].add(w.sum(axis=1), indices_are_sorted=True)
        return (gram, rhs, count), None

    init = (jnp.zeros((num_segments, k, k), factors.dtype),
            jnp.zeros((num_segments, k), factors.dtype),
            jnp.zeros((num_segments,), factors.dtype))
    (gram, rhs, count), _ = jax.lax.scan(
        body, init, (tgt_c, seg_c, val_c, w_c))
    return gram, rhs, count


@functools.partial(jax.jit, static_argnames=("chunk_rows",))
def row_predict_add(
    factors: jax.Array,     # [F, B] factor columns indexed by row_tgt
    x_rows: jax.Array,      # [S, B] this side's factor columns per segment
    row_tgt: jax.Array,     # [R, L]
    row_seg: jax.Array,     # [R]
    row_pred: jax.Array,    # [R, L] running prediction (0 to initialize)
    chunk_rows: int = 8192,
) -> jax.Array:
    """row_pred + <x_rows[seg], factors[tgt]> per rating slot.

    The residual-maintenance primitive of the subspace ALS solver: called
    with the full factor matrices it initializes each rating's predicted
    value; called with a single block's columns and the block DELTA it
    folds one block update into the running prediction without touching
    the other rank coordinates.
    """
    r, l = row_tgt.shape
    row_tgt, row_seg, _, num_chunks, chunk = _pad_rows_sorted(
        row_tgt, row_seg, (), x_rows.shape[0], chunk_rows)
    tgt_c = row_tgt.reshape(num_chunks, chunk, l)
    seg_c = row_seg.reshape(num_chunks, chunk)

    def body(_, sl):
        tgt, seg = sl
        f = factors[tgt]                                  # [C, L, B]
        return None, jnp.einsum("clb,cb->cl", f, x_rows[seg])

    _, pred = jax.lax.scan(body, None, (tgt_c, seg_c))
    return row_pred + pred.reshape(num_chunks * chunk, l)[:r]


@functools.partial(
    jax.jit, static_argnames=("num_segments", "chunk_rows"))
def block_gram_rhs(
    factors_b: jax.Array,   # [F, B] ONE rank block's factor columns
    x_b: jax.Array,         # [S, B] this side's current block columns
    row_tgt: jax.Array,     # [R, L]
    row_seg: jax.Array,     # [R] (sorted)
    row_pred: jax.Array,    # [R, L] full current prediction per rating
    rhs_val: jax.Array,     # [R, L] rhs weight*value per rating
    gram_w: jax.Array,      # [R, L] Gramian weights (0 = padding)
    num_segments: int,
    chunk_rows: int = 8192,
) -> Tuple[jax.Array, jax.Array]:
    """Per-segment b x b normal equations of one rank-subspace block.

    The block-coordinate-descent analog of `rows_gram_rhs` (iALS++,
    arXiv:2110.14044): with the other rank coordinates frozen at their
    current values, each segment's optimal block solves

        (sum_j gram_w_j f_j f_j^T + reg I) y =
            sum_j (rhs_val_j - gram_w_j * (pred_j - <f_j, x_b>)) f_j

    where ``pred - <f_b, x_b>`` is the prediction with this block's own
    contribution removed. Explicit feedback passes ``gram_w = w`` and
    ``rhs_val = w * rating``; implicit (Hu-Koren-Volinsky) passes
    ``gram_w = w * (c-1)`` and ``rhs_val = w * c * p`` (the global
    Gramian term is added by the caller from the cached V^T V). The
    gather/matmul buffers are [C, L, b] instead of [C, L, K] — the
    bandwidth saving that makes the O(r * b^2) per-row sweep pay.
    Returns (gram [S, b, b], rhs [S, b]).
    """
    b = factors_b.shape[-1]
    l = row_tgt.shape[1]
    row_tgt, row_seg, (row_pred, rhs_val, gram_w), num_chunks, chunk = \
        _pad_rows_sorted(row_tgt, row_seg, (row_pred, rhs_val, gram_w),
                         num_segments, chunk_rows)
    tgt_c = row_tgt.reshape(num_chunks, chunk, l)
    seg_c = row_seg.reshape(num_chunks, chunk)
    pred_c = row_pred.reshape(num_chunks, chunk, l)
    val_c = rhs_val.reshape(num_chunks, chunk, l)
    w_c = gram_w.reshape(num_chunks, chunk, l)

    def body(carry, sl):
        gram, rhs = carry
        tgt, seg, pred, val, w = sl
        f = factors_b[tgt]                                # [C, L, b]
        pred_b = jnp.einsum("clb,cb->cl", f, x_b[seg])    # block's own part
        fw = f * w[..., None]
        gram_rows = jnp.einsum("clb,cln->cbn", fw, f)     # batched MXU matmul
        rhs_rows = jnp.einsum("clb,cl->cb", f, val - w * (pred - pred_b))
        gram = gram.at[seg].add(gram_rows, indices_are_sorted=True)
        rhs = rhs.at[seg].add(rhs_rows, indices_are_sorted=True)
        return (gram, rhs), None

    init = (jnp.zeros((num_segments, b, b), factors_b.dtype),
            jnp.zeros((num_segments, b), factors_b.dtype))
    (gram, rhs), _ = jax.lax.scan(
        body, init, (tgt_c, seg_c, pred_c, val_c, w_c))
    return gram, rhs
