"""Shape bucketing for serving-time batches.

Every jitted scorer compiles one executable per distinct input shape, so
a micro-batcher that hands the device whatever batch size it happened to
drain (3, then 5, then 17, ...) turns steady traffic into a stream of
XLA compiles — the static-shape discipline ALX applies to training
(models/als.py `_bucket_rows`) applies to serving too. This module is
the single definition of the serving-side rounding rule: batches pad up
to the next power of two, capped at the configured `max_batch`, so a
scorer family compiles at most ``bucket_count(max_batch)`` shapes ever
(``log2(max_batch) + 1`` for a power-of-two cap: 1, 2, 4, ..., cap)
instead of one per observed B.

The helpers are pad-mask aware by convention: callers remember the real
row count, slice padded rows off every result, and never let a padding
row reach user-visible output (`server.query_server._predict_batch`,
`models/als.ALSModel.recommend_batch`).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def bucket_size(n: int, cap: Optional[int] = None) -> int:
    """The padded size for a batch of `n`: the next power of two, capped
    at `cap` (a non-power-of-two cap is itself the terminal bucket, so
    the shape set stays ``{1, 2, 4, ..., cap}``). n <= 0 buckets to 0 —
    empty batches never reach a compiled scorer."""
    if n <= 0:
        return 0
    b = 1 << (n - 1).bit_length()
    if cap is not None and cap > 0:
        b = min(b, max(cap, n))
    return b


def bucket_count(cap: int) -> int:
    """How many distinct bucket shapes `bucket_size(-, cap)` can emit —
    the bound the compile-count acceptance check asserts against."""
    if cap <= 0:
        return 0
    # powers of two <= cap, plus the cap itself when it is not a power
    return cap.bit_length() + (0 if cap & (cap - 1) == 0 else 1)


def pad_rows(rows: np.ndarray, bucket: int,
             fill: float = 0.0) -> np.ndarray:
    """Pad a [B, ...] array with `fill` rows up to `bucket` (no-op when
    already there). Callers slice ``result[:B]`` afterwards."""
    n = rows.shape[0]
    if n >= bucket:
        return rows
    pad = np.full((bucket - n,) + rows.shape[1:], fill, dtype=rows.dtype)
    return np.concatenate([rows, pad])


def padding_waste(n: int, bucket: int) -> int:
    """Rows of throwaway compute a padded batch carries (>= 0) — the
    `pio_batch_pad_waste_rows_total` increment."""
    return max(0, bucket - n) if n > 0 else 0
