"""Model/algorithm library (the MLlib replacement).

TPU-native implementations of the algorithms the reference's judged engine
templates use (SURVEY.md section 2.8): blockwise ALS (explicit + implicit),
cooccurrence, categorical NaiveBayes, logistic regression, and the e2
extras (MarkovChain, BinaryVectorizer).
"""
