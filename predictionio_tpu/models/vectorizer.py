"""Binary one-hot vectorizer (e2 parity).

Parity with e2/.../engine/BinaryVectorizer.scala:26-63: maps (property,
value) string pairs to indices of a binary feature vector; vectorization
over many rows is a single scatter.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class BinaryVectorizer:
    property_map: Dict[Tuple[str, str], int]

    @property
    def num_features(self) -> int:
        return len(self.property_map)

    @classmethod
    def fit(cls, rows: Sequence[Dict[str, str]],
            properties: Sequence[str]) -> "BinaryVectorizer":
        """Collect distinct (property, value) pairs -> contiguous indices."""
        pairs = sorted({(p, str(row[p])) for row in rows
                        for p in properties if p in row})
        return cls(property_map={pair: i for i, pair in enumerate(pairs)})

    def to_vector(self, row: Dict[str, str]) -> np.ndarray:
        vec = np.zeros(self.num_features, np.float32)
        for key, value in row.items():
            idx = self.property_map.get((key, str(value)))
            if idx is not None:
                vec[idx] = 1.0
        return vec

    def to_matrix(self, rows: Sequence[Dict[str, str]]) -> np.ndarray:
        out = np.zeros((len(rows), self.num_features), np.float32)
        for i, row in enumerate(rows):
            for key, value in row.items():
                idx = self.property_map.get((key, str(value)))
                if idx is not None:
                    out[i, idx] = 1.0
        return out


# the e2 CrossValidation.splitData analog lives in core.cross_validation
# (shared by every engine's readEval); re-exported here because the e2
# module also shipped it next to the vectorizer
from predictionio_tpu.core.cross_validation import split_data  # noqa: E402,F401
