"""Item cooccurrence counting for similar-product recommendation.

TPU-native replacement for the reference CooccurrenceAlgorithm's Spark
self-join (examples/scala-parallel-similarproduct/multi-events-multi-algos/
src/main/scala/CooccurrenceAlgorithm.scala:71-105): distinct (user, item)
pairs -> per-item-pair counts -> top-N per item.

Design: counting cooccurrences is C = A^T A for the binary user x item
interaction matrix. When the dense A fits a memory budget the count becomes
ONE bf16-friendly MXU matmul (ML-1M: [6040, 3706] -> 8e10 MACs, milliseconds
on a v5e chip, vs a shuffle-heavy Spark join). Larger item spaces fall back
to vectorized host counting over sorted per-user pair enumeration (the same
work the Spark join materializes, without the shuffle).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.data.bimap import vocab_index

#: max dense A entries before falling back to host counting (f32 ~2GB)
DENSE_BUDGET = 500_000_000


def distinct_pairs(user_idx: np.ndarray, item_idx: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """De-duplicate (user, item) events (the reference's .distinct())."""
    combined = user_idx.astype(np.int64) * (item_idx.max() + 1 if item_idx.size else 1) \
        + item_idx.astype(np.int64)
    _, keep = np.unique(combined, return_index=True)
    return user_idx[keep], item_idx[keep]


def cooccurrence_counts_dense(user_idx: np.ndarray, item_idx: np.ndarray,
                              n_users: int, n_items: int) -> np.ndarray:
    """C = A^T A on device — the MXU path. Returns [n_items, n_items] with
    the diagonal zeroed."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def count(u, i):
        a = jnp.zeros((n_users, n_items), jnp.float32).at[u, i].set(1.0)
        c = a.T @ a
        return c * (1.0 - jnp.eye(n_items, dtype=jnp.float32))

    return np.asarray(jax.device_get(count(jnp.asarray(user_idx),
                                           jnp.asarray(item_idx))))


def cooccurrence_topn_host(user_idx: np.ndarray, item_idx: np.ndarray,
                           n_items: int, n: int) -> Dict[int, List[Tuple[int, int]]]:
    """Host fallback: enumerate per-user item pairs vectorized, count, top-N."""
    order = np.argsort(user_idx, kind="stable")
    u_s, i_s = user_idx[order], item_idx[order]
    # pair enumeration per user: for each user's item list, all i1 < i2 combos
    pairs: Dict[Tuple[int, int], int] = {}
    start = 0
    while start < len(u_s):
        end = start
        while end < len(u_s) and u_s[end] == u_s[start]:
            end += 1
        items = np.sort(i_s[start:end])
        if len(items) > 1:
            i1, i2 = np.triu_indices(len(items), k=1)
            for a, b in zip(items[i1], items[i2]):
                if a != b:
                    pairs[(int(a), int(b))] = pairs.get((int(a), int(b)), 0) + 1
        start = end
    top: Dict[int, List[Tuple[int, int]]] = {}
    for (a, b), c in pairs.items():
        top.setdefault(a, []).append((b, c))
        top.setdefault(b, []).append((a, c))
    return {k: sorted(v, key=lambda x: -x[1])[:n] for k, v in top.items()}


def train_cooccurrence(user_idx: np.ndarray, item_idx: np.ndarray,
                       n_users: int, n_items: int, n: int
                       ) -> Dict[int, List[Tuple[int, int]]]:
    """Top-N cooccurring (item, count) per item (trainCooccurrence parity)."""
    if len(user_idx) == 0:
        return {}
    user_idx, item_idx = distinct_pairs(user_idx, item_idx)
    # both the [n_users, n_items] interaction matrix AND the
    # [n_items, n_items] count matrix must fit the budget
    if max(n_users * n_items, n_items * n_items) <= DENSE_BUDGET:
        counts = cooccurrence_counts_dense(user_idx, item_idx, n_users, n_items)
        top: Dict[int, List[Tuple[int, int]]] = {}
        k = min(n, max(n_items - 1, 1))
        idx = np.argpartition(-counts, kth=k - 1, axis=1)[:, :k]
        for item in range(n_items):
            cands = [(int(j), int(counts[item, j])) for j in idx[item]
                     if counts[item, j] > 0]
            if cands:
                top[item] = sorted(cands, key=lambda x: -x[1])[:n]
        return top
    return cooccurrence_topn_host(user_idx, item_idx, n_items, n)


@dataclasses.dataclass
class CooccurrenceModel:
    """CooccurrenceModel parity: top-N lists + id maps."""

    item_vocab: np.ndarray                      # sorted distinct item ids
    top_cooccurrences: Dict[int, List[Tuple[int, int]]]

    def item_index(self, item_id: str) -> Optional[int]:
        return vocab_index(self.item_vocab, item_id)

    def similar(self, item_ids: List[str], num: int,
                exclude_query: bool = True,
                white_list: Optional[List[str]] = None,
                black_list: Optional[List[str]] = None,
                candidate_filter=None,
                ) -> List[Tuple[str, float]]:
        """Combine the query items' top lists (predict parity: sum counts
        per candidate, filter, sort desc). candidate_filter(idx) -> bool
        applies engine-specific rules (e.g. category matching)."""
        query_idx = {i for i in (self.item_index(x) for x in item_ids)
                     if i is not None}
        white = None
        if white_list is not None:
            white = {i for i in (self.item_index(x) for x in white_list)
                     if i is not None}
        black = set()
        if black_list is not None:
            black = {i for i in (self.item_index(x) for x in black_list)
                     if i is not None}
        counts: Dict[int, int] = {}
        for q in query_idx:
            for cand, c in self.top_cooccurrences.get(q, []):
                counts[cand] = counts.get(cand, 0) + c
        out = []
        for cand, c in sorted(counts.items(), key=lambda x: -x[1]):
            if exclude_query and cand in query_idx:
                continue
            if white is not None and cand not in white:
                continue
            if cand in black:
                continue
            if candidate_filter is not None and not candidate_filter(cand):
                continue
            out.append((str(self.item_vocab[cand]), float(c)))
            if len(out) >= num:
                break
        return out
