"""Item cooccurrence counting for similar-product recommendation.

TPU-native replacement for the reference CooccurrenceAlgorithm's Spark
self-join (examples/scala-parallel-similarproduct/multi-events-multi-algos/
src/main/scala/CooccurrenceAlgorithm.scala:71-105): distinct (user, item)
pairs -> per-item-pair counts -> top-N per item.

Design: counting cooccurrences is C = A^T A for the binary user x item
interaction matrix. When A fits the device budget:

* A is scattered on the HOST as uint8 (numpy fancy indexing —
  microseconds; the r2 version used XLA `.at[u,i].set` and lost to
  numpy 0.59x because a big one-hot scatter is a terrible XLA op),
  shipped once and kept device-resident (ops/device_cache), and widened
  on device: bf16 on the MXU (0/1 exact, f32 accumulation, exact below
  2^24), f32 on CPU.
* C's ROW BLOCKS are sharded over the mesh's first axis via shard_map:
  device d assembles full-width A with ONE on-device all_gather (riding
  ICI/DCN — this also serves multi-process meshes), then computes its
  block C[block_d, :] = A[:, block_d]^T @ A in 512-row SLABS, reducing
  each slab to its per-row top-N immediately. Neither the full
  [n_items, n_items] count matrix nor even one device's whole block
  ever materializes — the item-space ceiling is O(nu * ni) HBM, not
  O(ni^2) (SURVEY.md §2.9 P1/P4: the Spark self-join becomes a sharded
  slab matmul + top-k).

Item spaces past the HBM budget fall back to vectorized host counting
over sorted per-user pair enumeration (the same work the Spark join
materializes, without the shuffle).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from predictionio_tpu.data.bimap import vocab_index

#: max dense A entries before falling back to host counting (f32 ~2GB)
#: on CPU / unknown backends
DENSE_BUDGET = 500_000_000
#: per-TPU-chip HBM byte budget for the slabbed kernel (16GB chips,
#: leaving headroom for XLA workspace). The dominant term is the
#: REPLICATED bf16 all-gather of A on every chip — it does not shard,
#: so the budget must not scale with device count. Covers similarproduct
#: at the ML-20M shape (138k x 27k: ~11.3GB/chip) on one v5e.
DEVICE_HBM_BUDGET = 12_000_000_000
#: kernel slab height (rows of the count block materialized at once)
KERNEL_SLAB = 512


def distinct_pairs(user_idx: np.ndarray, item_idx: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """De-duplicate (user, item) events (the reference's .distinct())."""
    combined = user_idx.astype(np.int64) * (item_idx.max() + 1 if item_idx.size else 1) \
        + item_idx.astype(np.int64)
    _, keep = np.unique(combined, return_index=True)
    return user_idx[keep], item_idx[keep]


def cooccurrence_topn(mesh, user_idx: np.ndarray, item_idx: np.ndarray,
                      n_users: int, n_items: int, n_top: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Top-N cooccurrence (counts [n_items, k], item idx [n_items, k])
    via the sharded MXU matmul described in the module docstring. Rows
    with fewer than k nonzero cooccurrents pad with count 0 (filter on
    count > 0). k = min(n_top, n_items)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    # shard_map below shards over the FIRST mesh axis only (other axes
    # replicate), so block geometry must follow that axis's size — the
    # total device count mis-addresses the diagonal on multi-axis meshes
    n_shards = int(mesh.shape[axis])
    k = int(min(n_top, n_items))

    if int(np.prod(mesh.devices.shape)) == 1 and jax.default_backend() == "cpu":
        # single-device CPU fallback: BLAS syrk exploits the symmetry of
        # A^T A (half the FLOPs); XLA lowers it to a generic gemm and
        # loses 2x. The dispatch-aware backend pick mirrors the serving
        # path (models/als.py _use_host).
        from predictionio_tpu.ops.topk import host_topk

        a = np.zeros((n_users, n_items), np.float32)
        a[user_idx, item_idx] = 1.0
        c = a.T @ a
        np.fill_diagonal(c, 0.0)
        return host_topk(c, k)

    # pad items to a multiple of 128 lanes x shard count: zero columns
    # count nothing and padded rows are sliced off after the gather
    blk = -(-n_items // (128 * n_shards)) * 128
    ni_pad = blk * n_shards

    def _put_incidence():
        from predictionio_tpu.utils.profiling import phase

        # build uint8 on host (quarter the f32 bytes over the host->device
        # link) — the kernel widens to the compute dtype on device, where
        # the cast fuses into the matmul read for free
        with phase("incidence_build"):
            a = np.zeros((n_users, ni_pad), np.uint8)
            a[user_idx, item_idx] = 1
        with phase("incidence_transfer"):
            a_dev = jax.device_put(a, NamedSharding(mesh, P(None, axis)))
            jax.block_until_ready(a_dev)
        return a_dev

    # resident across calls keyed on the pair arrays: eval sweeps and
    # warm/timed reruns over the same interactions upload A once
    # (ops/device_cache — the ALSData.put rule for ad-hoc inputs)
    from predictionio_tpu.ops import device_cache

    # the hashable Mesh itself keys the layout — id(mesh) could alias
    # after the mesh is GC'd (the fn_cache.py rule)
    a_dev = device_cache.resident(
        [user_idx, item_idx],
        ("cooc_a", mesh, axis, n_users, ni_pad), _put_incidence)
    run = _sharded_topn_fn(mesh, axis, n_shards, blk, ni_pad, k)
    vals, idx = jax.device_get(run(a_dev))
    return np.asarray(vals)[:n_items], np.asarray(idx)[:n_items]


def _sharded_topn_fn(mesh, axis: str, n_dev: int, blk: int, ni_pad: int,
                     k: int):
    """Compiled sharded count+topk fn, cached per (mesh, shape params) —
    a per-call jit wrapper would re-trace every fold of an eval sweep."""
    from predictionio_tpu.ops.fn_cache import mesh_cached_fn

    def build():
        import jax
        import jax.numpy as jnp
        from predictionio_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from jax.sharding import NamedSharding

        # uint8 A widens on device: bf16 on the MXU (0/1 exact, f32
        # accumulate), f32 on CPU where XLA emulates bf16 matmuls slowly
        cdt = (jnp.bfloat16 if jax.default_backend() in ("tpu", "axon")
               else jnp.float32)
        # row-SLAB the count block: the full [blk, ni_pad] C block would
        # put an O(n_items^2 / n_dev) buffer in HBM (2.9GB at ML-20M's
        # 27k items on one chip); slabs of 512 rows reduce the count to
        # top-k immediately, so HBM holds only A and a [512, ni_pad]
        # slab — the item-space ceiling becomes O(nu * ni), not O(ni^2).
        # Small blocks (f32 C block <= 256MB, e.g. the ML-1M shape) keep
        # the single-matmul fast path: one big MXU dispatch, no loop.
        slab = blk if blk * ni_pad * 4 <= (1 << 28) else min(KERNEL_SLAB, blk)
        n_slabs = -(-blk // slab)
        blk_pad = n_slabs * slab

        def block(a_cols):
            # a_cols [nu, blk]: this device's item column block; the full
            # width is assembled on-device by ONE all-gather riding
            # ICI/DCN — no host ever feeds a replicated copy, which also
            # makes the same kernel serve multi-process meshes
            a_full = jax.lax.all_gather(
                a_cols.astype(cdt), axis, axis=1, tiled=True)
            row0 = jax.lax.axis_index(axis) * blk
            cols = jnp.arange(ni_pad)[None, :]
            a_pad = jnp.pad(a_cols, ((0, 0), (0, blk_pad - blk)))

            def one_slab(j):
                sl = jax.lax.dynamic_slice(
                    a_pad, (0, j * slab), (a_pad.shape[0], slab))
                c = jnp.dot(sl.T.astype(cdt), a_full,
                            preferred_element_type=jnp.float32)
                rows = row0 + j * slab + jnp.arange(slab)[:, None]
                c = jnp.where(rows == cols, 0.0, c)      # zero diagonal
                # padded slab rows (rows >= row0+blk) only ever produce
                # zeros: their a_pad columns are zero
                return jax.lax.top_k(c, k)
            vals, idx = jax.lax.map(one_slab, jnp.arange(n_slabs))
            return (vals.reshape(1, blk_pad, k)[:, :blk],
                    idx.reshape(1, blk_pad, k)[:, :blk])

        sharded = shard_map(
            block, mesh=mesh,
            in_specs=P(None, axis),
            out_specs=(P(axis, None, None), P(axis, None, None)),
            check_vma=False)

        # replicated output: every process can device_get the full top-N
        # (multi-host safe); on one process the final gather is free
        @functools.partial(
            jax.jit, out_shardings=NamedSharding(mesh, P()))
        def run(a_dev):
            vals, idx = sharded(a_dev)
            return (vals.reshape(ni_pad, k), idx.reshape(ni_pad, k))

        return run

    return mesh_cached_fn("cooccurrence_topn", mesh,
                          (axis, blk, ni_pad, k), build)


def cooccurrence_topn_distributed(mesh, local_user_idx: np.ndarray,
                                  local_item_idx: np.ndarray,
                                  n_users: int, n_items: int, n_top: int
                                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Multi-process top-N cooccurrence from PER-PROCESS event shards.

    Each process passes only the (user, item) pairs its own storage shard
    produced (`find_columnar(shard=...)`); pairs are re-keyed to their
    item-column-block owners by one `lax.all_to_all`
    (parallel/shuffle.py), de-duplicated locally, and each process builds
    + commits only ITS column block of the incidence matrix. The same
    sharded matmul kernel then runs with the full-width gather riding the
    interconnect. No process ever materializes the global pair set or the
    full incidence matrix — the Spark distinct+self-join as collectives.
    """
    import jax
    import jax.numpy as jnp  # noqa: F401  (backend probe inside kernel)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from predictionio_tpu.models.als import _process_shard_range
    from predictionio_tpu.parallel.shuffle import exchange_rows

    axis = mesh.axis_names[0]
    n_shards = int(mesh.shape[axis])
    assert n_shards == int(np.prod(mesh.devices.shape)), (
        "distributed cooccurrence requires a 1-axis mesh")
    k = int(min(n_top, n_items))
    blk = -(-n_items // (128 * n_shards)) * 128
    ni_pad = blk * n_shards

    lo, hi = _process_shard_range(mesh)
    shards_per_proc = hi - lo
    # owner read off the mesh (not arithmetic — uneven devices-per-
    # process or non-ascending process order would mis-route rows)
    proc_of_shard = np.asarray(
        [d.process_index for d in mesh.devices.flat], np.int32)
    dest = proc_of_shard[np.minimum(
        local_item_idx.astype(np.int64) // blk, n_shards - 1)]
    payload = np.stack([np.ascontiguousarray(local_user_idx, np.int32),
                        np.ascontiguousarray(local_item_idx, np.int32)],
                       axis=1)
    mine = exchange_rows(dest, payload)
    # global dedup is now local: every copy of a pair landed here
    u, i = distinct_pairs(mine[:, 0], mine[:, 1]) if len(mine) else (
        mine[:, 0], mine[:, 1])
    assert i.size == 0 or (i.min() >= lo * blk and i.max() < hi * blk), (
        "exchange delivered items outside this process's column range")

    a_local = np.zeros((n_users, shards_per_proc * blk), np.uint8)
    a_local[u, i - lo * blk] = 1
    a_dev = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(None, axis)), a_local, (n_users, ni_pad))
    run = _sharded_topn_fn(mesh, axis, n_shards, blk, ni_pad, k)
    vals, idx = jax.device_get(run(a_dev))
    return np.asarray(vals)[:n_items], np.asarray(idx)[:n_items]


def cooccurrence_topn_host(user_idx: np.ndarray, item_idx: np.ndarray,
                           n_items: int, n: int) -> Dict[int, List[Tuple[int, int]]]:
    """Host fallback: enumerate per-user item pairs vectorized, count, top-N."""
    order = np.argsort(user_idx, kind="stable")
    u_s, i_s = user_idx[order], item_idx[order]
    # pair enumeration per user: for each user's item list, all i1 < i2 combos
    pairs: Dict[Tuple[int, int], int] = {}
    start = 0
    while start < len(u_s):
        end = start
        while end < len(u_s) and u_s[end] == u_s[start]:
            end += 1
        items = np.sort(i_s[start:end])
        if len(items) > 1:
            i1, i2 = np.triu_indices(len(items), k=1)
            for a, b in zip(items[i1], items[i2]):
                if a != b:
                    pairs[(int(a), int(b))] = pairs.get((int(a), int(b)), 0) + 1
        start = end
    top: Dict[int, List[Tuple[int, int]]] = {}
    for (a, b), c in pairs.items():
        top.setdefault(a, []).append((b, c))
        top.setdefault(b, []).append((a, c))
    return {k: sorted(v, key=lambda x: -x[1])[:n] for k, v in top.items()}


def train_cooccurrence(user_idx: np.ndarray, item_idx: np.ndarray,
                       n_users: int, n_items: int, n: int, mesh=None
                       ) -> Dict[int, List[Tuple[int, int]]]:
    """Top-N cooccurring (item, count) per item (trainCooccurrence parity).

    With a mesh, C's row blocks spread over its first axis; without one,
    a single-device mesh on the default backend."""
    if len(user_idx) == 0:
        return {}
    user_idx, item_idx = distinct_pairs(user_idx, item_idx)
    # budget check BEFORE any jax backend init (jax.devices() claims the
    # chip — pointless and potentially minutes-slow over a tunnel when
    # the host fallback is going to run anyway). The slabbed kernel never
    # materializes the [n_items, n_items] count matrix; its PER-CHIP
    # working set is the uint8 A shard + the replicated bf16 all-gather
    # of full-width A (which does NOT shrink with more chips) + one
    # [slab, ni_pad] f32 count block. With a mesh already claimed we can
    # see the backend; the CPU/default budget stays conservative.
    n_shards = int(mesh.shape[mesh.axis_names[0]]) if mesh is not None else 1
    ni_pad = -(-n_items // (128 * n_shards)) * 128 * n_shards
    fits = n_users * ni_pad <= DENSE_BUDGET
    if not fits and mesh is not None:
        import jax

        if jax.default_backend() in ("tpu", "axon"):
            n_dev = int(np.prod(mesh.devices.shape))
            per_chip = (n_users * ni_pad // n_dev       # uint8 shard
                        + 2 * n_users * ni_pad          # bf16 gather
                        + 4 * KERNEL_SLAB * ni_pad)     # f32 slab block
            fits = per_chip <= DEVICE_HBM_BUDGET
    if fits:
        if mesh is None:
            import jax
            from jax.sharding import Mesh

            mesh = Mesh(np.asarray(jax.devices())[:1], axis_names=("data",))
        vals, idx = cooccurrence_topn(mesh, user_idx, item_idx,
                                      n_users, n_items, n)
        top: Dict[int, List[Tuple[int, int]]] = {}
        for item in range(n_items):
            cands = [(int(j), int(c)) for j, c in zip(idx[item], vals[item])
                     if c > 0]
            if cands:
                top[item] = cands       # top_k output is already sorted desc
        return top
    return cooccurrence_topn_host(user_idx, item_idx, n_items, n)


@dataclasses.dataclass
class CooccurrenceModel:
    """CooccurrenceModel parity: top-N lists + id maps."""

    item_vocab: np.ndarray                      # sorted distinct item ids
    top_cooccurrences: Dict[int, List[Tuple[int, int]]]

    def item_index(self, item_id: str) -> Optional[int]:
        return vocab_index(self.item_vocab, item_id)

    def similar(self, item_ids: List[str], num: int,
                exclude_query: bool = True,
                white_list: Optional[List[str]] = None,
                black_list: Optional[List[str]] = None,
                candidate_filter=None,
                ) -> List[Tuple[str, float]]:
        """Combine the query items' top lists (predict parity: sum counts
        per candidate, filter, sort desc). candidate_filter(idx) -> bool
        applies engine-specific rules (e.g. category matching)."""
        query_idx = {i for i in (self.item_index(x) for x in item_ids)
                     if i is not None}
        white = None
        if white_list is not None:
            white = {i for i in (self.item_index(x) for x in white_list)
                     if i is not None}
        black = set()
        if black_list is not None:
            black = {i for i in (self.item_index(x) for x in black_list)
                     if i is not None}
        counts: Dict[int, int] = {}
        for q in query_idx:
            for cand, c in self.top_cooccurrences.get(q, []):
                counts[cand] = counts.get(cand, 0) + c
        out = []
        for cand, c in sorted(counts.items(), key=lambda x: -x[1]):
            if exclude_query and cand in query_idx:
                continue
            if white is not None and cand not in white:
                continue
            if cand in black:
                continue
            if candidate_filter is not None and not candidate_filter(cand):
                continue
            out.append((str(self.item_vocab[cand]), float(c)))
            if len(out) >= num:
                break
        return out
