"""Markov chain transition model (e2 parity).

Parity with e2/.../engine/MarkovChain.scala:25-87: from (i, j, count)
transition observations build a row-normalized transition matrix keeping the
top-N entries per row; predict(current_state) returns that row's top
transitions. Normalization/top-N are vectorized.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import numpy as np


@dataclasses.dataclass
class MarkovChainModel:
    """n states; per-row top-N (next_state, probability) lists."""

    n_states: int
    top_n: int
    transitions: Dict[int, List[Tuple[int, float]]]

    def predict(self, current_state: int) -> List[Tuple[int, float]]:
        return self.transitions.get(current_state, [])


def train_markov_chain(src: np.ndarray, dst: np.ndarray, counts: np.ndarray,
                       n_states: int, top_n: int) -> MarkovChainModel:
    """MarkovChain.train parity over COO (src, dst, count) observations."""
    # aggregate duplicate (src, dst) entries
    keys = src.astype(np.int64) * n_states + dst.astype(np.int64)
    uniq, inv = np.unique(keys, return_inverse=True)
    agg = np.zeros(len(uniq), np.float64)
    np.add.at(agg, inv, counts)
    s = (uniq // n_states).astype(np.int64)
    d = (uniq % n_states).astype(np.int64)
    row_sum = np.zeros(n_states, np.float64)
    np.add.at(row_sum, s, agg)
    prob = agg / row_sum[s]

    transitions: Dict[int, List[Tuple[int, float]]] = {}
    order = np.lexsort((-prob, s))
    for idx in order:
        row = int(s[idx])
        lst = transitions.setdefault(row, [])
        if len(lst) < top_n:
            lst.append((int(d[idx]), float(prob[idx])))
    return MarkovChainModel(n_states=n_states, top_n=top_n,
                            transitions=transitions)
