"""Softmax / logistic regression via optax.

The MLlib LogisticRegression analog used by the classification template
variants (SURVEY.md section 2.8). Full-batch jitted gradient descent with
optax.adam: for template-scale data the whole dataset lives on device and
each step is one fused MXU matmul + softmax-CE; lax.scan drives the epochs
inside a single compiled program.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np


@dataclasses.dataclass
class LogRegParams:
    iterations: int = 200
    learning_rate: float = 0.1
    reg: float = 1e-4
    seed: int = 0


@dataclasses.dataclass
class LogRegModel:
    label_vocab: np.ndarray
    W: np.ndarray            # [F, L]
    b: np.ndarray            # [L]

    def predict_scores(self, X: np.ndarray) -> np.ndarray:
        return np.atleast_2d(X) @ self.W + self.b

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self.label_vocab[np.argmax(self.predict_scores(X), axis=1)]


def train_logreg(X: np.ndarray, labels: Sequence[str],
                 params: LogRegParams = LogRegParams()) -> LogRegModel:
    import jax
    import jax.numpy as jnp
    import optax

    labels = np.asarray(labels, dtype=object)
    label_vocab, y = np.unique(labels, return_inverse=True)
    n_features, n_labels = X.shape[1], len(label_vocab)

    Xd = jnp.asarray(X, jnp.float32)
    yd = jnp.asarray(y, jnp.int32)

    def loss_fn(w_b):
        W, b = w_b
        logits = Xd @ W + b
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, yd).mean()
        return ce + params.reg * (W * W).sum()

    opt = optax.adam(params.learning_rate)
    key = jax.random.PRNGKey(params.seed)
    W0 = jax.random.normal(key, (n_features, n_labels), jnp.float32) * 0.01
    b0 = jnp.zeros((n_labels,), jnp.float32)

    @jax.jit
    def fit(W, b):
        state = opt.init((W, b))

        def step(carry, _):
            (W, b), state = carry
            grads = jax.grad(loss_fn)((W, b))
            updates, state = opt.update(grads, state)
            W, b = optax.apply_updates((W, b), updates)
            return ((W, b), state), None

        ((W, b), _), _ = jax.lax.scan(
            step, ((W, b), state), None, length=params.iterations)
        return W, b

    W, b = fit(W0, b0)
    return LogRegModel(
        label_vocab=label_vocab,
        W=np.asarray(jax.device_get(W)),
        b=np.asarray(jax.device_get(b)))
