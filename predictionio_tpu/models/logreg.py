"""Softmax / logistic regression via optax.

The MLlib LogisticRegression analog used by the classification template
variants (SURVEY.md section 2.8). Full-batch jitted gradient descent with
optax.adam: for template-scale data the whole dataset lives on device and
each step is one fused MXU matmul + softmax-CE; lax.scan drives the epochs
inside a single compiled program.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np


@dataclasses.dataclass
class LogRegParams:
    iterations: int = 200
    learning_rate: float = 0.1
    reg: float = 1e-4
    seed: int = 0


@dataclasses.dataclass
class LogRegModel:
    label_vocab: np.ndarray
    W: np.ndarray            # [F, L]
    b: np.ndarray            # [L]

    def predict_scores(self, X: np.ndarray) -> np.ndarray:
        return np.atleast_2d(X) @ self.W + self.b

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self.label_vocab[np.argmax(self.predict_scores(X), axis=1)]


def train_logreg(X: np.ndarray, labels: Sequence[str],
                 params: LogRegParams = LogRegParams(),
                 mesh=None) -> LogRegModel:
    """With a multi-device `mesh`, example rows shard over its first axis
    (NamedSharding) and XLA's SPMD partitioner inserts the gradient psum —
    data-parallel training in the collective-over-ICI style of SURVEY §2.9
    P1 (replacing MLlib LogisticRegression's Spark aggregation). Padded
    rows carry weight 0 so the masked mean is shard-count invariant."""
    import jax
    import jax.numpy as jnp
    import optax

    labels = np.asarray(labels, dtype=object)
    label_vocab, y = np.unique(labels, return_inverse=True)
    n_features, n_labels = X.shape[1], len(label_vocab)

    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    pad = (-len(y)) % n_dev
    Xp = np.concatenate([X, np.zeros((pad, n_features), X.dtype)]) \
        if pad else X
    yp = np.concatenate([y, np.zeros(pad, y.dtype)]) if pad else y
    wts = np.concatenate([np.ones(len(y), np.float32),
                          np.zeros(pad, np.float32)])
    if mesh is not None and n_dev > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = mesh.axis_names[0]
        Xd = jax.device_put(np.asarray(Xp, np.float32),
                            NamedSharding(mesh, P(axis, None)))
        yd = jax.device_put(np.asarray(yp, np.int32),
                            NamedSharding(mesh, P(axis)))
        wd = jax.device_put(wts, NamedSharding(mesh, P(axis)))
    else:
        Xd = jnp.asarray(Xp, jnp.float32)
        yd = jnp.asarray(yp, jnp.int32)
        wd = jnp.asarray(wts)

    def loss_fn(w_b):
        W, b = w_b
        logits = Xd @ W + b
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, yd)
        ce = (ce * wd).sum() / wd.sum()
        return ce + params.reg * (W * W).sum()

    opt = optax.adam(params.learning_rate)
    key = jax.random.PRNGKey(params.seed)
    W0 = jax.random.normal(key, (n_features, n_labels), jnp.float32) * 0.01
    b0 = jnp.zeros((n_labels,), jnp.float32)

    @jax.jit
    def fit(W, b):
        state = opt.init((W, b))

        def step(carry, _):
            (W, b), state = carry
            grads = jax.grad(loss_fn)((W, b))
            updates, state = opt.update(grads, state)
            W, b = optax.apply_updates((W, b), updates)
            return ((W, b), state), None

        ((W, b), _), _ = jax.lax.scan(
            step, ((W, b), state), None, length=params.iterations)
        return W, b

    W, b = fit(W0, b0)
    return LogRegModel(
        label_vocab=label_vocab,
        W=np.asarray(jax.device_get(W)),
        b=np.asarray(jax.device_get(b)))
