"""Naive Bayes classifiers.

Two variants, replacing the reference's two NB paths:
  * CategoricalNaiveBayes — parity with e2's string-feature NB
    (e2/.../engine/CategoricalNaiveBayes.scala:23-172): per-position
    categorical features, log prior + per-feature log likelihoods, optional
    default-likelihood function for unseen values. Counting is vectorized
    (np.unique + bincount) instead of combineByKey.
  * MultinomialNB — the MLlib NaiveBayes analog used by the classification
    template (examples/scala-parallel-classification/add-algorithm/src/main/
    scala/NaiveBayesAlgorithm.scala:35-56): numeric count-vector features;
    prediction is one MXU matmul X @ logP^T + prior.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# Categorical NB (e2 parity)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """e2 LabeledPoint: (label, string features per position)."""

    label: str
    features: Tuple[str, ...]


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    """priors/likelihoods structure parity (CategoricalNaiveBayes.scala:87)."""

    priors: Dict[str, float]                           # label -> log prior
    likelihoods: Dict[str, List[Dict[str, float]]]     # label -> per-position

    def log_score(self, point: LabeledPoint,
                  default_likelihood: Callable[[Sequence[float]], float]
                  = lambda ls: float("-inf")) -> Optional[float]:
        if point.label not in self.priors:
            return None
        return self._log_score(point.label, point.features,
                               default_likelihood)

    def _log_score(self, label: str, features: Sequence[str],
                   default_likelihood) -> float:
        ll = self.likelihoods[label]
        total = self.priors[label]
        for feature, position in zip(features, ll):
            total += position.get(
                feature, default_likelihood(list(position.values())))
        return total

    def predict(self, features: Sequence[str]) -> str:
        scored = [(label, self._log_score(label, features,
                                          lambda ls: float("-inf")))
                  for label in self.priors]
        return max(scored, key=lambda x: x[1])[0]


def train_categorical_nb(points: Sequence[LabeledPoint]
                         ) -> CategoricalNaiveBayesModel:
    """CategoricalNaiveBayes.train parity, vectorized."""
    if not points:
        raise ValueError("no training points")
    n_positions = len(points[0].features)
    labels = np.asarray([p.label for p in points], dtype=object)
    label_vocab, label_codes = np.unique(labels, return_inverse=True)
    label_counts = np.bincount(label_codes, minlength=len(label_vocab))
    total = float(len(points))

    priors = {str(lab): math.log(label_counts[i] / total)
              for i, lab in enumerate(label_vocab)}
    likelihoods: Dict[str, List[Dict[str, float]]] = {
        str(lab): [] for lab in label_vocab}

    for pos in range(n_positions):
        feats = np.asarray([p.features[pos] for p in points], dtype=object)
        feat_vocab, feat_codes = np.unique(feats, return_inverse=True)
        # joint counts [n_labels, n_feat_values] in one bincount
        joint = np.bincount(
            label_codes * len(feat_vocab) + feat_codes,
            minlength=len(label_vocab) * len(feat_vocab),
        ).reshape(len(label_vocab), len(feat_vocab))
        for li, lab in enumerate(label_vocab):
            position_map = {
                str(feat_vocab[fi]): math.log(joint[li, fi] / label_counts[li])
                for fi in range(len(feat_vocab)) if joint[li, fi] > 0}
            likelihoods[str(lab)].append(position_map)

    return CategoricalNaiveBayesModel(priors=priors, likelihoods=likelihoods)


# ---------------------------------------------------------------------------
# Multinomial NB (MLlib analog)
# ---------------------------------------------------------------------------

#: inputs below this element count train on host (BLAS one-hot gemm) —
#: the device (or sharded-device) count matmul can't repay its transfer
#: + dispatch below this size
DEVICE_MIN_SIZE = 1_000_000

def _sharded_count_fn(mesh, axis: str, n_labels: int):
    """Compiled sharded count fn, cached per (mesh, n_labels) — jit's
    cache keys on function identity, so the wrapper must be reused."""
    from predictionio_tpu.ops.fn_cache import mesh_cached_fn

    def build():
        import jax
        import jax.numpy as jnp
        from predictionio_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        def count_block(c, x):
            onehot = jax.nn.one_hot(c, n_labels, dtype=jnp.float32)
            return jax.lax.psum(onehot.T @ x.astype(jnp.float32), axis)

        return jax.jit(shard_map(
            count_block, mesh=mesh,
            in_specs=(P(axis), P(axis, None)),
            out_specs=P()))

    return mesh_cached_fn("nb_count", mesh, (axis, n_labels), build)


def _count_fn(n_labels: int):
    """Stable single-device count jit per label count (a per-call jit
    would recompile every train — seconds over a remote-compile relay).
    Ledger-cached so the per-label-count programs show up bounded in
    ``pio_jax_compile_total{family=nb_count_host}``."""
    from predictionio_tpu.ops.fn_cache import shape_cached_fn

    def build():
        import jax
        import jax.numpy as jnp

        @jax.jit
        def count(codes, x):
            onehot = jax.nn.one_hot(codes, n_labels, dtype=jnp.float32)
            return onehot.T @ x.astype(jnp.float32)

        return count

    return shape_cached_fn("nb_count_host", n_labels, build)


def _compact_for_transfer(X: np.ndarray) -> np.ndarray:
    """Count matrices are usually small non-negative integers stored as
    float; ship them as uint8/uint16 (4x/2x fewer bytes over the
    host->device link — the usual bottleneck, SURVEY §7 'HBM bandwidth')
    and widen to f32 on device."""
    if X.dtype.kind in "ui":
        return X
    if X.dtype.kind != "f" or X.size == 0:
        return X
    xmax, xmin = X.max(), X.min()
    if xmin < 0 or xmax >= 65536 or np.any(np.mod(X, 1)):
        return X
    return X.astype(np.uint8 if xmax < 256 else np.uint16)


def _score_fn():
    """Stable scoring jit (a per-call wrapper would re-trace — and
    re-COMPILE, seconds over a remote-compile relay — every predict);
    one ledger entry under ``family=nb_score``."""
    from predictionio_tpu.ops.fn_cache import shape_cached_fn

    def build():
        import jax
        import jax.numpy as jnp

        @jax.jit
        def score(x, lp, pri):
            return x.astype(jnp.float32) @ lp.T + pri[None, :]

        return score

    return shape_cached_fn("nb_score", (), build)


#: device predict only pays off above this element count when the input
#: is NOT already device-resident (host BLAS beats tunnel transfer)
PREDICT_DEVICE_MIN_SIZE = 50_000_000


@dataclasses.dataclass
class MultinomialNBModel:
    """label vocab + log priors [L] + log feature probs [L, F]."""

    label_vocab: np.ndarray
    log_prior: np.ndarray
    log_prob: np.ndarray

    def predict_scores(self, X: np.ndarray) -> np.ndarray:
        """[N, F] -> [N, L] joint log-likelihood (one matmul).

        Dispatch-aware routing (the serving-path rule, models/als.py
        _use_host): the matmul is tiny next to shipping X over the
        host->device link, so the device only wins when X is already
        resident there (train just ran on it) or very large. The cache
        keys on the CALLER's array object (atleast_2d happens inside the
        build), so train-then-predict on the same X reuses one upload."""
        from predictionio_tpu.ops import device_cache

        if not device_cache.is_resident([X], ("nb_x",)) \
                and X.size < PREDICT_DEVICE_MIN_SIZE:
            xs = np.atleast_2d(X)
            return xs.astype(np.float32, copy=False) @ self.log_prob.T \
                + self.log_prior[None, :]
        import jax

        xd = device_cache.resident(
            [X], ("nb_x",),
            lambda: jax.device_put(_compact_for_transfer(np.atleast_2d(X))))
        scores = np.asarray(jax.device_get(_score_fn()(
            xd, self.log_prob, self.log_prior)))
        # a resident copy from a sharded train carries device-count
        # padding rows; slice back to the caller's row count
        return scores[:np.atleast_2d(X).shape[0]]

    def predict(self, X: np.ndarray) -> np.ndarray:
        scores = self.predict_scores(np.atleast_2d(X))
        return self.label_vocab[np.argmax(scores, axis=1)]


def train_multinomial_nb(X: np.ndarray, labels: Sequence[str],
                         smoothing: float = 1.0, mesh=None
                         ) -> MultinomialNBModel:
    """MLlib NaiveBayes.train parity (lambda smoothing). Per-label feature
    counting runs as a one-hot [L,N]@[N,F] device matmul (MXU) when the
    input is big enough to pay for the transfer.

    With a multi-device `mesh`, documents shard over its first axis and
    each device contributes a partial [L, F] count combined by one psum —
    the collective analog of the reference's distributed `combineByKey`
    (e2/.../CategoricalNaiveBayes.scala:29, SURVEY §2.9 P1)."""
    from predictionio_tpu.ops import device_cache

    labels = np.asarray(labels, dtype=object)
    label_vocab, label_codes = np.unique(labels, return_inverse=True)
    n_labels = len(label_vocab)
    n_features = X.shape[1]
    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    # device path: worth the transfer for big X, but the [N, L] one-hot it
    # materializes must stay bounded too (many-label inputs would OOM where
    # the host path needs only the [L, F] buffer)
    if mesh is not None and n_dev > 1 and X.size >= DEVICE_MIN_SIZE \
            and X.shape[0] * n_labels * 4 <= (1 << 28) * n_dev:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        axis = mesh.axis_names[0]
        shard = int(mesh.shape[axis])
        pad = (-len(label_codes)) % shard

        def _put_x_sharded():
            from predictionio_tpu.utils.profiling import phase

            with phase("nb_compact"):
                Xc = _compact_for_transfer(X)
                if pad:
                    Xc = np.concatenate(
                        [Xc, np.zeros((pad, n_features), Xc.dtype)])
            with phase("nb_transfer"):
                xd = jax.device_put(Xc, NamedSharding(mesh, P(axis, None)))
                jax.block_until_ready(xd)
            return xd

        # only X's sharded placement is cached (labels change freely —
        # the tiny padded codes vector ships fresh on every call); the
        # hashable Mesh itself keys the layout (id(mesh) could alias
        # after GC — the fn_cache.py rule)
        xd = device_cache.resident(
            [X], ("nb_x_sharded", mesh, pad), _put_x_sharded)
        # alias under predict's key too: model.predict(X) must reuse this
        # resident copy instead of paying a second full upload (the score
        # matmul slices the padding rows back off)
        device_cache.resident([X], ("nb_x",), lambda: xd)
        codes = np.concatenate(
            [label_codes.astype(np.int32),
             np.full(pad, -1, np.int32)]         # one_hot(-1) == zero row
        ) if pad else label_codes.astype(np.int32)
        counts = np.asarray(jax.device_get(
            _sharded_count_fn(mesh, axis, n_labels)(codes, xd)
        )).astype(np.float64)
    elif X.size >= DEVICE_MIN_SIZE and X.shape[0] * n_labels * 4 <= 1 << 28:
        import jax

        def _put_x():
            from predictionio_tpu.utils.profiling import phase

            with phase("nb_compact"):
                Xc = _compact_for_transfer(X)
            with phase("nb_transfer"):
                xd = jax.device_put(Xc)
                jax.block_until_ready(xd)
            return xd

        xd = device_cache.resident([X], ("nb_x",), _put_x)
        counts = np.asarray(jax.device_get(_count_fn(n_labels)(
            label_codes.astype(np.int32), xd))).astype(np.float64)
    elif X.dtype.kind == "f" and X.shape[0] * n_labels * 4 <= 1 << 28:
        # host BLAS one-hot count: one [L, N] @ [N, F] gemm — ~20x faster
        # than np.add.at's per-element scatter at spam-corpus sizes. Same
        # 256MB one-hot bound as the device branch: past it, fall through
        # to the O(1)-extra-memory scatter fold
        onehot = np.zeros((n_labels, X.shape[0]), np.float32)
        onehot[label_codes, np.arange(X.shape[0])] = 1.0
        counts = (onehot @ X).astype(np.float64)
    else:
        counts = np.zeros((n_labels, n_features), np.float64)
        np.add.at(counts, label_codes, X)
    label_counts = np.bincount(label_codes, minlength=n_labels)
    log_prior = np.log(label_counts / label_counts.sum())
    smoothed = counts + smoothing
    log_prob = np.log(smoothed / smoothed.sum(axis=1, keepdims=True))
    return MultinomialNBModel(
        label_vocab=label_vocab,
        log_prior=log_prior.astype(np.float32),
        log_prob=log_prob.astype(np.float32))
