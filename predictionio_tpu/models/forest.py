"""Random-forest classifier, vectorized over trees on the device.

The tree model behind the classification template's RandomForest variant
(examples/scala-parallel-classification/add-algorithm/src/main/scala/
RandomForestAlgorithm.scala — MLlib `RandomForest.trainClassifier` with
numClasses/numTrees/featureSubsetStrategy/impurity/maxDepth/maxBins).

TPU-native design — nothing like MLlib's per-node task queues:

  * features are quantized once on host into `max_bins` quantile bins
    (MLlib's binning), so split search is integer histogramming;
  * every tree is a COMPLETE binary array of depth `max_depth` grown
    breadth-first: at level d all 2^d nodes of ALL trees split at once.
    One `segment_sum` builds the [nodes*features*bins*classes] histogram
    cell grid, a cumulative-sum scan turns it into left/right class
    counts per candidate threshold, and an argmin over the impurity
    surface picks each node's (feature, threshold) — fixed shapes
    throughout, `vmap` over trees, one jit for the whole fit;
  * bootstrap resampling and per-(tree, node) feature subsets are index
    arrays drawn up front (`featureSubsetStrategy` auto/all/sqrt/onethird);
  * prediction walks all trees in lockstep ([T, N] gathers per level) and
    majority-votes, MLlib's classification vote.

Nodes are always split to full depth; a node with no valid split (pure,
or empty under bootstrap) stores the sentinel threshold B-1 so every
sample routes left and the leaf majority is unchanged — the shape-static
equivalent of MLlib's early leaf cut-off.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.core.params import Params


@dataclasses.dataclass
class ForestParams(Params):
    """RandomForestAlgorithmParams parity."""

    num_classes: int = 0                  # 0 = infer from labels
    num_trees: int = 10
    feature_subset_strategy: str = "auto"   # auto|all|sqrt|onethird
    impurity: str = "gini"                  # gini|entropy
    max_depth: int = 4
    max_bins: int = 32
    seed: int = 0


def _subset_size(strategy: str, n_features: int) -> int:
    s = strategy.lower()
    if s == "auto" or s == "sqrt":
        # MLlib classification "auto" = sqrt
        return max(1, int(np.ceil(np.sqrt(n_features))))
    if s == "onethird":
        return max(1, int(np.ceil(n_features / 3)))
    if s == "all":
        return n_features
    raise ValueError(f"unknown featureSubsetStrategy {strategy!r}")


def _impurity_cost(left, right, kind: str):
    """Weighted impurity of a (left, right) class-count split.
    left/right: [..., C] counts. Returns [...] cost; +inf where a side
    is empty (invalid split, MLlib's minInstancesPerNode=1)."""
    nl = left.sum(-1)
    nr = right.sum(-1)
    n = nl + nr

    def node_impurity(counts, total):
        p = counts / jnp.maximum(total, 1.0)[..., None]
        if kind == "entropy":
            return -(jnp.where(p > 0, p * jnp.log(p), 0.0)).sum(-1)
        return 1.0 - (p * p).sum(-1)          # gini

    cost = (nl * node_impurity(left, nl) +
            nr * node_impurity(right, nr)) / jnp.maximum(n, 1.0)
    return jnp.where((nl == 0) | (nr == 0), jnp.inf, cost)


def _fit_kernel(bins, labels, boot_idx, feat_mask, n_classes: int,
                max_depth: int, max_bins: int, impurity: str):
    """Single-tree fit on quantized features; vmapped over trees.

    bins      [N, F] int32 quantile-bin codes
    labels    [N] int32 class codes
    boot_idx  [N] int32 bootstrap sample indices (this tree's bag)
    feat_mask [2^max_depth - 1, F] bool — allowed features per node
    Returns (feat [M], thr [M], leaf [2^max_depth] class ids) with
    M = 2^max_depth - 1 internal nodes in breadth-first order.
    """
    n, f = bins.shape
    b, c = max_bins, n_classes
    xb = bins[boot_idx]                       # [N, F] this tree's bag
    yb = labels[boot_idx]                     # [N]

    feat_out = jnp.zeros((2 ** max_depth - 1,), jnp.int32)
    thr_out = jnp.full((2 ** max_depth - 1,), b - 1, jnp.int32)
    node = jnp.zeros((n,), jnp.int32)         # relative id within level

    for d in range(max_depth):
        width = 2 ** d
        base = width - 1
        # histogram: cell = ((node*F + f)*B + bin) -> [width*F*B, C]
        cell = (node[:, None] * f + jnp.arange(f)[None, :]) * b + xb
        onehot = jax.nn.one_hot(yb, c, dtype=jnp.float32)
        hist = jax.ops.segment_sum(
            jnp.repeat(onehot, f, axis=0).reshape(n, f, c).reshape(-1, c),
            cell.reshape(-1), num_segments=width * f * b)
        hist = hist.reshape(width, f, b, c)
        # threshold t sends bin <= t left: left counts = cumsum over bins
        left = jnp.cumsum(hist, axis=2)        # [w, F, B, C]
        total = left[:, :, -1:, :]
        right = total - left
        cost = _impurity_cost(left, right, impurity)   # [w, F, B]
        # last bin (everything left) is the no-op sentinel; forbid it in
        # the argmin by +inf, and forbid disallowed features
        cost = cost.at[:, :, -1].set(jnp.inf)
        mask = feat_mask[base:base + width]            # [w, F]
        cost = jnp.where(mask[:, :, None], cost, jnp.inf)
        flat = cost.reshape(width, f * b)
        best = jnp.argmin(flat, axis=1)                # [w]
        best_cost = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
        bf = (best // b).astype(jnp.int32)
        bt = (best % b).astype(jnp.int32)
        # no valid split -> sentinel (feature 0, thr B-1: all left)
        ok = jnp.isfinite(best_cost)
        bf = jnp.where(ok, bf, 0)
        bt = jnp.where(ok, bt, b - 1)
        feat_out = jax.lax.dynamic_update_slice(feat_out, bf, (base,))
        thr_out = jax.lax.dynamic_update_slice(thr_out, bt, (base,))
        # route samples
        nf = bf[node]
        nt = bt[node]
        go_right = jnp.take_along_axis(xb, nf[:, None], 1)[:, 0] > nt
        node = node * 2 + go_right.astype(jnp.int32)

    # leaves: majority class of the final level's histogram
    width = 2 ** max_depth
    cell = node * c + yb
    leaf_hist = jax.ops.segment_sum(
        jnp.ones((n,), jnp.float32), cell, num_segments=width * c
    ).reshape(width, c)
    leaf = jnp.argmax(leaf_hist, axis=1).astype(jnp.int32)
    return feat_out, thr_out, leaf


@functools.partial(jax.jit, static_argnames=("n_classes", "max_depth",
                                             "max_bins", "impurity"))
def _fit_forest(bins, labels, boot_idx, feat_mask, n_classes, max_depth,
                max_bins, impurity):
    return jax.vmap(
        lambda bi, fm: _fit_kernel(bins, labels, bi, fm, n_classes,
                                   max_depth, max_bins, impurity)
    )(boot_idx, feat_mask)


def _sharded_fit_fn(mesh, c: int, depth: int, b: int, impurity: str):
    """Compiled tree-sharded fit fn, cached per (mesh, hyperparams) — a
    per-call jit(shard_map(...)) wrapper would re-trace every fold of a
    cross-validated eval (jit's cache keys on function identity)."""
    from predictionio_tpu.ops.fn_cache import mesh_cached_fn

    axis = mesh.axis_names[0]

    def build():
        from predictionio_tpu.parallel.compat import shard_map
        from jax.sharding import PartitionSpec as P

        return jax.jit(shard_map(
            lambda xqd, cd, bi, fm: jax.vmap(
                lambda one_b, one_m: _fit_kernel(
                    xqd, cd, one_b, one_m, c, depth, b, impurity)
            )(bi, fm),
            mesh=mesh,
            in_specs=(P(), P(), P(axis, None), P(axis, None, None)),
            out_specs=(P(axis, None), P(axis, None), P(axis, None)),
            check_vma=False))

    return mesh_cached_fn("forest_fit", mesh, (axis, c, depth, b, impurity),
                          build)


@functools.partial(jax.jit, static_argnames=("max_depth", "n_classes"))
def _predict_kernel(feat, thr, leaf, qbins, max_depth, n_classes):
    """feat/thr [T, M], leaf [T, 2^D], qbins [N, F] -> votes argmax [N]."""
    t = feat.shape[0]
    nq = qbins.shape[0]
    node = jnp.zeros((t, nq), jnp.int32)
    for d in range(max_depth):
        base = 2 ** d - 1
        nf = jnp.take_along_axis(feat, base + node, axis=1)    # [T, N]
        nt = jnp.take_along_axis(thr, base + node, axis=1)
        xb = qbins.T[None, :, :]                                # [1, F, N]
        val = jnp.take_along_axis(
            jnp.broadcast_to(xb, (t,) + xb.shape[1:]), nf[:, None, :],
            axis=1)[:, 0, :]
        node = node * 2 + (val > nt).astype(jnp.int32)
    pred = jnp.take_along_axis(leaf, node, axis=1)              # [T, N]
    votes = jax.vmap(
        lambda col: jnp.bincount(col, length=n_classes),
        in_axes=1)(pred)                                        # [N, C]
    return jnp.argmax(votes, axis=1)


@dataclasses.dataclass
class ForestModel:
    """Picklable forest: bin thresholds + per-tree node arrays."""

    classes: np.ndarray          # [C] original labels (object/str)
    thresholds: np.ndarray       # [F, B-1] float32 quantile cut points
    feat: np.ndarray             # [T, 2^D - 1] int32
    thr: np.ndarray              # [T, 2^D - 1] int32 (bin index)
    leaf: np.ndarray             # [T, 2^D] int32 class codes
    max_depth: int

    def _binize(self, X: np.ndarray) -> np.ndarray:
        xq = np.empty(X.shape, np.int32)
        for j in range(X.shape[1]):
            xq[:, j] = np.searchsorted(self.thresholds[j], X[:, j],
                                       side="left")
        return xq

    def predict(self, X: np.ndarray) -> np.ndarray:
        """[N, F] -> [N] predicted labels (majority vote)."""
        X = np.asarray(X, np.float32)
        codes = _predict_kernel(
            jnp.asarray(self.feat), jnp.asarray(self.thr),
            jnp.asarray(self.leaf), jnp.asarray(self._binize(X)),
            self.max_depth, len(self.classes))
        return self.classes[np.asarray(codes)]


def train_forest(X: np.ndarray, y: Sequence, params: ForestParams,
                 mesh=None) -> ForestModel:
    """Fit a forest on dense [N, F] features with arbitrary labels.

    With a multi-device `mesh`, TREES shard over its first axis (the
    embarrassingly-parallel axis MLlib also exploits per-tree): each
    device grows its tree subset on replicated binned data, no cross-
    device traffic until the per-tree node arrays gather at the end.
    num_trees pads up to a shard-count multiple for the fit, then the
    padding is sliced off so the model is mesh-shape invariant."""
    X = np.asarray(X, np.float32)
    n, f = X.shape
    classes, codes = np.unique(np.asarray(y), return_inverse=True)
    c = int(params.num_classes) or len(classes)
    if c < len(classes):
        raise ValueError(f"numClasses={c} but labels have {len(classes)}")
    b = int(params.max_bins)

    # quantile binning (MLlib's findSplits): B-1 interior cut points
    qs = np.linspace(0, 1, b + 1)[1:-1]
    thresholds = np.quantile(X, qs, axis=0).T.astype(np.float32)  # [F, B-1]
    xq = np.empty((n, f), np.int32)
    for j in range(f):
        xq[:, j] = np.searchsorted(thresholds[j], X[:, j], side="left")

    t_req = int(params.num_trees)
    # trees shard over the FIRST mesh axis only (_sharded_fit_fn), so the
    # pad target is that axis's size, not the total device count
    n_dev = int(mesh.shape[mesh.axis_names[0]]) if mesh is not None else 1
    depth = int(params.max_depth)
    # RNG draws sized by the REQUESTED tree count so the stream (and hence
    # every kept tree) is identical on any mesh; padding to the device-
    # count multiple happens on the arrays afterwards and is sliced off
    # the model below
    rng = np.random.default_rng(params.seed)
    boot = rng.integers(0, n, size=(t_req, n)).astype(np.int32)
    m = _subset_size(params.feature_subset_strategy, f)
    n_nodes = 2 ** depth - 1
    if m >= f:
        mask = np.ones((t_req, n_nodes, f), bool)
    else:
        # per-(tree, node) random feature subset of size m
        scores = rng.random((t_req, n_nodes, f))
        kth = np.partition(scores, m - 1, axis=-1)[..., m - 1:m]
        mask = scores <= kth
    t = t_req + ((-t_req) % n_dev if n_dev > 1 else 0)
    if t > t_req:
        pad = t - t_req     # throwaway trees: re-fit copies of tree 0
        boot = np.concatenate([boot, np.repeat(boot[:1], pad, 0)])
        mask = np.concatenate([mask, np.repeat(mask[:1], pad, 0)])

    if n_dev > 1:
        fit = _sharded_fit_fn(mesh, c, depth, b, params.impurity)
        feat, thr, leaf = fit(
            jnp.asarray(xq), jnp.asarray(codes.astype(np.int32)),
            jnp.asarray(boot), jnp.asarray(mask))
    else:
        feat, thr, leaf = _fit_forest(
            jnp.asarray(xq), jnp.asarray(codes.astype(np.int32)),
            jnp.asarray(boot), jnp.asarray(mask), c, depth, b,
            params.impurity)
    # slice the padding back off: the trained model (and its votes) must
    # not depend on the mesh shape
    return ForestModel(
        classes=classes, thresholds=thresholds,
        feat=np.asarray(feat)[:t_req], thr=np.asarray(thr)[:t_req],
        leaf=np.asarray(leaf)[:t_req], max_depth=depth)
