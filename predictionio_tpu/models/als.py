"""Blockwise ALS matrix factorization on a device mesh.

The TPU-native replacement for MLlib ALS (`ALS.run`/`ALS.trainImplicit`
invoked by the reference templates at examples/scala-parallel-recommendation/
customize-serving/src/main/scala/ALSAlgorithm.scala:51-85 and
examples/scala-parallel-similarproduct/.../ALSAlgorithm.scala:60). Design
follows the ALX pattern (PAPERS.md): users and items are sharded in
contiguous blocks over the mesh's "data" axis; each half-sweep gathers the
opposite (replicated) factor matrix, assembles per-segment normal equations
with sorted segment-sums, and solves them as one batched Cholesky on the MXU.

Where Spark ALS shuffles rating blocks between executors every sweep, here
the COO ratings are resident on device (sorted twice: by user and by item)
and the only cross-device traffic is the factor all-gather XLA inserts when
the sharded sweep output feeds the next sweep's replicated input — exactly
the collective-over-ICI layout SURVEY.md section 2.9 P3 prescribes.

Explicit feedback uses ALS-WR weighted-lambda regularization (MLlib's
scheme); implicit feedback implements Hu-Koren-Volinsky confidence weighting
(c = 1 + alpha * r) with the shared V^T V Gramian trick.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.core.params import Params
from predictionio_tpu.data.bimap import vocab_index
from predictionio_tpu.ops.bucketing import bucket_size, pad_rows as _pad_rows
from predictionio_tpu.ops.fn_cache import shape_cached_fn
from predictionio_tpu.ops.linalg import batched_spd_solve
from predictionio_tpu.ops.segment import (
    block_gram_rhs, row_predict_add, rows_gram_rhs, segment_count,
)
from predictionio_tpu.ops.topk import host_topk as _host_topk

#: selectable training solvers: "full" = one K x K normal-equations solve
#: per row per half-sweep (the classic ALS step); "subspace" = iALS++
#: block coordinate descent over rank blocks (arXiv:2110.14044)
SOLVERS = ("full", "subspace")


@dataclasses.dataclass
class ALSParams(Params):
    """Hyperparameters (template ALSAlgorithmParams parity: rank,
    numIterations, lambda, seed; implicit adds alpha)."""

    rank: int = 10
    num_iterations: int = 10
    reg: float = 0.01
    alpha: float = 1.0
    implicit_prefs: bool = False
    weighted_reg: bool = True   # ALS-WR: lambda scaled by per-entity count
    seed: int = 3
    #: rows per lax.scan chunk — bounds the gather/matmul buffer (the padded
    #: row length itself is a data-layout knob on ALSData.build)
    chunk_size: int = 8192
    #: "full" (per-row K x K solve) or "subspace" (iALS++ block coordinate
    #: descent: per outer iteration sweep rank blocks of `block_size`,
    #: solving b x b systems against the frozen remainder — O(r * b^2)
    #: per row instead of O(r^3), the win compounding as rank grows)
    solver: str = "full"
    #: rank-block width of the subspace solver (ignored by "full")
    block_size: int = 16


def validate_solver(params: "ALSParams") -> None:
    """Loud failure on a typo'd solver config — a silent fallback would
    fake the full path's perf numbers under a subspace label (or vice
    versa)."""
    if params.solver not in SOLVERS:
        raise ValueError(
            f"unknown ALS solver {params.solver!r}: expected one of "
            f"{'|'.join(SOLVERS)}")
    if params.solver == "subspace" and params.block_size < 1:
        raise ValueError(
            f"block_size must be >= 1, got {params.block_size}")


def block_starts(rank: int, block_size: int) -> Tuple[int, ...]:
    """Static start offsets of the rank blocks one subspace sweep solves.

    Blocks are `block_size` wide; when rank is not divisible the LAST
    block is shifted left to end at `rank` (so it overlaps its
    predecessor instead of shrinking — every block keeps one static b x b
    shape, and re-solving the overlap columns is still exact coordinate
    descent). rank <= block_size degrades to one block == the full solve.
    """
    b = max(1, min(block_size, rank))
    return tuple(sorted({min(s, rank - b) for s in range(0, rank, b)}))


# ---------------------------------------------------------------------------
# Host-side data layout (ALX-style padded rows)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShardedRows:
    """Ratings packed into padded per-segment rows, split across shards.

    Row r holds up to L ratings of ONE segment (heavy segments span several
    consecutive rows); shard s owns contiguous segments
    [s * seg_per_shard, (s+1) * seg_per_shard). This layout turns Gramian
    assembly into batched [L, K] matmuls on the MXU with one small combine
    scatter per row — the ALX layout (PAPERS.md) — instead of per-rating
    scatter-adds.
    """

    tgt: np.ndarray   # int32 [D, R, L] — opposite-side factor rows
    val: np.ndarray   # float32 [D, R, L] — rating values
    w: np.ndarray     # float32 [D, R, L] — weights (0 = padding)
    seg: np.ndarray   # int32 [D, R] — LOCAL segment id of each row (sorted)
    seg_per_shard: int
    n_segments: int   # padded total (n_shards * seg_per_shard)
    row_len: int


def _auto_row_len(nnz: int, n_segments: int) -> int:
    mean = max(1.0, nnz / max(n_segments, 1))
    return int(min(512, max(16, 1 << int(np.ceil(np.log2(mean))))))


def _row_positions(seg_local: np.ndarray, row_len: int,
                   seg_per_shard: int):
    """Packing positions for sorted-by-segment ratings: (rrow, col,
    n_rows, row_seg), where element j lands at [rrow[j], col[j]] of an
    [n_rows, row_len] padded-row array. Shared by the training build and
    the eval sweep's auxiliary columns (fold ids packed into the SAME
    layout). n == 0 degrades to one all-padding row (rrow/col None)."""
    n = len(seg_local)
    if n == 0:
        return None, None, 1, np.full((1,), seg_per_shard - 1, np.int32)
    # the input is SORTED by segment (both callers sort first), so the
    # group structure falls out of one linear diff pass — np.unique would
    # re-sort 20M elements it already received in order
    new_seg = np.empty(n, bool)
    new_seg[0] = True
    np.not_equal(seg_local[1:], seg_local[:-1], out=new_seg[1:])
    first_idx = np.flatnonzero(new_seg)            # [U] group starts
    uniq = seg_local[first_idx]
    counts = np.diff(np.append(first_idx, n))
    rows_per = -(-counts // row_len)
    row_start = np.concatenate([[0], np.cumsum(rows_per)])
    inv = np.cumsum(new_seg) - 1                   # group id per element
    pos = np.arange(n) - first_idx[inv]
    rrow = row_start[inv] + pos // row_len
    col = pos % row_len
    n_rows = int(row_start[-1])
    row_seg = np.repeat(uniq, rows_per).astype(np.int32)
    return rrow, col, n_rows, row_seg


def _build_rows(seg_local: np.ndarray, tgt: np.ndarray, val: np.ndarray,
                weights: Optional[np.ndarray], row_len: int,
                seg_per_shard: int):
    """Pack one shard's (sorted-by-segment) ratings into padded rows."""
    rrow, col, n_rows, row_seg = _row_positions(seg_local, row_len,
                                                seg_per_shard)
    tgt_out = np.zeros((n_rows, row_len), np.int32)
    val_out = np.zeros((n_rows, row_len), np.float32)
    w_out = np.zeros((n_rows, row_len), np.float32)
    if rrow is not None:
        tgt_out[rrow, col] = tgt
        val_out[rrow, col] = val
        w_out[rrow, col] = weights if weights is not None else 1.0
    return tgt_out, val_out, w_out, row_seg


def _bucket_rows(r_max: int) -> int:
    """Bucket the padded row count so near-identical datasets (k-fold
    splits of one rating set differ by ~1/k rows) share ONE compiled
    program — without this an eval sweep pays folds x ranks separate XLA
    compiles, minutes on a TPU; padding rows carry w=0 and fold into the
    padding segment, so the math is unchanged. Single definition: the
    single-process and distributed builders MUST round identically or
    their programs stop sharing the jit cache."""
    return max(256, -(-r_max // 256) * 256)


def _stack_parts(per_shard, r_max: int, row_len: int, seg_per_shard: int):
    """Stack per-shard `_build_rows` outputs into the padded [S, R, L]
    (+[S, R] seg) arrays — shared by shard_rows and build_distributed."""
    n = len(per_shard)

    def _stack(idx, fill, dtype, shape_tail):
        out = np.full((n, r_max) + shape_tail, fill, dtype=dtype)
        for s, parts in enumerate(per_shard):
            a = parts[idx]
            out[s, :a.shape[0]] = a
        return out

    seg_out = np.full((n, r_max), seg_per_shard - 1, np.int32)
    for s, (_, _, _, rs) in enumerate(per_shard):
        seg_out[s, :rs.shape[0]] = rs
    return (_stack(0, 0, np.int32, (row_len,)),
            _stack(1, 0.0, np.float32, (row_len,)),
            _stack(2, 0.0, np.float32, (row_len,)),
            seg_out)


def shard_rows(seg_idx: np.ndarray, tgt_idx: np.ndarray, values: np.ndarray,
               n_segments: int, n_shards: int,
               weights: Optional[np.ndarray] = None,
               row_len: Optional[int] = None) -> ShardedRows:
    """Sort by segment, split at shard boundaries, pack into padded rows."""
    order = np.argsort(seg_idx, kind="stable")
    seg_s = seg_idx[order].astype(np.int64)
    tgt_s = tgt_idx[order].astype(np.int32)
    val_s = values[order].astype(np.float32)
    w_s = weights[order].astype(np.float32) if weights is not None else None
    nnz = len(seg_s)
    if row_len is None:
        row_len = _auto_row_len(nnz, n_segments)

    seg_per_shard = -(-max(n_segments, 1) // n_shards)
    bounds = np.searchsorted(
        seg_s, np.arange(1, n_shards) * seg_per_shard, side="left")
    starts = np.concatenate([[0], bounds, [nnz]]).astype(np.int64)

    per_shard = []
    for s in range(n_shards):
        lo, hi = int(starts[s]), int(starts[s + 1])
        per_shard.append(_build_rows(
            seg_s[lo:hi] - s * seg_per_shard, tgt_s[lo:hi], val_s[lo:hi],
            w_s[lo:hi] if w_s is not None else None, row_len, seg_per_shard))
    r_max = _bucket_rows(max(t.shape[0] for t, _, _, _ in per_shard))
    tgt, val, w, seg = _stack_parts(per_shard, r_max, row_len, seg_per_shard)
    return ShardedRows(
        tgt=tgt, val=val, w=w, seg=seg,
        seg_per_shard=seg_per_shard,
        n_segments=n_shards * seg_per_shard,
        row_len=row_len,
    )


@dataclasses.dataclass
class ALSData:
    """Device-ready training layout: padded rows sorted both ways + dims."""

    by_user: ShardedRows    # seg=user, tgt=item
    by_item: ShardedRows    # seg=item, tgt=user
    n_users: int
    n_items: int
    n_users_pad: int
    n_items_pad: int
    nnz: int
    #: digest of the PRE-shard COO triples — mesh-shape independent, so a
    #: checkpoint fingerprint built from it survives resuming on a
    #: different device count (the padded row layout does not)
    digest: str = ""

    @classmethod
    def build(cls, user_idx: np.ndarray, item_idx: np.ndarray,
              ratings: np.ndarray, n_users: int, n_items: int,
              n_shards: int, row_len: Optional[int] = None) -> "ALSData":
        by_user = shard_rows(user_idx, item_idx, ratings, n_users, n_shards,
                             row_len=row_len)
        by_item = shard_rows(item_idx, user_idx, ratings, n_items, n_shards,
                             row_len=row_len)
        return cls(by_user=by_user, by_item=by_item,
                   n_users=n_users, n_items=n_items,
                   n_users_pad=by_user.n_segments,
                   n_items_pad=by_item.n_segments,
                   nnz=int(len(ratings)),
                   digest=coo_digest(user_idx, item_idx, ratings))

    def put(self, mesh: Mesh) -> "ALSData":
        """Commit the row arrays to the mesh ONCE (sharded over "data",
        matching the half-sweep in_specs), so repeated `train_als` calls —
        warm-up, timed runs, eval sweeps over hyperparams — reuse resident
        device buffers instead of re-uploading the whole rating set per
        call. Over a tunneled TPU that upload is the dominant cost at
        ML-20M scale (~0.5 GB of padded rows).

        Multi-process (jax.distributed) runs assemble the global arrays
        from each process's local shard rows without gathering anywhere
        (SURVEY §2.9 P2 sharded input loading; the JdbcRDD-partition
        analog)."""
        multiproc = jax.process_count() > 1
        if multiproc:
            # the local-slice math below requires the standard layouts:
            # one shard row per mesh position, and each process's devices
            # occupying a CONTIGUOUS run of mesh.devices.flat (the order
            # jax.devices() yields on multi-host). Anything else would
            # silently mis-assemble training data — fail loudly instead.
            n_rows = self.by_user.tgt.shape[0]
            assert n_rows == mesh.devices.size, (
                f"data built for {n_rows} shards but mesh has "
                f"{mesh.devices.size} devices — build with "
                "n_shards=mesh.devices.size for multi-process put()")
            lo, hi = _process_shard_range(mesh)

        def commit_one(arr, sharding):
            if isinstance(arr, jax.Array):
                if arr.sharding == sharding:
                    return arr      # already resident HERE (idempotent)
                if multiproc:
                    raise ValueError(
                        "ALSData is resident on a different mesh; "
                        "re-putting across meshes is not supported in "
                        "multi-process runs")
                return jax.device_put(arr, sharding)   # reshard
            if not multiproc:
                return jax.device_put(arr, sharding)
            return jax.make_array_from_process_local_data(
                sharding, np.ascontiguousarray(arr[lo:hi]), arr.shape)

        def commit(rows: ShardedRows) -> ShardedRows:
            row_sh = NamedSharding(mesh, P("data", None, None))
            seg_sh = NamedSharding(mesh, P("data", None))
            return dataclasses.replace(
                rows,
                tgt=commit_one(rows.tgt, row_sh),
                val=commit_one(rows.val, row_sh),
                w=commit_one(rows.w, row_sh),
                seg=commit_one(rows.seg, seg_sh))

        out = dataclasses.replace(self, by_user=commit(self.by_user),
                                  by_item=commit(self.by_item))
        jax.block_until_ready([
            out.by_user.tgt, out.by_user.val, out.by_user.w, out.by_user.seg,
            out.by_item.tgt, out.by_item.val, out.by_item.w, out.by_item.seg])
        return out


# ---------------------------------------------------------------------------
# Device sweeps
# ---------------------------------------------------------------------------

def _half_sweep_dyn(opposite: jax.Array, row_tgt, row_seg, row_val, row_w,
                    seg_per_shard: int, *, reg, alpha,
                    implicit_prefs: bool, weighted_reg: bool,
                    alpha_is_zero: bool, chunk_rows: int) -> jax.Array:
    """Solve this side's factors for one shard. opposite is the full
    (replicated) opposite-side factor matrix; rows are the padded ALX
    layout. ``reg``/``alpha`` may be python floats OR traced scalars —
    the device-batched eval sweep vmaps this body over a candidate axis
    of (reg, alpha) values, so only the program-SHAPING flags
    (implicit_prefs / weighted_reg / alpha_is_zero) are static."""
    if implicit_prefs:
        # Hu-Koren-Volinsky: preference p = [r > 0], confidence
        # c = 1 + alpha * |r| (negative r = confident dislike, the
        # similarproduct LikeAlgorithm convention).
        # A_s = V^T V + sum (c-1) f f^T + lam I ; b_s = sum c p f
        # One row pass: gram weights (c-1); rhs values c*p/(c-1) so that
        # value * weight = c * p exactly. alpha == 0 degenerates to c = 1
        # (unweighted implicit), where the gram correction vanishes and the
        # rhs is a plain preference sum — use a direct pass for that case.
        gram_all = opposite.T @ opposite                 # [K, K] MXU
        p = jnp.where(row_val > 0, 1.0, 0.0)
        if alpha_is_zero:
            gram, rhs, cnt = rows_gram_rhs(
                opposite, row_tgt, row_seg, row_val=p, row_w=row_w,
                num_segments=seg_per_shard, chunk_rows=chunk_rows)
            gram = jnp.zeros_like(gram)  # (c-1) = 0; keep only the rhs
        else:
            cm1 = alpha * jnp.abs(row_val)               # c - 1
            vals = jnp.where(cm1 > 0,
                             (1.0 + cm1) * p / jnp.maximum(cm1, 1e-12), 0.0)
            gram, rhs, _ = rows_gram_rhs(
                opposite, row_tgt, row_seg,
                row_val=vals, row_w=row_w * cm1,
                num_segments=seg_per_shard, chunk_rows=chunk_rows)
            cnt = segment_count(row_seg, row_w.sum(axis=1), seg_per_shard)
        A = gram_all[None, :, :] + gram
        lam = reg * jnp.where(weighted_reg, jnp.maximum(cnt, 1.0), 1.0)
        A = A + lam[:, None, None] * jnp.eye(opposite.shape[1], dtype=A.dtype)
        return batched_spd_solve(A, rhs)
    gram, rhs, cnt = rows_gram_rhs(
        opposite, row_tgt, row_seg, row_val=row_val, row_w=row_w,
        num_segments=seg_per_shard, chunk_rows=chunk_rows)
    lam = reg * jnp.where(weighted_reg, jnp.maximum(cnt, 1.0), 1.0)
    A = gram + lam[:, None, None] * jnp.eye(opposite.shape[1], dtype=gram.dtype)
    return batched_spd_solve(A, rhs)


def _half_sweep(opposite: jax.Array, row_tgt, row_seg, row_val, row_w,
                seg_per_shard: int, params: ALSParams,
                chunk_rows: int) -> jax.Array:
    """Static-params wrapper over `_half_sweep_dyn` (the training path)."""
    return _half_sweep_dyn(
        opposite, row_tgt, row_seg, row_val, row_w, seg_per_shard,
        reg=params.reg, alpha=params.alpha,
        implicit_prefs=params.implicit_prefs,
        weighted_reg=params.weighted_reg,
        alpha_is_zero=(params.alpha == 0), chunk_rows=chunk_rows)


def _global_gram(opposite: jax.Array, axis: Optional[str],
                 n_shards: int) -> jax.Array:
    """The K x K Gramian of the full opposite factor matrix, computed ONCE
    per half-sweep (the implicit solver's V^T V term). On a mesh the
    contraction is SHARDED: each device reduces its slice of the
    (replicated) rows and one psum of the tiny [K, K] result combines —
    the ALX sharded-Gramian layout (arXiv:2112.02194)."""
    if axis is None or n_shards <= 1:
        return opposite.T @ opposite
    f, k = opposite.shape
    per = -(-f // n_shards)
    op = jnp.pad(opposite, ((0, per * n_shards - f), (0, 0)))
    i = jax.lax.axis_index(axis)
    sl = jax.lax.dynamic_slice(op, (i * per, 0), (per, k))
    return jax.lax.psum(sl.T @ sl, axis)


def _half_sweep_subspace_dyn(x_prev: jax.Array, opposite: jax.Array,
                             row_tgt, row_seg, row_val, row_w,
                             seg_per_shard: int, *, reg, alpha,
                             implicit_prefs: bool, weighted_reg: bool,
                             alpha_is_zero: bool, chunk_rows: int,
                             block_size: int, axis: Optional[str] = None,
                             mesh_shards: int = 1) -> jax.Array:
    """Block coordinate descent half-sweep (iALS++, arXiv:2110.14044).

    Instead of one K x K normal-equations solve per row, sweep rank
    blocks of width b: for each block, solve every row's b x b system
    against the frozen remainder of its own factors (``x_prev``, updated
    block by block), with the per-rating predictions maintained
    incrementally. Per-half-sweep cost drops from
    ``nnz*K^2 + S*K^3`` to ``nnz*K*b + S*K*b^2`` — and the batched
    Cholesky shrinks from [S, K, K] (whose K-step recurrence rewrites
    the whole buffer every step, the HBM-bandwidth wall at K >= 64) to
    [S, b, b].

    Cached once per half-sweep and reused by every block solve: the
    per-segment weight counts (the ALS-WR lambda scaling) and, for
    implicit feedback, the global Gramian V^T V (sharded over the mesh
    via `_global_gram`) whose b-column slices feed each block. ``reg`` /
    ``alpha`` may be traced (the eval sweep vmaps them); only
    block_size and the mode flags shape the program.
    """
    k = opposite.shape[1]
    b = max(1, min(block_size, k))
    starts = block_starts(k, block_size)
    # block buffers are [C, L, b] vs the full path's [C, L, K]: larger
    # chunks for the same memory budget -> fewer scan steps
    chunk_b = chunk_rows * max(1, k // b)

    # ---- per-half-sweep cache: built once, reused by every block solve
    cnt = segment_count(row_seg, row_w.sum(axis=1), seg_per_shard)
    lam = reg * jnp.where(weighted_reg, jnp.maximum(cnt, 1.0), 1.0)
    if implicit_prefs:
        gram_all = _global_gram(opposite, axis, mesh_shards)   # [K, K]
        p = jnp.where(row_val > 0, 1.0, 0.0)
        if alpha_is_zero:
            # c = 1 everywhere: the per-rating Gramian term vanishes
            gram_w = jnp.zeros_like(row_w)
            rhs_val = row_w * p
        else:
            cm1 = alpha * jnp.abs(row_val)                     # c - 1
            gram_w = row_w * cm1
            rhs_val = row_w * (1.0 + cm1) * p
    else:
        gram_all = None
        gram_w = row_w
        rhs_val = row_w * row_val

    pred = row_predict_add(
        opposite, x_prev, row_tgt, row_seg,
        jnp.zeros_like(row_val), chunk_rows=chunk_rows)
    eye_b = jnp.eye(b, dtype=opposite.dtype)

    x = x_prev
    for j, s in enumerate(starts):
        f_b = jax.lax.slice_in_dim(opposite, s, s + b, axis=1)
        x_b = jax.lax.slice_in_dim(x, s, s + b, axis=1)
        gram, rhs = block_gram_rhs(
            f_b, x_b, row_tgt, row_seg, pred, rhs_val, gram_w,
            num_segments=seg_per_shard, chunk_rows=chunk_b)
        if implicit_prefs:
            # dense all-items term from the CACHED global Gramian:
            # A += G[B,B]; rhs -= (x G)[:,B] - x_B G[B,B]
            g_col = jax.lax.slice_in_dim(gram_all, s, s + b, axis=1)
            g_bb = jax.lax.slice_in_dim(g_col, s, s + b, axis=0)
            gram = gram + g_bb[None, :, :]
            rhs = rhs - (x @ g_col - x_b @ g_bb)
        A = gram + lam[:, None, None] * eye_b
        y = batched_spd_solve(A, rhs)
        if j + 1 < len(starts):
            # fold this block's delta into the running predictions (the
            # LAST block's update feeds nothing, so skip its pass)
            pred = row_predict_add(f_b, y - x_b, row_tgt, row_seg, pred,
                                   chunk_rows=chunk_b)
        x = jax.lax.dynamic_update_slice_in_dim(x, y, s, axis=1)
    return x


def _make_sweeps(mesh: Mesh, data_dims, params: ALSParams):
    """Build the shard_map'd user/item half-sweeps for the given mesh.

    The full solver's sweeps take (opposite, rows...); the subspace
    solver's additionally take this side's PREVIOUS factors — sharded
    like the output, since block coordinate descent updates rank blocks
    of each shard's own rows against the frozen remainder."""
    from predictionio_tpu.parallel.compat import shard_map

    validate_solver(params)
    n_users_pad, n_items_pad, ups, ips = data_dims[:4]
    axis = "data"
    chunk = params.chunk_size
    n_shards = int(mesh.devices.size)

    # check_vma=False: the generic row kernel mixes replicated factor
    # inputs with device-varying row chunks inside lax.scan; correctness is
    # covered by the single-vs-8-device equivalence test
    row_spec = P(axis, None, None)
    seg_spec = P(axis, None)

    if params.solver == "subspace":
        def sub_kwargs():
            return dict(
                reg=params.reg, alpha=params.alpha,
                implicit_prefs=params.implicit_prefs,
                weighted_reg=params.weighted_reg,
                alpha_is_zero=(params.alpha == 0), chunk_rows=chunk,
                block_size=params.block_size, axis=axis,
                mesh_shards=n_shards)

        def user_block(Up, V, tgt, seg, val, w):
            return _half_sweep_subspace_dyn(
                Up[0], V, tgt[0], seg[0], val[0], w[0], ups,
                **sub_kwargs())[None]

        def item_block(Vp, U, tgt, seg, val, w):
            return _half_sweep_subspace_dyn(
                Vp[0], U, tgt[0], seg[0], val[0], w[0], ips,
                **sub_kwargs())[None]

        specs = (P(axis, None, None), P(), row_spec, seg_spec,
                 row_spec, row_spec)
        user_sweep = shard_map(
            user_block, mesh=mesh, in_specs=specs,
            out_specs=P(axis, None, None), check_vma=False)
        item_sweep = shard_map(
            item_block, mesh=mesh, in_specs=specs,
            out_specs=P(axis, None, None), check_vma=False)
        return user_sweep, item_sweep

    def user_block(V, tgt, seg, val, w):
        # one shard: [1, R, L] row blocks -> local users [ups, K]
        return _half_sweep(V, tgt[0], seg[0], val[0], w[0], ups, params, chunk)[None]

    def item_block(U, tgt, seg, val, w):
        return _half_sweep(U, tgt[0], seg[0], val[0], w[0], ips, params, chunk)[None]

    user_sweep = shard_map(
        user_block, mesh=mesh,
        in_specs=(P(), row_spec, seg_spec, row_spec, row_spec),
        out_specs=P(axis, None, None), check_vma=False)
    item_sweep = shard_map(
        item_block, mesh=mesh,
        in_specs=(P(), row_spec, seg_spec, row_spec, row_spec),
        out_specs=P(axis, None, None), check_vma=False)
    return user_sweep, item_sweep


def _make_chunk_core(mesh: Mesh, data_dims, params: ALSParams, iters: int):
    """Shared iteration body: (by_user, by_item, V) -> (U, V) after `iters`
    alternating sweeps. Both the straight and the checkpointed paths run
    exactly this, so they cannot drift apart."""
    n_users_pad, n_items_pad, ups, ips = data_dims[:4]
    k = params.rank
    n_shards = n_users_pad // ups
    user_sweep, item_sweep = _make_sweeps(mesh, data_dims, params)
    subspace = params.solver == "subspace"

    def chunk(by_user, by_item, U, V):
        # U rides the chunk boundary: the full solver's first user sweep
        # overwrites it (so a zero U is merely conventional there), but
        # the subspace solver REFINES it — dropping it between
        # checkpointing chunks would cold-restart block descent per chunk
        # and make results depend on checkpointer.interval
        u_tgt, u_seg, u_val, u_w = by_user
        i_tgt, i_seg, i_val, i_w = by_item

        def body(_, carry):
            U, V = carry
            if subspace:
                # block coordinate descent refines each side's factors in
                # place: the previous values flow in sharded alongside
                # the (replicated) opposite side
                U = user_sweep(U.reshape(n_shards, ups, k), V,
                               u_tgt, u_seg, u_val, u_w
                               ).reshape(n_users_pad, k)
                V = item_sweep(V.reshape(n_shards, ips, k), U,
                               i_tgt, i_seg, i_val, i_w
                               ).reshape(n_items_pad, k)
            else:
                U = user_sweep(V, u_tgt, u_seg, u_val, u_w
                               ).reshape(n_users_pad, k)
                V = item_sweep(U, i_tgt, i_seg, i_val, i_w
                               ).reshape(n_items_pad, k)
            return (U, V)

        return jax.lax.fori_loop(0, iters, body, (U, V))

    return chunk


def make_train_fn(mesh: Mesh, data_dims, params: ALSParams):
    """Build the jitted full training function for the given mesh.

    Returns train(by_user_arrays, by_item_arrays, key) -> (U, V), where the
    per-shard COO arrays are sharded over the mesh's "data" axis and the
    factor matrices flow replicated-in / sharded-out; XLA inserts the
    all-gather between half-sweeps (collectives over ICI).
    """
    n_users_pad, n_items_pad, _, _, n_items = data_dims
    k = params.rank
    chunk = _make_chunk_core(mesh, data_dims, params, params.num_iterations)

    def train(by_user, by_item, key):
        V = (jax.random.normal(key, (n_items_pad, k), jnp.float32)
             / jnp.sqrt(jnp.asarray(k, jnp.float32)))
        # padding item rows start (and stay) zero: random pad rows would
        # pollute the implicit solvers' global V^T V Gramian — the full
        # sweep zeroes them exactly on its first item solve, but block
        # coordinate descent only decays them, and snapshot/resume
        # truncates at n_items, so nonzero pads would make a resumed run
        # diverge from the uninterrupted one
        V = jnp.where((jnp.arange(n_items_pad) < n_items)[:, None], V, 0.0)
        U0 = jnp.zeros((n_users_pad, k), jnp.float32)
        return chunk(by_user, by_item, U0, V)

    return jax.jit(train)


def make_chunk_fn(mesh: Mesh, data_dims, params: ALSParams, iters: int):
    """Like make_train_fn but runs `iters` iterations from a given
    (U, V) — the unit of mid-training checkpointing (train_als drives
    the outer loop, snapshotting between chunks; U matters to the
    subspace solver, which refines it, and is inert to the full solver,
    whose first sweep overwrites it)."""
    return jax.jit(_make_chunk_core(mesh, data_dims, params, iters))


#: compile-ledger family of the training path: one entry per distinct
#: (mesh, data dims, hyperparams, chunking) program — for the subspace
#: solver that means one per (rank, block_size) family on fixed data, the
#: bound the solver tests assert via `fn_cache.family_keys`
TRAIN_FAMILY = "als_train"


def _cached_train_fn(mesh: Mesh, data_dims, params: ALSParams,
                     chunk_iters: Optional[int] = None):
    """Memoized jitted train fns — rebuilding the closures on every call
    would force a re-trace per training run (FastEvalEngine's
    compilation-cache analog; the key is everything that shapes the
    compiled program). Registered in the shared `ops/fn_cache` ledger so
    training compiles surface as ``pio_jax_compile_total{family=
    als_train}``, with the same bounded-LRU protection for long-running
    servers retraining on growing data. Returns (fn, fresh) — fresh
    meaning this fetch BUILT the fn, so its first dispatch will
    trace+compile."""
    from predictionio_tpu.ops.fn_cache import family_keys, mesh_cached_fn

    def build():
        if chunk_iters is None:
            return make_train_fn(mesh, data_dims, params)
        return make_chunk_fn(mesh, data_dims, params, chunk_iters)

    # block_size only shapes SUBSPACE programs; normalizing it to 0 for
    # "full" keeps full-solver trains that merely carry different resolved
    # block sizes (e.g. a PIO_ALS_BLOCK_SIZE override on a full box) on
    # ONE compiled program and ONE ledger entry — mirroring the eval
    # sweep's group_candidates
    key_params = (dataclasses.replace(params, block_size=0)
                  if params.solver == "full" else params)
    key = (data_dims, dataclasses.astuple(key_params), chunk_iters)
    # a fn fetched fresh has never been dispatched: its first call pays
    # trace+compile, which the half-sweep timing metric must not count
    fresh = (mesh, key) not in family_keys(TRAIN_FAMILY)
    return mesh_cached_fn(TRAIN_FAMILY, mesh, key, build), fresh


def _process_shard_range(mesh: Mesh) -> Tuple[int, int]:
    """This process's contiguous run [lo, hi) of mesh shard rows (one row
    per device along the flattened mesh). Asserts the layout every
    multi-process path requires: process-contiguous device order."""
    import jax

    me = jax.process_index()
    rows_mine = [i for i, d in enumerate(mesh.devices.flat)
                 if d.process_index == me]
    lo, hi = min(rows_mine), max(rows_mine) + 1
    assert len(rows_mine) == hi - lo, (
        "mesh interleaves processes along the shard axis "
        f"(process {me} owns rows {rows_mine}); multi-process data "
        "layouts require process-contiguous device order")
    return lo, hi


def build_distributed(mesh: Mesh, user_idx: np.ndarray,
                      item_idx: np.ndarray, ratings: np.ndarray,
                      n_users: int, n_items: int,
                      row_len: Optional[int] = None) -> ALSData:
    """Assemble mesh-committed ALSData from PER-PROCESS event shards.

    The full partitioned input pipeline (SURVEY §2.9 P2 + P4): each
    process passes only the ratings its own storage shard produced
    (`find_columnar(shard=(p, P))`, the JDBCPEvents.scala:89-101
    partition-read analog), rows are re-keyed to their segment owners by
    ONE `lax.all_to_all` per side (parallel/shuffle.py — the Spark
    shuffle as an XLA collective), and each process packs + commits only
    its own padded row blocks. No process ever materializes the global
    rating set; peak host memory is the local shard + its exchange bins.

    Single-process meshes degrade to `ALSData.build(...).put(mesh)`.
    """
    import jax

    from predictionio_tpu.parallel.shuffle import allgather_object, \
        exchange_rows

    user_idx = np.ascontiguousarray(user_idx, np.int32)
    item_idx = np.ascontiguousarray(item_idx, np.int32)
    ratings = np.ascontiguousarray(ratings, np.float32)
    n_shards = int(mesh.devices.size)
    if jax.process_count() == 1:
        return ALSData.build(user_idx, item_idx, ratings, n_users,
                             n_items, n_shards, row_len=row_len).put(mesh)

    lo, hi = _process_shard_range(mesh)
    shards_per_proc = hi - lo
    # global sizes ride one tiny metadata all-gather
    meta = allgather_object({
        "nnz": int(len(ratings)),
        "hash": _coo_hash_commutative(user_idx, item_idx, ratings)})
    nnz = sum(m["nnz"] for m in meta)
    digest = _combine_coo_hashes(meta, nnz)
    if row_len is None:
        row_len = _auto_row_len(nnz, max(n_users, n_items))

    payload = np.stack([user_idx, item_idx,
                        ratings.view(np.int32)], axis=1)

    # each shard row's owner read off the mesh itself — never inferred
    # from arithmetic, which would silently drop rows on meshes with
    # uneven devices-per-process or non-ascending process order
    proc_of_shard = np.asarray(
        [d.process_index for d in mesh.devices.flat], np.int32)

    def one_side(n_segments: int, seg_col: int, tgt_col: int):
        seg_per_shard = -(-max(n_segments, 1) // n_shards)
        shard_of = np.minimum(payload[:, seg_col] // seg_per_shard,
                              n_shards - 1)
        mine = exchange_rows(proc_of_shard[shard_of], payload)
        seg = mine[:, seg_col]
        assert seg.size == 0 or (
            seg.min() >= lo * seg_per_shard
            and seg.max() < hi * seg_per_shard), (
            "exchange delivered segments outside this process's shard "
            "range — shard ownership mapping is inconsistent")
        order = np.argsort(seg, kind="stable")
        seg_s = seg[order].astype(np.int64)
        tgt_s = mine[order, tgt_col]
        val_s = mine[order, 2].view(np.float32)
        # pack each OWNED shard's rows (the local slice of shard_rows,
        # with the row-count bucketing agreed globally via all-gather)
        bounds = np.searchsorted(
            seg_s, (lo + np.arange(shards_per_proc + 1)) * seg_per_shard)
        parts = []
        for j in range(shards_per_proc):
            a, b = int(bounds[j]), int(bounds[j + 1])
            parts.append(_build_rows(
                seg_s[a:b] - (lo + j) * seg_per_shard, tgt_s[a:b],
                val_s[a:b], None, row_len, seg_per_shard))
        r_local = max(t.shape[0] for t, _, _, _ in parts)
        r_max = _bucket_rows(max(allgather_object(r_local)))
        tgt, val, w, seg = _stack_parts(parts, r_max, row_len,
                                        seg_per_shard)

        def commit(local, tail):
            # specs spelled exactly as ALSData.put writes them, so put()'s
            # idempotence check recognizes these arrays as already resident
            spec = P("data", None, None) if tail else P("data", None)
            return jax.make_array_from_process_local_data(
                NamedSharding(mesh, spec), np.ascontiguousarray(local),
                (n_shards, r_max) + tail)

        return ShardedRows(
            tgt=commit(tgt, (row_len,)),
            val=commit(val, (row_len,)),
            w=commit(w, (row_len,)),
            seg=commit(seg, ()),
            seg_per_shard=seg_per_shard,
            n_segments=n_shards * seg_per_shard,
            row_len=row_len)

    by_user = one_side(n_users, 0, 1)
    by_item = one_side(n_items, 1, 0)
    out = ALSData(by_user=by_user, by_item=by_item,
                  n_users=n_users, n_items=n_items,
                  n_users_pad=by_user.n_segments,
                  n_items_pad=by_item.n_segments,
                  nnz=nnz, digest=digest)
    jax.block_until_ready([
        out.by_user.tgt, out.by_user.val, out.by_user.w, out.by_user.seg,
        out.by_item.tgt, out.by_item.val, out.by_item.w, out.by_item.seg])
    return out


def _coo_hash_commutative(user_idx, item_idx, ratings) -> int:
    """Per-process contribution to an order- AND partition-independent
    dataset hash: a commutative sum of per-row mixes (splitmix64-style),
    so the combined digest is identical however rows are spread across
    processes. Weaker than blake2b over sorted rows but still sensitive
    to any single changed rating — enough for checkpoint fingerprints."""
    with np.errstate(over="ignore"):
        h = (user_idx.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
             ^ item_idx.astype(np.uint64) * np.uint64(0xC2B2AE3D27D4EB4F)
             ^ ratings.view(np.uint32).astype(np.uint64)
             * np.uint64(0x165667B19E3779F9))
        h ^= h >> np.uint64(31)
        h *= np.uint64(0xFF51AFD7ED558CCD)
        h ^= h >> np.uint64(29)
        return int(h.sum(dtype=np.uint64))


def _combine_coo_hashes(meta, nnz: int) -> str:
    total = np.uint64(0)
    with np.errstate(over="ignore"):
        for m in meta:
            total += np.uint64(m["hash"])
    return f"coo-{nnz}-{int(total):016x}"


def coo_digest(user_idx: np.ndarray, item_idx: np.ndarray,
               ratings: np.ndarray) -> str:
    """Identity hash of the FULL rating set (canonical dtypes, so int32
    vs int64 inputs digest identically). Full, not sampled: a checkpoint
    resumed against data where even one rating changed must retrain.

    The hash is a commutative sum of per-row mixes, so it is independent
    of row ORDER and of how rows are PARTITIONED across processes —
    single-process `ALSData.build` and multi-host `build_distributed`
    digest the same data identically, which the als_fingerprint
    mesh-shape-independence contract requires."""
    u = np.ascontiguousarray(np.asarray(user_idx).reshape(-1), np.int64)
    i = np.ascontiguousarray(np.asarray(item_idx).reshape(-1), np.int64)
    r = np.ascontiguousarray(np.asarray(ratings).reshape(-1), np.float32)
    return f"coo-{len(r)}-{_coo_hash_commutative(u, i, r):016x}"


def als_fingerprint(data: ALSData, params: ALSParams) -> str:
    """Identity of a training run for checkpoint-resume safety: math-shaping
    hyperparams (num_iterations/chunk_size excluded — more iterations on the
    same run IS the resume use case; solver/block_size excluded too — both
    solvers minimize the same objective and V is the complete state, so a
    snapshot survives switching solvers mid-run) + dataset stats + the
    mesh-independent
    COO digest (NOT the padded row layout, which varies with shard count —
    snapshots must survive resuming on a different mesh shape). A crashed
    run restarted with different reg/seed/alpha/implicit_prefs, or against
    different ratings of the same shape, retrains from scratch."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    h.update(repr((params.rank, params.reg, params.alpha,
                   params.implicit_prefs, params.weighted_reg,
                   params.seed)).encode())
    h.update(np.asarray([data.nnz, data.n_users, data.n_items],
                        np.int64).tobytes())
    h.update(data.digest.encode())
    return h.hexdigest()


def train_als(mesh: Mesh, data: ALSData, params: ALSParams,
              checkpointer=None) -> Tuple[np.ndarray, np.ndarray]:
    """Train and return host (U [n_users, K], V [n_items, K]).

    With a `workflow.checkpoint.Checkpointer`, iterations run in chunks of
    `checkpointer.interval`, snapshotting the item factors between chunks
    (the ALS state is fully determined by V — each sweep recomputes U from
    it); a crashed/preempted run resumes from the latest snapshot, even on
    a different mesh shape (snapshots hold unpadded host arrays)."""
    import time

    from predictionio_tpu.obs.tracing import span
    from predictionio_tpu.obs.train_stats import (
        als_block_sweeps, als_gramian_cache_hits, als_half_sweep_seconds,
    )

    validate_solver(params)
    n_shards = int(np.prod(mesh.devices.shape))
    assert data.by_user.tgt.shape[0] == n_shards, \
        f"data built for {data.by_user.tgt.shape[0]} shards, mesh has {n_shards}"
    # commit the rows to the mesh (idempotent): every caller then feeds
    # identically-sharded resident arrays, so one (params, dims) pair
    # compiles exactly once per process regardless of entry path, and
    # repeated calls never re-upload
    data = data.put(mesh)
    multihost = jax.process_count() > 1

    def gather_host(arr, n_rows):
        """Full host copy of a (possibly cross-host-sharded) factor
        matrix — every host needs it for serving/persistence."""
        if multihost:
            from jax.experimental.multihost_utils import process_allgather

            return np.asarray(process_allgather(arr, tiled=True))[:n_rows]
        return np.asarray(jax.device_get(arr))[:n_rows]

    dims = (data.n_users_pad, data.n_items_pad,
            data.by_user.seg_per_shard, data.by_item.seg_per_shard,
            data.n_items)
    key = jax.random.PRNGKey(params.seed)
    bu = (data.by_user.tgt, data.by_user.seg, data.by_user.val, data.by_user.w)
    bi = (data.by_item.tgt, data.by_item.seg, data.by_item.val, data.by_item.w)

    solve_s = 0.0    # device-dispatch wall only, excluding snapshot I/O
    compiled = False  # any timed dispatch paid trace+compile
    iters_run = params.num_iterations
    if checkpointer is None:
        train, fresh = _cached_train_fn(mesh, dims, params)
        compiled |= fresh
        with span("als_solve"):
            t0 = time.perf_counter()
            U, V = train(bu, bi, key)
            jax.block_until_ready(V)
            solve_s += time.perf_counter() - t0
    else:
        k = params.rank
        fp = als_fingerprint(data, params)
        snap = checkpointer.latest(fingerprint=fp)
        it = 0
        V = None
        U = None     # subspace snapshots carry U too (BCD state is (U, V))
        if multihost:
            # the resume decision must be IDENTICAL on every host or the
            # SPMD programs diverge (some resuming, some from scratch);
            # process 0's snapshot is authoritative — snapshot dirs are
            # per-host paths, not guaranteed shared
            from jax.experimental.multihost_utils import (
                broadcast_one_to_all)

            ok = snap is not None and snap[1].get("V") is not None \
                and snap[1]["V"].shape == (data.n_items, k) \
                and snap[0] < params.num_iterations
            # only subspace snapshots carry U; gating on the solver (a
            # host-uniform static) avoids allocating + broadcasting an
            # n_users x k zero buffer on every full-solver train start
            want_u = params.solver == "subspace"
            has_u = want_u and ok and snap[1].get("U") is not None \
                and snap[1]["U"].shape == (data.n_users, k)
            meta = np.zeros(3, np.int64)
            v_buf = np.zeros((data.n_items, k), np.float32)
            u_buf = (np.zeros((data.n_users, k), np.float32) if want_u
                     else np.zeros((0, k), np.float32))
            if jax.process_index() == 0 and ok:
                meta[:] = (1, snap[0], int(has_u))
                v_buf[:] = np.asarray(snap[1]["V"], np.float32)
                if has_u:
                    u_buf[:] = np.asarray(snap[1]["U"], np.float32)
            meta, v_buf, u_buf = broadcast_one_to_all((meta, v_buf, u_buf))
            if int(meta[0]):
                it = int(meta[1])
                V = jnp.zeros((data.n_items_pad, k), jnp.float32)
                V = V.at[:data.n_items].set(jnp.asarray(v_buf))
                if int(meta[2]):
                    U = jnp.zeros((data.n_users_pad, k), jnp.float32)
                    U = U.at[:data.n_users].set(jnp.asarray(u_buf))
        elif snap is not None and snap[1].get("V") is not None \
                and snap[1]["V"].shape == (data.n_items, k) \
                and snap[0] < params.num_iterations:
            # a snapshot at/past the target (stale run with fewer iters)
            # would skip the loop and leave U zeroed — retrain instead
            it, state = snap
            V = jnp.zeros((data.n_items_pad, k), jnp.float32)
            V = V.at[:data.n_items].set(jnp.asarray(state["V"]))
            if state.get("U") is not None \
                    and state["U"].shape == (data.n_users, k):
                U = jnp.zeros((data.n_users_pad, k), jnp.float32)
                U = U.at[:data.n_users].set(jnp.asarray(state["U"]))
        if V is None:
            V = (jax.random.normal(key, (data.n_items_pad, k), jnp.float32)
                 / jnp.sqrt(jnp.asarray(k, jnp.float32)))
            # same pad-row zeroing as make_train_fn's init: the chunked
            # run must start from the identical state
            V = jnp.where((jnp.arange(data.n_items_pad)
                           < data.n_items)[:, None], V, 0.0)
        if U is None:
            U = jnp.zeros((data.n_users_pad, k), jnp.float32)
        iters_run = params.num_iterations - it
        # the full solver's state is V alone (each sweep recomputes U
        # exactly); block coordinate descent refines BOTH sides, so its
        # snapshots carry U too — resume stays bit-equivalent to the
        # uninterrupted run
        snap_u = params.solver == "subspace"
        while it < params.num_iterations:
            n = min(checkpointer.interval, params.num_iterations - it)
            chunk, fresh = _cached_train_fn(mesh, dims, params,
                                            chunk_iters=n)
            compiled |= fresh
            with span("als_solve"):
                t0 = time.perf_counter()
                U, V = chunk(bu, bi, U, V)
                jax.block_until_ready(V)
                solve_s += time.perf_counter() - t0
            it += n
            if it < params.num_iterations:
                if multihost:
                    # V is sharded across hosts: snapshot the gathered
                    # copy, and only process 0 writes (every process
                    # writing the same file would race)
                    state = {"V": gather_host(V, data.n_items)}
                    if snap_u:
                        state["U"] = gather_host(U, data.n_users)
                    if jax.process_index() == 0:
                        checkpointer.save(it, state, fingerprint=fp)
                else:
                    state = {"V": V[:data.n_items]}
                    if snap_u:
                        state["U"] = U[:data.n_users]
                    checkpointer.save(it, state, fingerprint=fp)

    # half-sweep accounting (host-side: the sweeps run fused inside one
    # device loop, so per-sweep numbers are derived, not sampled; only
    # solve-dispatch wall counts — snapshot gathers/writes between chunks
    # must not inflate the kernel's timing, and a cold dispatch's
    # trace+compile would drown the per-solver comparison the histogram
    # exists for, so compiling runs observe nothing)
    half_sweeps = max(1, 2 * iters_run)
    if not compiled:
        als_half_sweep_seconds().observe(
            solve_s / half_sweeps, solver=params.solver)
    if params.solver == "subspace":
        n_blocks = len(block_starts(params.rank, params.block_size))
        als_block_sweeps().inc(half_sweeps * n_blocks)
        # the per-half-sweep Gramian/count cache serves every block solve
        # after the first without a rebuild
        als_gramian_cache_hits().inc(half_sweeps * max(0, n_blocks - 1))
    return gather_host(U, data.n_users), gather_host(V, data.n_items)


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames="num")
def _topk_scores_batch(user_vecs: jax.Array, V: jax.Array, mask: jax.Array,
                       num: int) -> Tuple[jax.Array, jax.Array]:
    scores = user_vecs @ V.T                    # [B, n_items] MXU matmul
    scores = jnp.where(mask, -jnp.inf, scores)
    return jax.lax.top_k(scores, num)


@functools.partial(jax.jit, static_argnames="num")
def _topk_scores_batch_nomask(user_vecs: jax.Array, V: jax.Array,
                              num: int) -> Tuple[jax.Array, jax.Array]:
    """No-exclusion fast path: skips the [B, n_items] mask build AND its
    host->device transfer — on a tunneled TPU each transfer is a network
    round-trip, and plain `{"user": ..., "num": N}` queries (the reference
    quickstart shape, tests/pio_tests/scenarios/quickstart_test.py:86) never
    carry black/white lists."""
    return jax.lax.top_k(user_vecs @ V.T, num)


#: measured seconds for one tiny jitted dispatch + fetch on the default
#: backend — the fixed per-request cost of touching the device at all.
#: Over the axon tunnel this is tens of milliseconds (every dispatch is a
#: network round-trip); on a local chip ~100us; on CPU ~20us. Serving
#: compares it against the host-BLAS cost of the same scoring matmul and
#: sends the batch wherever it finishes sooner (dispatch-latency-aware
#: serving — the design answer to BENCH_r03's 137ms query p50, where the
#: reference's in-heap serial loop CreateServer.scala:508-510 pays zero
#: dispatch cost). Re-probed when the scorer MODE changes: a stale
#: measurement taken under a different kernel regime would mis-route
#: batches for the rest of the process. Tests/benches that FORCE the
#: device lane assign ``_DEVICE_ROUNDTRIP_S = 0.0`` directly (leaving
#: the mode marker alone), which pins the value across modes.
_DEVICE_ROUNDTRIP_S: Optional[float] = None
_DEVICE_ROUNDTRIP_MODE: Optional[str] = None


def device_roundtrip_s() -> float:
    global _DEVICE_ROUNDTRIP_S, _DEVICE_ROUNDTRIP_MODE
    from predictionio_tpu.ops.scoring import process_scorer_config

    mode = process_scorer_config().mode
    if _DEVICE_ROUNDTRIP_S is None or (
            _DEVICE_ROUNDTRIP_MODE is not None
            and _DEVICE_ROUNDTRIP_MODE != mode):
        import time

        # pio: ignore[PIO001]: one-shot roundtrip probe; result memoized in _DEVICE_ROUNDTRIP_S
        probe = jax.jit(lambda a: jax.lax.top_k(a @ a.T, 4))
        x = np.ones((8, 8), np.float32)
        jax.block_until_ready(probe(x))          # compile outside the clock
        t0 = time.perf_counter()
        for _ in range(3):
            jax.device_get(probe(x))
        _DEVICE_ROUNDTRIP_S = (time.perf_counter() - t0) / 3
        _DEVICE_ROUNDTRIP_MODE = mode
    return _DEVICE_ROUNDTRIP_S


#: rough host matmul+argpartition throughput (flop/s) for the crossover
#: estimate; measured lazily the first time a model serves from host.
_HOST_FLOPS: Optional[float] = None


def _host_flops() -> float:
    global _HOST_FLOPS
    if _HOST_FLOPS is None:
        import time

        u = np.ones((16, 32), np.float32)
        v = np.ones((2048, 32), np.float32)
        _host_topk(u @ v.T, 10)                  # warm the BLAS path
        t0 = time.perf_counter()
        _host_topk(u @ v.T, 10)
        dt = max(time.perf_counter() - t0, 1e-7)
        _HOST_FLOPS = 2.0 * u.shape[0] * v.shape[0] * v.shape[1] / dt
    return _HOST_FLOPS


@dataclasses.dataclass
class ALSModel:
    """Trained factors + id maps (template ALSModel.scala:33-80 analog).

    Picklable pytree-of-numpy; recommend() runs the scoring matvec jitted.
    """

    user_vocab: np.ndarray   # sorted distinct user ids (index = row of U)
    item_vocab: np.ndarray   # sorted distinct item ids (index = row of V)
    U: np.ndarray            # [n_users, K]
    V: np.ndarray            # [n_items, K]

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_resident", None)      # device arrays never hit the checkpoint
        d.pop("_scorer_cache", None)  # quantized residency rebuilds on load
        return d

    @property
    def V_device(self) -> jax.Array:
        """Item factors resident on device across requests (SURVEY §2.9 P7:
        serve-time model residency). Re-uploaded only when V is swapped."""
        cached = getattr(self, "_resident", None)
        if cached is None or cached[0] is not self.V:
            cached = (self.V, jax.device_put(np.asarray(self.V)))
            self._resident = cached
        return cached[1]

    def user_index(self, user_id: str) -> Optional[int]:
        return vocab_index(self.user_vocab, user_id)

    def item_index(self, item_id: str) -> Optional[int]:
        return vocab_index(self.item_vocab, item_id)

    def predict_rating(self, user_id: str, item_id: str) -> Optional[float]:
        ui, ii = self.user_index(user_id), self.item_index(item_id)
        if ui is None or ii is None:
            return None
        return float(self.U[ui] @ self.V[ii])

    def _query_mask(self, exclude_items: Tuple[str, ...],
                    allow_items) -> np.ndarray:
        mask = np.zeros(len(self.item_vocab), dtype=bool)
        for it in exclude_items:
            ii = self.item_index(it)
            if ii is not None:
                mask[ii] = True
        if allow_items is not None:
            allow = np.ones(len(self.item_vocab), dtype=bool)
            for it in allow_items:
                ii = self.item_index(it)
                if ii is not None:
                    allow[ii] = False
            mask |= allow
        return mask

    def recommend(self, user_id: str, num: int,
                  exclude_items: Tuple[str, ...] = (),
                  allow_items: Optional[Tuple[str, ...]] = None):
        """Top-num (item_id, score), optionally excluding/allowlisting."""
        return self.recommend_batch(
            [(user_id, num, exclude_items, allow_items)])[0]

    def _use_host(self, n_rows: int, any_mask: bool) -> bool:
        """Route the batch to host BLAS when the estimated host scoring
        time undercuts one device round-trip. On a tunneled TPU the
        round-trip is ~10-100ms, so small catalogs (ML-100K: 1682 x 10)
        always serve from host; catalogs where the [B,N]@[N,K] matmul
        dominates go to the MXU. Masked batches lean host-ward because the
        device path also pays the [B, n_items] mask transfer.

        Host BLAS materializes full f32 scores, i.e. it IS the exact
        scorer — so it only competes in exact mode. A non-exact scorer
        mode (ops/scoring) always routes device: the operator chose
        quantized residency for a catalog scale where the host crossover
        is irrelevant, and splitting a fused deployment's traffic across
        an exact host lane would make answers depend on batch size."""
        from predictionio_tpu.ops.scoring import holder_scorer_config

        cfg = holder_scorer_config(self)
        if cfg.mode != "exact":
            return False
        if int(getattr(cfg, "shards", 1) or 1) > 1:
            # model-parallel serving: the catalog is declared bigger than
            # one device (ops/scoring.ShardedScorer shards even exact
            # mode), so the single-host materialized path must not win
            # the crossover
            return False
        flops = 2.0 * n_rows * len(self.item_vocab) * self.U.shape[1]
        host_s = flops / _host_flops()
        device_s = device_roundtrip_s() * (1.5 if any_mask else 1.0)
        return host_s < device_s

    def _fused_scorer(self):
        """The cached ops/scoring scorer for the CURRENT process scorer
        mode, or None when exact (or when the built scorer's parity
        gate demoted it to exact). Keyed on V's identity like
        `V_device`, so a fold-in apply that swaps V requantizes on the
        next scored batch — the pre-swap warm drive in practice."""
        from predictionio_tpu.ops import scoring

        scorer = scoring.scorer_for(self, self.V)
        if scorer is None or not scorer.active:
            return None
        return scorer

    def recommend_batch(self, requests):
        """Batched recommend: one [B,K]@[K,N] matmul + top_k for B queries.

        requests: sequence of (user_id, num, exclude_items, allow_items).
        Returns a list parallel to requests; [] for unknown users. This is
        the batch behind query-server micro-batching (SURVEY §2.9 P7) — the
        reference serves queries one at a time in a serial loop
        (CreateServer.scala:508). The batch runs on device (MXU matmul +
        top_k) or host BLAS, whichever the measured dispatch-latency
        crossover says is faster (`_use_host`).
        """
        out = [[] for _ in requests]
        scored = self._score_topk(requests)
        if scored is None:
            return out
        rows, scores, idx, _k = scored
        n_items = len(self.item_vocab)
        # vectorized result assembly: ONE finite-mask + ONE tolist (C-level
        # float conversion) + per-row vocab gathers instead of a Python
        # isfinite/str/float call per recommended item — on a big offline
        # batch the per-item churn here was costing more than the matmul
        finite = np.isfinite(scores)
        score_rows = scores.tolist()
        for b, j in enumerate(rows):
            want = min(requests[j][1], n_items)
            names = self.item_vocab[idx[b][:want]]
            fin_b, s_b = finite[b], score_rows[b]
            out[j] = [(str(names[t]), s_b[t])
                      for t in range(want) if fin_b[t]]
        return out

    def recommend_batch_arrays(self, requests):
        """`recommend_batch` as flat columns — the offline-throughput
        assembly (workflow/batch_predict.py arrow lane). Returns
        ``(items, scores, counts)``: request ``j`` owns the slice
        ``sum(counts[:j]) : sum(counts[:j+1])`` of the flat ``items``
        (object ndarray of item ids) and ``scores`` (float64 ndarray;
        float32 scores widened exactly as Python ``float()`` does, so
        values match the list path bit for bit). Never materializes a
        per-item Python tuple — at batch-scoring rates that churn costs
        more than the matmul; counts are 0 for unknown users."""
        counts = np.zeros(len(requests), dtype=np.int64)
        scored = self._score_topk(requests)
        empty = np.asarray([], dtype=object)
        if scored is None:
            return empty, np.asarray([], dtype=np.float64), counts
        rows, scores, idx, k = scored
        n_items = len(self.item_vocab)
        want = np.fromiter(
            (min(requests[j][1], n_items) for j in rows),
            dtype=np.int64, count=len(rows))
        take = np.isfinite(scores) & (np.arange(k)[None, :] < want[:, None])
        counts[np.asarray(rows)] = take.sum(axis=1)
        return (self.item_vocab[idx[take]],
                scores[take].astype(np.float64), counts)

    def _score_topk(self, requests):
        """Shared scoring core of the recommend_batch family: validate,
        gather known users, run the host-BLAS or bucketed-device matmul +
        top-k. Returns (rows, scores[B,k], idx[B,k], k) over the known-user
        rows, or None when no request has a known user."""
        n_items = len(self.item_vocab)
        for _u, num, _ex, _allow in requests:
            if num < 0:
                raise ValueError(f"num must be >= 0, got {num}")
        rows, uidx = [], []
        any_mask = False
        for j, (user_id, _num, ex, allow) in enumerate(requests):
            ui = self.user_index(user_id)
            if ui is not None:
                rows.append(j)
                uidx.append(ui)
                if ex or allow is not None:
                    any_mask = True
        if not rows:
            return None
        k = min(max(min(requests[j][1], n_items) for j in rows), n_items)
        u_batch = self.U[np.asarray(uidx)]

        if self._use_host(len(rows), any_mask):
            scores = u_batch @ self.V.T                  # [B, N] host BLAS
            if any_mask:
                for b, j in enumerate(rows):
                    m = self._query_mask(requests[j][2], requests[j][3])
                    scores[b, m] = -np.inf
            scores, idx = _host_topk(scores, k)
        elif (scorer := self._fused_scorer()) is not None:
            # fused/quantized/two-stage streaming kernel (ops/scoring):
            # the [B, n_items] score matrix never materializes, and the
            # seen-items mask folds into the tiles as a -inf sentinel,
            # so masked and unmasked batches ride ONE kernel family
            mask = None
            if any_mask:
                mask = np.stack(
                    [self._query_mask(requests[j][2], requests[j][3])
                     for j in rows])
            scores, idx = scorer.topk(u_batch, k, mask=mask)
        else:
            # bucket B and k to powers of two (ops/bucketing — the rule
            # the serving micro-batcher shares) so this scorer compiles a
            # handful of shapes instead of one per (batch, num) combo; an
            # un-bucketed jit would stall whole batches on recompiles
            b_pad = bucket_size(len(rows))
            k_pad = min(bucket_size(k), n_items)
            u_batch = _pad_rows(u_batch, b_pad)
            rank = u_batch.shape[1]
            if any_mask:
                mask = np.stack(
                    [self._query_mask(requests[j][2], requests[j][3])
                     for j in rows]
                    + [np.ones(n_items, bool)] * (b_pad - len(rows)))
                # shape_cached_fn returns the SAME shared jit (compiles
                # live in jit's cache); its build counter is the
                # per-bucket compile ledger pio_jax_compile_total reads
                fn = shape_cached_fn(
                    "als_topk_masked", (b_pad, k_pad, n_items, rank),
                    lambda: _topk_scores_batch)
                scores, idx = fn(jnp.asarray(u_batch), self.V_device,
                                 jnp.asarray(mask), k_pad)
            else:
                fn = shape_cached_fn(
                    "als_topk", (b_pad, k_pad, n_items, rank),
                    lambda: _topk_scores_batch_nomask)
                scores, idx = fn(jnp.asarray(u_batch), self.V_device,
                                 k_pad)
            scores, idx = jax.device_get((scores, idx))  # one fetch
            scores = scores[:len(rows), :k]
            idx = idx[:len(rows), :k]
        return rows, scores, idx, k


def rmse(model_U: np.ndarray, model_V: np.ndarray, user_idx: np.ndarray,
         item_idx: np.ndarray, ratings: np.ndarray) -> float:
    """Held-out RMSE of r_hat = u . v (the judged metric)."""
    pred = np.einsum("nk,nk->n", model_U[user_idx], model_V[item_idx])
    return float(np.sqrt(np.mean((pred - ratings) ** 2)))


# ---------------------------------------------------------------------------
# Online fold-in (deploy/foldin.py): batched single-side row solves
# ---------------------------------------------------------------------------

#: compile-ledger family of the online fold-in solver: one entry per
#: distinct (factor shape, segment bucket, row bucket, row_len, mode)
#: program — bounded by the power-of-two bucket ladders, never by the
#: number of applies (the als_topk discipline applied to fold-in)
FOLDIN_FAMILY = "als_foldin"


@functools.partial(
    jax.jit, static_argnames=("num_segments", "implicit_prefs",
                              "weighted_reg", "alpha_is_zero", "chunk_rows"))
def _foldin_solve(factors, gram_all, row_tgt, row_seg, row_val, row_w,
                  reg, alpha, *, num_segments: int, implicit_prefs: bool,
                  weighted_reg: bool, alpha_is_zero: bool,
                  chunk_rows: int) -> jax.Array:
    """Solve `num_segments` rows' normal equations against the frozen
    `factors` in one batched program — `_half_sweep_dyn`'s math with the
    global Gramian PASSED IN (``gram_all``, cached per serving unit by
    :class:`FoldInSolver`) instead of recomputed per dispatch, which is
    what makes a 2-second apply cadence affordable on a large catalog.
    Explicit feedback ignores ``gram_all`` (pass zeros)."""
    if implicit_prefs:
        p = jnp.where(row_val > 0, 1.0, 0.0)
        if alpha_is_zero:
            # c = 1 everywhere: the per-rating Gramian term vanishes
            _, rhs, cnt = rows_gram_rhs(
                factors, row_tgt, row_seg, row_val=p, row_w=row_w,
                num_segments=num_segments, chunk_rows=chunk_rows)
            A = jnp.broadcast_to(
                gram_all, (num_segments,) + gram_all.shape)
        else:
            cm1 = alpha * jnp.abs(row_val)               # c - 1
            vals = jnp.where(cm1 > 0,
                             (1.0 + cm1) * p / jnp.maximum(cm1, 1e-12), 0.0)
            gram, rhs, _ = rows_gram_rhs(
                factors, row_tgt, row_seg,
                row_val=vals, row_w=row_w * cm1,
                num_segments=num_segments, chunk_rows=chunk_rows)
            cnt = segment_count(row_seg, row_w.sum(axis=1), num_segments)
            A = gram_all[None, :, :] + gram
        lam = reg * jnp.where(weighted_reg, jnp.maximum(cnt, 1.0), 1.0)
        A = A + lam[:, None, None] * jnp.eye(factors.shape[1], dtype=A.dtype)
        return batched_spd_solve(A, rhs)
    gram, rhs, cnt = rows_gram_rhs(
        factors, row_tgt, row_seg, row_val=row_val, row_w=row_w,
        num_segments=num_segments, chunk_rows=chunk_rows)
    lam = reg * jnp.where(weighted_reg, jnp.maximum(cnt, 1.0), 1.0)
    A = gram + lam[:, None, None] * jnp.eye(factors.shape[1],
                                            dtype=gram.dtype)
    return batched_spd_solve(A, rhs)


_GRAM_FN = jax.jit(lambda v: v.T @ v)


class FoldInSolver:
    """Device-batched online fold-in against one frozen factor matrix.

    The composable unit iALS++ (arXiv:2110.14044) and ALX
    (arXiv:2112.02194) both build on: with the opposite side's factors
    frozen, each pending row (a user with fresh events, or an item with
    fresh raters) is an independent K x K least-squares solve — so B
    pending rows batch into ONE device program: gather each row's rated
    columns from `factors` (the ALX padded-row layout, reusing the
    training path's `_row_positions` packing + `rows_gram_rhs` Gramian
    assembly), add the per-unit cached global Gramian (implicit
    feedback's V^T V term, computed once per serving unit, not per
    apply), and run one batched Cholesky.

    Shapes are bucketed to powers of two (segment count AND packed row
    count) and registered in the ``als_foldin`` fn_cache family, so a
    server folding every few seconds compiles a bucket ladder once and
    then never again — the compile ledger stays bounded however long the
    event stream runs.
    """

    def __init__(self, factors: np.ndarray, params: ALSParams,
                 row_len: int = 32, factors_device=None):
        self.params = params
        self.row_len = max(1, int(row_len))
        host = np.ascontiguousarray(np.asarray(factors), np.float32)
        self._shape = host.shape
        #: resident device copy — callers with an already-resident array
        #: (ALSModel.V_device) pass it to skip the upload
        self._dev = (factors_device if factors_device is not None
                     else jax.device_put(host))
        self._gram = None        # lazy [K, K] V^T V (implicit) / zeros

    @property
    def rank(self) -> int:
        return self._shape[1]

    def _gram_dev(self):
        if self._gram is None:
            if self.params.implicit_prefs:
                self._gram = _GRAM_FN(self._dev)
            else:
                self._gram = jnp.zeros((self.rank, self.rank), jnp.float32)
        return self._gram

    def solve(self, rated, values, weights=None) -> np.ndarray:
        """Solve rows for B segments: ``rated[i]`` holds segment i's
        rated opposite-side indices (int), ``values[i]`` the rating
        values, optional ``weights[i]`` per-rating weights (default 1).
        Returns host float32 [B, K]. A segment with zero ratings solves
        to the zero row — callers should skip empties instead of
        applying them."""
        b = len(rated)
        if b != len(values):
            raise ValueError(f"rated/values length mismatch: {b} vs "
                             f"{len(values)}")
        if b == 0:
            return np.zeros((0, self.rank), np.float32)
        counts = np.fromiter((len(r) for r in rated), dtype=np.int64,
                             count=b)
        if weights is not None and [len(w) for w in weights] != \
                counts.tolist():
            raise ValueError("weights must parallel rated per segment")
        seg = np.repeat(np.arange(b, dtype=np.int64), counts)
        total = int(counts.sum())
        if total:
            tgt = np.concatenate([np.asarray(r) for r in rated]
                                 ).astype(np.int32)
            val = np.concatenate([np.asarray(v) for v in values]
                                 ).astype(np.float32)
            w = (np.concatenate([np.asarray(x) for x in weights]
                                ).astype(np.float32)
                 if weights is not None
                 else np.ones(total, np.float32))
            bad = (tgt < 0) | (tgt >= self._shape[0])
            if bad.any():
                raise ValueError(
                    f"rated indices out of range [0, {self._shape[0]})")
        else:
            tgt = np.zeros(0, np.int32)
            val = np.zeros(0, np.float32)
            w = np.zeros(0, np.float32)
        b_pad = bucket_size(b)
        rrow, col, n_rows, row_seg = _row_positions(seg, self.row_len,
                                                    b_pad)
        r_pad = bucket_size(max(n_rows, 1))
        row_tgt = np.zeros((r_pad, self.row_len), np.int32)
        row_val = np.zeros((r_pad, self.row_len), np.float32)
        row_w = np.zeros((r_pad, self.row_len), np.float32)
        # pad rows aim at the LAST (padding) segment with weight 0, so
        # row_seg stays sorted and the pads contribute nothing
        seg_arr = np.full((r_pad,), b_pad - 1, np.int32)
        seg_arr[:n_rows] = row_seg
        if rrow is not None:
            row_tgt[rrow, col] = tgt
            row_val[rrow, col] = val
            row_w[rrow, col] = w
        p = self.params
        key = (self._shape, b_pad, r_pad, self.row_len,
               p.implicit_prefs, p.weighted_reg, p.alpha == 0)
        # shape_cached_fn returns the SAME shared jit (executables live
        # in jit's cache); its build counter is the per-bucket compile
        # ledger pio_jax_compile_total{family=als_foldin} reads
        fn = shape_cached_fn(FOLDIN_FAMILY, key, lambda: _foldin_solve)
        out = fn(self._dev, self._gram_dev(), jnp.asarray(row_tgt),
                 jnp.asarray(seg_arr), jnp.asarray(row_val),
                 jnp.asarray(row_w), p.reg, p.alpha,
                 num_segments=b_pad, implicit_prefs=p.implicit_prefs,
                 weighted_reg=p.weighted_reg,
                 alpha_is_zero=(p.alpha == 0), chunk_rows=1024)
        return np.asarray(jax.device_get(out))[:b]
