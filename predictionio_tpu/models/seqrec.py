"""Session-based sequence recommendation: a causal transformer over each
user's event stream (SASRec-style next-item prediction).

The reference has no sequence models — its closest notion is the MarkovChain
top-N transition engine (e2/.../engine/MarkovChain.scala:25-87, first-order
only). This model family is the long-context upgrade of that component: the
per-user ordered event sequence IS the long axis, attention replaces the
transition matrix, and the same DASE Engine surface serves it.

TPU-native design:
  * all shapes static (sessions padded/truncated to max_len; id 0 = padding);
  * one jitted train step: causal flash attention (ops/attention.py) + tied
    item-embedding softmax, optax adamw, donated optimizer state;
  * multi-axis sharding via NamedSharding constraints, XLA inserts the
    collectives: batch over the "data" axis (dp), item-embedding rows and
    attention heads over the "model" axis (tp). For sessions longer than one
    chip's HBM, ``attention_impl="ring"`` swaps the local flash kernel for
    ring attention over a "seq" axis (sp).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.core.params import Params
from predictionio_tpu.ops.attention import (
    blockwise_attention, ring_attention_traced,
)


@dataclasses.dataclass
class SeqRecParams(Params):
    """Hyperparameters; json keys camelCase per engine.json convention."""

    d_model: int = 64
    n_heads: int = 2
    n_layers: int = 2
    max_len: int = 32
    learning_rate: float = 1e-3
    batch_size: int = 128
    epochs: int = 10
    seed: int = 7
    #: "flash" (local blockwise kernel) or "ring" (sequence parallelism:
    #: K/V blocks rotate over the mesh's "seq" axis via ppermute — sp for
    #: sessions longer than one chip's HBM). "ring" requires training on
    #: a mesh with a "seq" axis; serving always uses the local kernel.
    attention_impl: str = "flash"


def init_params(rng: np.random.Generator, n_items: int, p: SeqRecParams,
                vocab_multiple: int = 1) -> Dict:
    """Weights as a pytree. Vocabulary row 0 is the padding item; the table
    is padded up to a multiple of the tp axis size so it shards evenly
    (dead rows never appear as targets and are masked at predict time)."""
    d, v = p.d_model, n_items + 1
    v = -(-v // vocab_multiple) * vocab_multiple
    scale = d ** -0.5

    def norm():
        return {"scale": jnp.ones((d,), jnp.float32),
                "bias": jnp.zeros((d,), jnp.float32)}

    def dense(n_in, n_out):
        return jnp.asarray(
            rng.normal(size=(n_in, n_out)) * (n_in ** -0.5), jnp.float32)

    layers = []
    for _ in range(p.n_layers):
        layers.append({
            "ln1": norm(), "ln2": norm(),
            "wqkv": dense(d, 3 * d), "wo": dense(d, d),
            "w1": dense(d, 4 * d), "w2": dense(4 * d, d),
        })
    return {
        "emb": jnp.asarray(rng.normal(size=(v, d)) * scale, jnp.float32),
        "pos": jnp.asarray(rng.normal(size=(p.max_len, d)) * scale,
                           jnp.float32),
        "ln_f": norm(),
        "layers": layers,
    }


def _layer_norm(x, ln):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * ln["scale"] + ln["bias"]


def forward(params: Dict, seqs: jax.Array, n_heads: int,
            mesh: Optional[Mesh] = None,
            attention_impl: str = "flash") -> jax.Array:
    """[B, L] int32 item ids (0 = pad) -> [B, L, D] hidden states.

    attention_impl="ring" + a mesh with a "seq" axis runs the attention
    sequence-parallel (ring_attention_traced): each device holds L/p of
    the sequence and K/V blocks rotate via ppermute — exact, O(L/p) HBM
    per device."""
    b, l = seqs.shape
    d = params["emb"].shape[1]
    h = params["emb"][seqs] + params["pos"][None, :l]
    pad = (seqs == 0)[..., None]
    key_mask = seqs != 0       # left-padding sits in the causal PAST; the
    if attention_impl not in ("flash", "ring"):
        raise ValueError(f"unknown attention_impl {attention_impl!r}: "
                         "expected 'flash' or 'ring'")
    use_ring = (attention_impl == "ring" and mesh is not None
                and "seq" in mesh.axis_names)
    if attention_impl == "ring" and not use_ring:
        raise ValueError('attention_impl="ring" requires a mesh with a '
                         '"seq" axis')
    for layer in params["layers"]:  # key mask keeps it out of the softmax
        x = _layer_norm(h, layer["ln1"])
        qkv = x @ layer["wqkv"]                       # [B, L, 3D] MXU
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(b, l, n_heads, d // n_heads)
        if use_ring:
            att = ring_attention_traced(
                split(q), split(k), split(v), mesh, axis="seq",
                causal=True, key_mask=key_mask)
        else:
            att = blockwise_attention(split(q), split(k), split(v),
                                      causal=True, key_mask=key_mask)
        h = h + att.reshape(b, l, d) @ layer["wo"]
        x = _layer_norm(h, layer["ln2"])
        h = h + jax.nn.gelu(x @ layer["w1"]) @ layer["w2"]
    return jnp.where(pad, 0.0, _layer_norm(h, params["ln_f"]))


def _loss_fn(params, seqs, targets, n_heads, mesh=None,
             attention_impl="flash"):
    """Next-item softmax cross-entropy, tied output embedding, pad-masked."""
    hidden = forward(params, seqs, n_heads, mesh, attention_impl)  # [B,L,D]
    logits = hidden @ params["emb"].T                 # [B, L, V] MXU
    mask = (targets > 0).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_train_step(mesh: Optional[Mesh], p: SeqRecParams, optimizer):
    """One donated jitted step. With a mesh, batch is sharded over "data"
    and embedding/ffn rows over "model"; XLA inserts the psums."""

    def step(params, opt_state, seqs, targets):
        if mesh is not None and "data" in mesh.axis_names:
            # with ring attention the sequence dim lives on "seq"; laying
            # the tokens out that way up front saves XLA a full reshard
            seq_dim = "seq" if ("seq" in mesh.axis_names
                                and p.attention_impl == "ring") else None
            sh = NamedSharding(mesh, P("data", seq_dim))
            seqs = jax.lax.with_sharding_constraint(seqs, sh)
            targets = jax.lax.with_sharding_constraint(targets, sh)
        loss, grads = jax.value_and_grad(_loss_fn)(
            params, seqs, targets, p.n_heads, mesh, p.attention_impl)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda w, u: w + u, params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def shard_params(params: Dict, mesh: Mesh) -> Dict:
    """Lay out the big matrices over the "model" axis (tp): embedding rows,
    ffn inner dim, qkv columns. Small norms replicate."""
    if "model" not in mesh.axis_names:
        return params

    def spec_of(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name == "emb":
            return P("model", None)
        if name in ("wqkv", "w1"):
            return P(None, "model")
        if name == "w2":
            return P("model", None)
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: jax.device_put(
            leaf, NamedSharding(mesh, spec_of(path, leaf))), params)


def pad_sessions(sessions: Sequence[Sequence[int]], max_len: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Sessions of 1-based item ids -> (inputs [N, L], targets [N, L]):
    inputs are the sequence shifted right; targets the sequence itself.
    Keeps the LAST max_len items of each session (recency window)."""
    n = len(sessions)
    inputs = np.zeros((n, max_len), np.int32)
    targets = np.zeros((n, max_len), np.int32)
    for i, s in enumerate(sessions):
        s = list(s)[-(max_len + 1):]
        tgt = s[1:] if len(s) > 1 else []
        inp = s[:-1] if len(s) > 1 else []
        if not inp:
            continue
        inputs[i, -len(inp):] = inp
        targets[i, -len(tgt):] = tgt
    return inputs, targets


@dataclasses.dataclass
class SeqRecModel:
    """Trained weights + id maps; picklable pytree-of-numpy."""

    item_vocab: np.ndarray     # index i -> item id string for code i+1
    params: Dict               # numpy pytree
    hyper: SeqRecParams

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_resident", None)
        return d

    def _device_params(self):
        cached = getattr(self, "_resident", None)
        if cached is None or cached[0] is not self.params:
            dev = jax.tree.map(jnp.asarray, self.params)
            cached = (self.params, dev)
            self._resident = cached
        return cached[1]

    def item_code(self, item_id: str) -> Optional[int]:
        i = np.searchsorted(self.item_vocab, item_id)
        if i < len(self.item_vocab) and self.item_vocab[i] == item_id:
            return int(i) + 1          # 0 is padding
        return None

    def recommend_next(self, recent_items: Sequence[str], num: int,
                       exclude_seen: bool = True) -> List[Tuple[str, float]]:
        codes = [c for it in recent_items
                 if (c := self.item_code(it)) is not None]
        if not codes:
            return []
        l = self.hyper.max_len
        seq = np.zeros((1, l), np.int32)
        tail = codes[-l:]
        seq[0, -len(tail):] = tail
        dev = self._device_params()
        hidden = _predict_hidden(dev, jnp.asarray(seq), self.hyper.n_heads)
        logits = np.array(hidden[0, -1] @ dev["emb"].T)   # writable copy
        logits[0] = -np.inf                     # padding id
        logits[len(self.item_vocab) + 1:] = -np.inf   # vocab-padding rows
        if exclude_seen:
            logits[np.asarray(codes)] = -np.inf   # ALL seen, not just tail
        k = min(num, len(self.item_vocab))
        top = np.argpartition(-logits, kth=k - 1)[:k]
        top = top[np.argsort(-logits[top])]
        return [(str(self.item_vocab[i - 1]), float(logits[i]))
                for i in top if np.isfinite(logits[i])]


@functools.partial(jax.jit, static_argnames="n_heads")
def _predict_hidden(params, seqs, n_heads):
    return forward(params, seqs, n_heads)


def seqrec_fingerprint(item_vocab: np.ndarray, p: SeqRecParams,
                       sessions: Sequence[Sequence[str]] = ()) -> str:
    """Identity of a seqrec run for checkpoint-resume safety: every
    hyperparam that shapes the trajectory (epochs excluded — training
    further IS the resume use case) + the full item vocabulary + the
    training sessions themselves. Guards against resuming onto a changed
    item set/order of the same size (embeddings silently mapped to wrong
    item codes), changed learning_rate/seed, or an event store whose
    interactions changed while the vocab did not."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    h.update(repr((p.d_model, p.n_heads, p.n_layers, p.max_len,
                   p.learning_rate, p.batch_size, p.seed)).encode())
    h.update("\x00".join(str(it) for it in item_vocab).encode())
    for s in sessions:
        h.update("\x00".join(str(it) for it in s).encode())
        h.update(b"\x01")
    return h.hexdigest()


def train_seqrec(mesh: Optional[Mesh], sessions: Sequence[Sequence[str]],
                 p: SeqRecParams, checkpointer=None) -> SeqRecModel:
    """End-to-end: id-assign, pad, adamw train, return pickled-friendly
    model. `sessions` are per-user time-ordered item-id lists. With a
    `workflow.checkpoint.Checkpointer`, (params, opt_state) snapshot every
    `interval` epochs and a preempted run resumes from the latest one."""
    import optax

    all_items = np.asarray(sorted({it for s in sessions for it in s}),
                           dtype=object)
    code = {it: i + 1 for i, it in enumerate(all_items)}
    coded = [[code[it] for it in s] for s in sessions if len(s) >= 2]
    if not coded:
        raise ValueError("need at least one session with >= 2 events")
    inputs, targets = pad_sessions(coded, p.max_len)

    rng = np.random.default_rng(p.seed)
    tp = mesh.shape.get("model", 1) if mesh is not None else 1
    params = init_params(rng, len(all_items), p, vocab_multiple=tp)
    fp = seqrec_fingerprint(all_items, p, sessions)
    epoch0 = 0
    restored_opt_leaves = None
    snap = checkpointer.latest(fingerprint=fp) \
        if checkpointer is not None else None
    if snap is not None and "params" in snap[1]:
        e, state = snap
        restored = jax.tree.map(jnp.asarray, state["params"])
        same = jax.tree.structure(restored) == jax.tree.structure(params) \
            and all(a.shape == b.shape for a, b in
                    zip(jax.tree.leaves(restored), jax.tree.leaves(params)))
        if same:
            epoch0, params = e, restored
            restored_opt_leaves = state.get("opt_leaves")
    # shard BEFORE optimizer.init so adamw's mu/nu inherit the tp layout
    # (a replicated opt state would double-replicate the embedding table)
    if mesh is not None and "model" in mesh.axis_names:
        params = shard_params(params, mesh)
    optimizer = optax.adamw(p.learning_rate)
    opt_state = optimizer.init(params)
    if restored_opt_leaves is not None:
        # snapshots hold the opt state as a flat leaf list (numpy-only
        # pytrees survive the restricted snapshot unpickler); rebuild it
        # against the freshly-initialized state's structure + sharding
        treedef = jax.tree.structure(opt_state)
        init_leaves = jax.tree.leaves(opt_state)
        # leaf count alone can't prove layout compatibility (round-3
        # advisor finding): every restored leaf must also match the
        # freshly-initialized leaf's shape AND dtype, else a snapshot
        # from different hyperparams (or an optax layout change) would
        # smuggle mis-shaped moments into the first apply_updates
        compatible = treedef.num_leaves == len(restored_opt_leaves) and all(
            np.asarray(s).shape == np.asarray(i).shape
            and np.asarray(s).dtype == np.asarray(i).dtype
            for s, i in zip(restored_opt_leaves, init_leaves))
        if compatible:
            saved = jax.tree.unflatten(treedef, restored_opt_leaves)
            opt_state = jax.tree.map(
                lambda init_leaf, s: jax.device_put(
                    jnp.asarray(s), init_leaf.sharding)
                if hasattr(init_leaf, "sharding") else s,
                opt_state, saved)
        else:
            import logging

            logging.getLogger(__name__).warning(
                "seqrec snapshot optimizer state incompatible with the "
                "current optimizer layout (%d leaves saved, %d expected, "
                "or shape/dtype mismatch) — resuming params at epoch %d "
                "with RESET adam moments",
                len(restored_opt_leaves), treedef.num_leaves, epoch0)
    step = make_train_step(mesh, p, optimizer)

    n = len(inputs)
    bs = min(p.batch_size, n)
    for epoch in range(epoch0, p.epochs):
        # shuffle a FRESH arange keyed by epoch: a resumed run replays the
        # identical batch order the uninterrupted run would have used
        order = np.arange(n)
        np.random.default_rng(p.seed + epoch).shuffle(order)
        for lo in range(0, n - bs + 1, bs):
            idx = order[lo:lo + bs]
            params, opt_state, _loss = step(
                params, opt_state, jnp.asarray(inputs[idx]),
                jnp.asarray(targets[idx]))
        done = epoch + 1
        if checkpointer is not None and checkpointer.due(done) \
                and done < p.epochs:
            checkpointer.save(done, {"params": params,
                                     "opt_leaves": jax.tree.leaves(opt_state)},
                              fingerprint=fp)
    del opt_state
    if mesh is not None and jax.process_count() > 1:
        # tp-sharded leaves span processes (not host-addressable); one
        # jitted identity with replicated out_shardings gathers them over
        # the interconnect so every host can extract the full model —
        # ledger-cached per mesh so retrains don't re-trace the gather
        from predictionio_tpu.ops.fn_cache import mesh_cached_fn

        replicate = mesh_cached_fn(
            "seqrec_replicate", mesh, (),
            lambda: jax.jit(lambda t: t,
                            out_shardings=NamedSharding(mesh, P())))
        params = replicate(params)
    host = jax.tree.map(np.asarray, params)
    return SeqRecModel(item_vocab=all_items, params=host, hyper=p)
