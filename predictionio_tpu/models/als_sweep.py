"""Device-batched k-fold x hyperparameter ALS evaluation sweep.

The reference MetricEvaluator loops param-sets x folds in Python,
rebuilding training data and paying a fresh XLA compile per candidate
(MetricEvaluator.scala:218 evaluateBase). ALX (arXiv:2112.02194) shows
ALS-family training is bandwidth-bound enough that batching independent
problems into one compiled program is nearly free, and iALS++
(arXiv:2110.14044) shows the hyperparameter sweep — not a single train —
dominates real matrix-factorization cost. So this module executes the
whole grid as a few large device programs:

* the fold split is built ONCE as fold-id columns packed into a single
  shared padded-row layout (`build_sweep_data`); per-fold training
  weights are computed on device as ``w * (fold_ids != fold)`` — test
  entries zero-weighted, same sparsity pattern, no per-fold data builds;
* training is ``vmap``-ed over a stacked leading axis of
  (candidate x fold) units covering every shape-PRESERVING
  hyperparameter (reg, alpha, seed, num_iterations); only shape-CHANGING
  params (rank, plus the program-shaping implicit/weighted-reg flags)
  split the grid into compile groups, so the XLA compile ledger
  (``pio_jax_compile_total{family=als_eval_sweep}``) is bounded by the
  number of distinct ranks, not by grid size;
* metrics (held-out RMSE, precision@k, top-N MSE) are computed on device
  in batch over the same leading axis; only one small sums tensor per
  launch is gathered to host;
* multi-process runs split compile groups round-robin across processes
  (the existing ``parallel/shuffle.allgather_object`` protocol) and
  merge the per-candidate score dicts; single-process multi-device runs
  shard the unit axis across local devices.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from predictionio_tpu.models.als import (
    ALSParams, _auto_row_len, _half_sweep_dyn, _half_sweep_subspace_dyn,
    _row_positions, validate_solver,
)
from predictionio_tpu.obs.eval_stats import (
    eval_batch_size, eval_candidates_counter, eval_compile_groups,
)
from predictionio_tpu.obs.registry import default_registry
from predictionio_tpu.obs.tracing import span
from predictionio_tpu.ops.fn_cache import shape_cached_fn

#: compile-ledger families: one entry per compile group (train) plus one
#: per group for the metric kernel — kept separate so the
#: "compile count == distinct ranks" contract is assertable on the
#: train family alone
TRAIN_FAMILY = "als_eval_sweep"
METRIC_FAMILY = "als_eval_metric"

#: units (candidate x fold) per compiled launch; grids larger than this
#: split into equal-size launches so one compile still covers them all
BATCH_MAX_ENV = "PIO_EVAL_BATCH_MAX"
_DEFAULT_BATCH_MAX = 256

#: per-chunk device buffer budget for the scan bodies (the vmapped
#: gather/score buffers scale with units x chunk). 256 MiB lets typical
#: eval-scale grids run each half-sweep as ONE un-chunked pass (measured
#: ~25% faster on CPU than 64 MiB chunking); grids big enough to exceed
#: it degrade to chunked scans instead of OOMing. PIO_EVAL_CHUNK_MB
#: overrides for small-HBM devices.
_CHUNK_BUDGET_BYTES = int(os.environ.get("PIO_EVAL_CHUNK_MB", 256)) << 20


# ---------------------------------------------------------------------------
# Shared fold-masked data layout
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SweepSide:
    """One side's padded rows + the fold id of every rating, packed into
    the SAME positions as the values (fold -1 = padding, never a fold)."""

    tgt: np.ndarray    # int32 [R, L]
    seg: np.ndarray    # int32 [R]
    val: np.ndarray    # float32 [R, L]
    w: np.ndarray      # float32 [R, L] (0 = padding)
    fold: np.ndarray   # int32 [R, L] (-1 = padding)
    n_segments: int
    row_len: int


@dataclasses.dataclass
class ALSSweepData:
    """The whole grid's training data, built once: both padded-row sides
    with fold columns, plus the host COO (for the metric kernels)."""

    by_user: SweepSide
    by_item: SweepSide
    user_idx: np.ndarray   # int32 [nnz]
    item_idx: np.ndarray   # int32 [nnz]
    ratings: np.ndarray    # float32 [nnz]
    fold_of: np.ndarray    # int32 [nnz]
    n_users: int
    n_items: int
    nnz: int
    k_folds: int


def _pack_sweep_side(seg_idx, tgt_idx, values, fold_of, n_segments,
                     row_len) -> SweepSide:
    order = np.argsort(seg_idx, kind="stable")
    rrow, col, n_rows, row_seg = _row_positions(
        seg_idx[order].astype(np.int64), row_len, n_segments)
    tgt = np.zeros((n_rows, row_len), np.int32)
    val = np.zeros((n_rows, row_len), np.float32)
    w = np.zeros((n_rows, row_len), np.float32)
    fold = np.full((n_rows, row_len), -1, np.int32)
    if rrow is not None:
        tgt[rrow, col] = tgt_idx[order]
        val[rrow, col] = values[order]
        w[rrow, col] = 1.0
        fold[rrow, col] = fold_of[order]
    return SweepSide(tgt=tgt, seg=row_seg, val=val, w=w, fold=fold,
                     n_segments=n_segments, row_len=row_len)


def build_sweep_data(user_idx: np.ndarray, item_idx: np.ndarray,
                     ratings: np.ndarray, fold_of: np.ndarray,
                     n_users: int, n_items: int,
                     row_len: Optional[int] = None) -> ALSSweepData:
    """Pack the FULL rating set once; fold membership rides along as a
    packed column instead of producing k separate data builds."""
    user_idx = np.ascontiguousarray(user_idx, np.int32)
    item_idx = np.ascontiguousarray(item_idx, np.int32)
    ratings = np.ascontiguousarray(ratings, np.float32)
    fold_of = np.ascontiguousarray(fold_of, np.int32)
    nnz = len(ratings)
    if row_len is None:
        row_len = _auto_row_len(nnz, max(n_users, n_items))
    return ALSSweepData(
        by_user=_pack_sweep_side(user_idx, item_idx, ratings, fold_of,
                                 n_users, row_len),
        by_item=_pack_sweep_side(item_idx, user_idx, ratings, fold_of,
                                 n_items, row_len),
        user_idx=user_idx, item_idx=item_idx, ratings=ratings,
        fold_of=fold_of, n_users=n_users, n_items=n_items, nnz=nnz,
        k_folds=int(fold_of.max()) + 1 if nnz else 0)


# ---------------------------------------------------------------------------
# Compile grouping
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class GroupStatic:
    """Everything that shapes the compiled program. Candidates differing
    only in reg/alpha/seed/num_iterations share a group (and a compile);
    each distinct (rank, solver, block_size) family is its own group —
    the compile-ledger bound the tests assert."""

    rank: int
    implicit_prefs: bool
    weighted_reg: bool
    alpha_is_zero: bool
    chunk_size: int
    solver: str = "full"
    block_size: int = 0     # 0 for the full solver (no block structure)

    @property
    def label(self) -> str:
        return f"rank={self.rank}" + \
            ("/implicit" if self.implicit_prefs else "") + \
            (f"/sub{self.block_size}" if self.solver == "subspace" else "")


def group_candidates(candidates: Sequence[ALSParams]
                     ) -> "OrderedDict[GroupStatic, List[int]]":
    groups: "OrderedDict[GroupStatic, List[int]]" = OrderedDict()
    for i, p in enumerate(candidates):
        validate_solver(p)
        key = GroupStatic(
            rank=int(p.rank), implicit_prefs=bool(p.implicit_prefs),
            weighted_reg=bool(p.weighted_reg),
            alpha_is_zero=bool(p.implicit_prefs and p.alpha == 0),
            chunk_size=int(p.chunk_size),
            solver=str(p.solver),
            # block_size only shapes subspace programs; normalizing it to
            # 0 for "full" keeps full-solver candidates in ONE group no
            # matter what block_size they happen to carry
            block_size=(int(p.block_size) if p.solver == "subspace" else 0))
        groups.setdefault(key, []).append(i)
    return groups


def _chunk_for_budget(per_element_bytes: int, n_rows: int) -> int:
    """Largest power-of-two chunk whose vmapped buffer fits the budget."""
    c = max(64, _CHUNK_BUDGET_BYTES // max(per_element_bytes, 1))
    c = 1 << int(np.floor(np.log2(c)))
    return int(min(max(n_rows, 1), c))


# ---------------------------------------------------------------------------
# Compiled kernels (built per group, registered in the compile ledger)
# ---------------------------------------------------------------------------

def _build_train_fn(static: GroupStatic, n_users: int, n_items: int,
                    max_iters: int, b: int, shapes, use_v0: bool):
    """jit(train) over a [b] unit axis: data args broadcast, candidate
    args (fold, reg, alpha, iters, init) vmapped."""
    import jax
    import jax.numpy as jnp

    k = static.rank
    (r_u, r_i, row_len) = shapes
    # the vmapped gather buffer inside rows_gram_rhs is [b, C, L, K]
    chunk_u = min(static.chunk_size, _chunk_for_budget(
        b * row_len * k * 4, r_u))
    chunk_i = min(static.chunk_size, _chunk_for_budget(
        b * row_len * k * 4, r_i))

    def train_batch(u_tgt, u_seg, u_val, u_w, u_fold,
                    i_tgt, i_seg, i_val, i_w, i_fold,
                    fold_c, reg_c, alpha_c, iters_c, init_c):
        def one(fold, reg, alpha, iters_n, init):
            # the fold split, applied on device: test entries zero-weight
            uw = u_w * (u_fold != fold)
            iw = i_w * (i_fold != fold)
            if use_v0:
                V = init
            else:
                key = jax.random.PRNGKey(init)
                V = (jax.random.normal(key, (n_items, k), jnp.float32)
                     / jnp.sqrt(jnp.asarray(k, jnp.float32)))

            def body(i, carry):
                U, V = carry
                if static.solver == "subspace":
                    U2 = _half_sweep_subspace_dyn(
                        U, V, u_tgt, u_seg, u_val, uw, n_users,
                        reg=reg, alpha=alpha,
                        implicit_prefs=static.implicit_prefs,
                        weighted_reg=static.weighted_reg,
                        alpha_is_zero=static.alpha_is_zero,
                        chunk_rows=chunk_u, block_size=static.block_size)
                    V2 = _half_sweep_subspace_dyn(
                        V, U2, i_tgt, i_seg, i_val, iw, n_items,
                        reg=reg, alpha=alpha,
                        implicit_prefs=static.implicit_prefs,
                        weighted_reg=static.weighted_reg,
                        alpha_is_zero=static.alpha_is_zero,
                        chunk_rows=chunk_i, block_size=static.block_size)
                else:
                    U2 = _half_sweep_dyn(
                        V, u_tgt, u_seg, u_val, uw, n_users,
                        reg=reg, alpha=alpha,
                        implicit_prefs=static.implicit_prefs,
                        weighted_reg=static.weighted_reg,
                        alpha_is_zero=static.alpha_is_zero,
                        chunk_rows=chunk_u)
                    V2 = _half_sweep_dyn(
                        U2, i_tgt, i_seg, i_val, iw, n_items,
                        reg=reg, alpha=alpha,
                        implicit_prefs=static.implicit_prefs,
                        weighted_reg=static.weighted_reg,
                        alpha_is_zero=static.alpha_is_zero,
                        chunk_rows=chunk_i)
                # units may carry fewer iterations than the group max:
                # finished units freeze their factors
                keep = i < iters_n
                return (jnp.where(keep, U2, U), jnp.where(keep, V2, V))

            U0 = jnp.zeros((n_users, k), jnp.float32)
            return jax.lax.fori_loop(0, max_iters, body, (U0, V))

        return jax.vmap(one)(fold_c, reg_c, alpha_c, iters_c, init_c)

    return jax.jit(train_batch)


def _build_metric_fn(rank: int, n_items: int, n_pad: int, b: int,
                     rank_spec: Optional[Tuple[int, int, float]]):
    """jit(metrics) over the same [b] unit axis; returns per-unit raw
    sums so folds pool EXACTLY like the sequential metric (points
    flattened across folds before averaging).

    Always: held-out squared error + test count over the COO entries.
    With ``rank_spec`` (query_num, precision_k, threshold): additionally
    the full-catalog rank of each held-out item, for precision@k and the
    top-N-masked MSE the DASE metrics compute.
    """
    import jax
    import jax.numpy as jnp

    if rank_spec is None:
        chunk = _chunk_for_budget(b * max(rank, 1) * 4, n_pad)
    else:
        # the [b, C, n_items] score buffer dominates
        chunk = _chunk_for_budget(b * n_items * 4, n_pad)
    n_chunks = -(-n_pad // chunk)

    def metric_batch(U, V, u_idx, i_idx, val, fold_e, fold_c):
        tail = n_chunks * chunk - n_pad
        # pad to a chunk multiple; fold -1 marks never-test entries
        u_p = jnp.concatenate([u_idx, jnp.zeros(tail, u_idx.dtype)])
        i_p = jnp.concatenate([i_idx, jnp.zeros(tail, i_idx.dtype)])
        v_p = jnp.concatenate([val, jnp.zeros(tail, val.dtype)])
        f_p = jnp.concatenate([fold_e, jnp.full(tail, -1, fold_e.dtype)])
        slabs = (u_p.reshape(n_chunks, chunk),
                 i_p.reshape(n_chunks, chunk),
                 v_p.reshape(n_chunks, chunk),
                 f_p.reshape(n_chunks, chunk))

        if rank_spec is None:
            def one(Ub, Vb, fold):
                def body(carry, sl):
                    u, i, v, f = sl
                    pred = jnp.sum(Ub[u] * Vb[i], axis=1)
                    test = (f == fold).astype(jnp.float32)
                    se, nt = carry
                    return (se + jnp.sum(test * (pred - v) ** 2),
                            nt + test.sum()), None

                (se, nt), _ = jax.lax.scan(body, (0.0, 0.0), slabs)
                return se, nt, jnp.zeros(()), jnp.zeros(()), jnp.zeros(())
        else:
            query_num, prec_k, threshold = rank_spec
            qn_eff = min(query_num, n_items)
            cut = min(prec_k, qn_eff)

            def one(Ub, Vb, fold):
                def body(carry, sl):
                    u, i, v, f = sl
                    uvec = Ub[u]                        # [C, K]
                    scores = uvec @ Vb.T                # [C, n_items]
                    s_i = jnp.sum(uvec * Vb[i], axis=1)
                    # rank = #items scoring strictly higher; the held-out
                    # item is in the served top-m iff rank < m
                    rk = jnp.sum(scores > s_i[:, None], axis=1)
                    test = (f == fold)
                    pred = jnp.where(rk < qn_eff, s_i, 0.0)
                    qual = test & (v >= threshold)
                    # a user with NO training ratings solves to an exactly
                    # zero factor row (gram=0, rhs=0), which would rank
                    # its held-out item 0 (nothing beats an all-zero
                    # score row) — but the sequential path serves an
                    # unknown user an EMPTY list, i.e. a miss. Mask those
                    # cold users out of the hit count to match.
                    known = jnp.any(uvec != 0, axis=1)
                    hit = qual & known & (rk < cut)
                    se, nt, hits, nq, tse = carry
                    testf = test.astype(jnp.float32)
                    return (se + jnp.sum(testf * (s_i - v) ** 2),
                            nt + testf.sum(),
                            hits + hit.sum().astype(jnp.float32),
                            nq + qual.sum().astype(jnp.float32),
                            tse + jnp.sum(testf * (pred - v) ** 2)), None

                init = (0.0, 0.0, 0.0, 0.0, 0.0)
                (se, nt, hits, nq, tse), _ = jax.lax.scan(body, init, slabs)
                return se, nt, hits, nq, tse

        return jax.vmap(one)(U, V, fold_c)

    return jax.jit(metric_batch)


# ---------------------------------------------------------------------------
# The sweep runner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CandidateResult:
    """Pooled-over-folds metrics + cost attribution for one candidate."""

    params: ALSParams
    group: str
    wall_s: float
    heldout_rmse: float
    n_test: int
    precision: Optional[float] = None
    n_qual: Optional[int] = None
    topn_mse: Optional[float] = None

    def to_json_dict(self) -> dict:
        return {
            "group": self.group,
            "wallTimeS": round(self.wall_s, 4),
            "heldoutRmse": self.heldout_rmse,
            "nTest": self.n_test,
            **({"precision": self.precision, "nQual": self.n_qual,
                "topnMse": self.topn_mse}
               if self.precision is not None else {}),
        }


@dataclasses.dataclass
class SweepResult:
    candidates: List[CandidateResult]
    n_groups: int
    batch_sizes: List[int]
    mode: str


def _local_shardings():
    """(unit_sharding_fn, replicated_sharding) over the LOCAL devices:
    unit arrays shard their leading [b] axis across devices (when b
    divides evenly), broadcast data is placed replicated ONCE so launches
    never re-transfer the padded-row layout. (None, None) on one device."""
    import jax

    devices = jax.local_devices()
    if len(devices) <= 1:
        return None, None
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(devices), axis_names=("cand",))

    def unit_sharding(b: int):
        if b % len(devices) != 0:
            return None
        return NamedSharding(mesh, P("cand"))

    return unit_sharding, NamedSharding(mesh, P())


def run_sweep(data: ALSSweepData, candidates: Sequence[ALSParams], *,
              rank_metrics: Optional[Tuple[int, int, float]] = None,
              batched: bool = True, warm_start: bool = False,
              registry=None) -> SweepResult:
    """Evaluate every candidate over every fold as a few device launches.

    ``rank_metrics`` — optional (query_num, precision_k, threshold) to
    additionally compute full-catalog precision@k / top-N MSE (costs a
    [units, chunk, n_items] score pass; held-out RMSE alone only gathers
    the held-out entries). ``batched=False`` runs the identical kernels
    one (candidate, fold) unit at a time — the sequential reference the
    parity tests compare against. ``warm_start=True`` initializes each
    rank group's item factors from the previous (smaller-rank) group's
    trained factors of the same fold, column-padded with fresh noise —
    an accuracy/speed knob that intentionally departs from seeded-init
    parity, so it is off by default.
    """
    import jax
    import jax.numpy as jnp

    if not candidates:
        raise ValueError("candidates must not be empty")
    if data.k_folds < 1:
        raise ValueError("sweep data has no folds (empty rating set?)")
    registry = registry or default_registry()
    mode = "batched" if batched else "sequential"
    k_folds = data.k_folds
    groups = group_candidates(candidates)
    group_items = list(groups.items())
    if warm_start:
        group_items.sort(key=lambda kv: kv[0].rank)

    # multi-process: split compile groups round-robin; merge small score
    # dicts at the end over the existing allgather protocol
    n_proc = jax.process_count()
    my_groups = [
        (gi, key, members) for gi, (key, members) in enumerate(group_items)
        if gi % n_proc == jax.process_index()]

    # entries padded to a chunk multiple; fold -1 never matches a fold
    n_pad = -(-max(data.nnz, 1) // 64) * 64
    pad = n_pad - data.nnz
    u_idx = np.concatenate([data.user_idx, np.zeros(pad, np.int32)])
    i_idx = np.concatenate([data.item_idx, np.zeros(pad, np.int32)])
    vals = np.concatenate([data.ratings, np.zeros(pad, np.float32)])
    fold_e = np.concatenate([data.fold_of, np.full(pad, -1, np.int32)])

    bu, bi = data.by_user, data.by_item
    unit_sharding, rep_sh = _local_shardings()
    with span("eval_data_put", registry):
        def _put(a):
            return (jax.device_put(a, rep_sh) if rep_sh is not None
                    else jnp.asarray(a))

        data_args = tuple(_put(a) for a in (
            bu.tgt, bu.seg, bu.val, bu.w, bu.fold,
            bi.tgt, bi.seg, bi.val, bi.w, bi.fold))
        entry_args = tuple(_put(a) for a in
                           (u_idx, i_idx, vals, fold_e))

    batch_max = int(os.environ.get(BATCH_MAX_ENV, _DEFAULT_BATCH_MAX))
    results: Dict[int, CandidateResult] = {}
    batch_sizes: List[int] = []
    prev_v: Dict[int, np.ndarray] = {}      # fold -> trained V (warm start)
    shapes = (bu.tgt.shape[0], bi.tgt.shape[0], bu.row_len)

    for gi, static, members in my_groups:
        group_label = f"g{gi}:{static.label}"
        units = [(ci, f) for ci in members for f in range(k_folds)]
        b = min(len(units), batch_max) if batched else 1
        max_iters = max(int(candidates[ci].num_iterations)
                        for ci in members)
        use_v0 = warm_start
        train_key = (static, max_iters, b, data.n_users, data.n_items,
                     shapes, use_v0)
        train_fn = shape_cached_fn(
            TRAIN_FAMILY, train_key,
            lambda: _build_train_fn(static, data.n_users, data.n_items,
                                    max_iters, b, shapes, use_v0))
        metric_key = (static.rank, data.n_items, n_pad, b, rank_metrics,
                      data.n_users)
        metric_fn = shape_cached_fn(
            METRIC_FAMILY, metric_key,
            lambda: _build_metric_fn(static.rank, data.n_items, n_pad, b,
                                     rank_metrics))
        unit_sh = unit_sharding(b) if unit_sharding is not None else None

        # raw pooled sums per candidate of this group
        sums = {ci: np.zeros(5, np.float64) for ci in members}
        t_group = time.perf_counter()
        for lo in range(0, len(units), b):
            launch = units[lo:lo + b]
            n_real = len(launch)
            launch = launch + [launch[0]] * (b - n_real)    # pad, discard
            fold_c = np.asarray([f for _, f in launch], np.int32)
            reg_c = np.asarray([candidates[ci].reg for ci, _ in launch],
                               np.float32)
            alpha_c = np.asarray([candidates[ci].alpha
                                  for ci, _ in launch], np.float32)
            iters_c = np.asarray([candidates[ci].num_iterations
                                  for ci, _ in launch], np.int32)
            if use_v0:
                init_c = np.stack([
                    _warm_init(prev_v.get(f), static.rank, data.n_items,
                               int(candidates[ci].seed), f)
                    for ci, f in launch])
            else:
                init_c = np.asarray([candidates[ci].seed
                                     for ci, _ in launch], np.int32)
            cand_args = (fold_c, reg_c, alpha_c, iters_c, init_c)
            if unit_sh is not None:
                cand_args = tuple(jax.device_put(a, unit_sh)
                                  for a in cand_args)
            with span("eval_train_group", registry):
                U, V = train_fn(*data_args, *cand_args)
                jax.block_until_ready(V)
            batch_sizes.append(n_real)
            eval_batch_size(registry).observe(n_real)
            with span("eval_metrics", registry):
                out = metric_fn(U, V, *entry_args,
                                cand_args[0])        # fold_c as placed
                out = np.asarray(jax.device_get(out), np.float64).T
            for j, (ci, _f) in enumerate(launch[:n_real]):
                sums[ci] += out[j]
            if warm_start:
                with span("eval_gather", registry):
                    v_host = np.asarray(jax.device_get(V))
                for j, (_ci, f) in enumerate(launch[:n_real]):
                    prev_v[f] = v_host[j]         # latest group wins
        group_wall = time.perf_counter() - t_group

        for ci in members:
            se, nt, hits, nq, tse = sums[ci]
            res = CandidateResult(
                params=candidates[ci], group=group_label,
                wall_s=group_wall / len(members),
                heldout_rmse=float(np.sqrt(se / nt)) if nt else float("nan"),
                n_test=int(nt))
            if rank_metrics is not None:
                qn, pk, _thr = rank_metrics
                denom = min(pk, min(qn, data.n_items))
                res.precision = (float(hits / (denom * nq)) if nq
                                 else float("nan"))
                res.n_qual = int(nq)
                res.topn_mse = (float(tse / nt) if nt else float("nan"))
            results[ci] = res

    if n_proc > 1:
        from predictionio_tpu.parallel.shuffle import allgather_object

        merged = {}
        for part in allgather_object(
                [(ci, dataclasses.asdict(r)) for ci, r in results.items()]):
            for ci, d in part:
                d["params"] = candidates[ci]
                merged[ci] = CandidateResult(**d)
        results = merged

    missing = [i for i in range(len(candidates)) if i not in results]
    assert not missing, f"sweep lost candidates {missing}"
    eval_candidates_counter(registry).inc(len(candidates), mode=mode)
    eval_compile_groups(registry).set(len(group_items))
    return SweepResult(
        candidates=[results[i] for i in range(len(candidates))],
        n_groups=len(group_items), batch_sizes=batch_sizes, mode=mode)


def _warm_init(v_prev: Optional[np.ndarray], rank: int, n_items: int,
               seed: int, fold: int) -> np.ndarray:
    """V0 for a warm-started unit: previous group's fold factors in the
    leading columns, fresh scaled noise in the rest (or everywhere when
    no previous group trained this fold)."""
    rng = np.random.default_rng(seed * 1009 + fold)
    v0 = (rng.standard_normal((n_items, rank)).astype(np.float32)
          / np.sqrt(rank))
    if v_prev is not None:
        keep = min(rank, v_prev.shape[1])
        v0[:, :keep] = v_prev[:n_items, :keep]
    return v0
