"""App-name-facing event store facades.

Parity with the reference's engine-facing facades:
  * `find_by_entity` / `find` <- LEventStore (data/.../store/LEventStore.scala:48-265),
    the serving-time path
  * `find_columnar` / `aggregate_properties` <- PEventStore
    (data/.../store/PEventStore.scala:35-121), the training path
  * app-name -> (app_id, channel_id) resolution <- store/Common.scala:25-60
"""

from __future__ import annotations

import datetime as _dt
import threading
from typing import Dict, Iterator, Optional, Sequence, Tuple

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.storage.base import UNFILTERED, StorageError
from predictionio_tpu.storage.registry import Storage

_channel_cache: Dict[Tuple[str, Optional[str]], Tuple[int, Optional[int]]] = {}
#: guards _channel_cache: concurrent first-touch resolves from the query
#: server's batcher worker threads would otherwise race the dict fill
_channel_cache_lock = threading.Lock()


def resolve_app(app_name: str, channel_name: Optional[str] = None
                ) -> Tuple[int, Optional[int]]:
    """app name (+ optional channel name) -> (app_id, channel_id).

    Cached, like store/Common.scala:25-60. Thread-safe: the metadata
    lookup runs outside the lock (it can hit storage), so two threads may
    race to resolve the same fresh key — both compute the same value and
    the second write is a no-op.
    """
    key = (app_name, channel_name)
    with _channel_cache_lock:
        if key in _channel_cache:
            return _channel_cache[key]
    app = Storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise StorageError(f"Invalid app name {app_name}")
    channel_id = None
    if channel_name is not None:
        channels = Storage.get_meta_data_channels().get_by_appid(app.id)
        matched = [c for c in channels if c.name == channel_name]
        if not matched:
            raise StorageError(
                f"Invalid channel name {channel_name} for app {app_name}")
        channel_id = matched[0].id
    with _channel_cache_lock:
        _channel_cache[key] = (app.id, channel_id)
    return app.id, channel_id


def clear_cache() -> None:
    with _channel_cache_lock:
        _channel_cache.clear()
    from predictionio_tpu.data.ingest import clear_scan_cache

    clear_scan_cache()


class EventStoreClient:
    """Unified facade over the configured event store, by app name."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type=UNFILTERED,
        target_entity_id=UNFILTERED,
        limit: Optional[int] = None,
        reversed_order: bool = False,
    ) -> Iterator[Event]:
        """PEventStore.find:59 / LEventStore.find:197 parity."""
        app_id, channel_id = resolve_app(app_name, channel_name)
        return Storage.get_events().find(
            app_id=app_id, channel_id=channel_id,
            start_time=start_time, until_time=until_time,
            entity_type=entity_type, entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit, reversed_order=reversed_order)

    @staticmethod
    def find_by_entity(
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type=UNFILTERED,
        target_entity_id=UNFILTERED,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        limit: Optional[int] = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        """Serving-time entity lookup (LEventStore.findByEntity:76)."""
        return EventStoreClient.find(
            app_name=app_name, channel_name=channel_name,
            start_time=start_time, until_time=until_time,
            entity_type=entity_type, entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit, reversed_order=latest)

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[_dt.datetime] = None,
        until_time: Optional[_dt.datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """PEventStore.aggregateProperties:87 parity."""
        app_id, channel_id = resolve_app(app_name, channel_name)
        return Storage.get_events().aggregate_properties(
            app_id=app_id, channel_id=channel_id, entity_type=entity_type,
            start_time=start_time, until_time=until_time, required=required)

    @staticmethod
    def find_columnar(app_name: str, channel_name: Optional[str] = None,
                      **filters):
        """Training-path columnar read (PEventStore.find -> pyarrow.Table)."""
        app_id, channel_id = resolve_app(app_name, channel_name)
        return Storage.get_events().find_columnar(app_id, channel_id, **filters)

    @staticmethod
    def snapshot_digest(app_name: str, channel_name: Optional[str] = None):
        """Cheap content fingerprint of the app's event namespace (None
        when the backend cannot produce one) — the ingest scan-cache key
        (data/ingest.py): equal digests promise an identical rescan."""
        app_id, channel_id = resolve_app(app_name, channel_name)
        store = Storage.get_events()
        fn = getattr(store, "snapshot_digest", None)
        return fn(app_id, channel_id) if fn is not None else None

    @staticmethod
    def read_snapshot(app_name: str, channel_name: Optional[str] = None):
        """Partitioned-read snapshot token for the configured backend
        (sqlite rowid window / parquet fragment list), or None when the
        backend cannot partition. Multi-host trainers capture this ONCE,
        broadcast it, and pass shard=(index, count, snapshot) to
        find_columnar so every process reads the same stable set."""
        app_id, channel_id = resolve_app(app_name, channel_name)
        store = Storage.get_events()
        fn = getattr(store, "read_snapshot", None)
        return fn(app_id, channel_id) if fn is not None else None


# short aliases mirroring the reference object names
PEventStore = EventStoreClient
LEventStore = EventStoreClient
