"""Schemaless JSON property bags.

Behavioral parity with the reference's `DataMap` / `PropertyMap`
(data/.../storage/DataMap.scala:45-245, PropertyMap.scala:36-99): a DataMap is
an immutable mapping from field name to a JSON value with typed getters; a
PropertyMap additionally carries first/last updated times and is the result of
folding `$set/$unset/$delete` events (see aggregator.py).
"""

from __future__ import annotations

import datetime as _dt
import json
from typing import Any, Iterable, Iterator, Mapping, Optional, Type, TypeVar

T = TypeVar("T")

_JSON_TYPES = (type(None), bool, int, float, str, list, dict)


class DataMapError(Exception):
    """Raised when a required field is missing or has the wrong type.

    (Parity with the reference's DataMapException.)
    """


def _copy_json_value(name: str, value: Any) -> Any:
    """Validate recursively and return a deep copy of container values.

    The copy keeps DataMap immutable even when the caller retains references
    to nested lists/dicts; the recursive check rejects non-JSON leaves at
    construction instead of at serialization time.
    """
    if isinstance(value, (type(None), bool, int, float, str)):
        return value
    if isinstance(value, list):
        return [_copy_json_value(name, v) for v in value]
    if isinstance(value, dict):
        for k in value:
            if not isinstance(k, str):
                raise DataMapError(f"field {name!r} has non-string object key {k!r}")
        return {k: _copy_json_value(name, v) for k, v in value.items()}
    raise DataMapError(
        f"field {name!r} has non-JSON value of type {type(value).__name__}")


class DataMap(Mapping[str, Any]):
    """Immutable mapping of field name -> JSON value with typed getters."""

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        f = {k: _copy_json_value(k, v) for k, v in dict(fields).items()} if fields else {}
        object.__setattr__(self, "_fields", f)

    # -- Mapping protocol ---------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return dict(self._fields) == dict(other)
        return NotImplemented

    def __hash__(self) -> int:  # stable enough for test use
        return hash(json.dumps(self._fields, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- reference API ------------------------------------------------------
    @property
    def fields(self) -> dict:
        return dict(self._fields)

    @property
    def is_empty(self) -> bool:
        return not self._fields

    def key_set(self) -> set:
        return set(self._fields)

    def require(self, name: str) -> None:
        """Parity with DataMap.require (DataMap.scala:60)."""
        if name not in self._fields:
            raise DataMapError(f"The field {name} is required.")

    def get(self, name: str, cls: Optional[Type[T]] = None) -> Any:
        """Mandatory typed getter: raises if absent or null.

        Parity with DataMap.get[T] (DataMap.scala:78): a present-but-null
        field raises, because a mandatory field cannot be None.

        NOTE: this deliberately shadows Mapping.get(key, default) — DataMap's
        `get` is the reference's mandatory typed getter. Use get_opt /
        get_or_else for optional access with defaults.
        """
        if cls is not None and not isinstance(cls, type):
            raise DataMapError(
                f"DataMap.get(name, cls) takes a type, got {cls!r}; "
                "use get_or_else(name, default) for defaults.")
        self.require(name)
        value = self._fields[name]
        if value is None:
            raise DataMapError(f"The required field {name} cannot be null.")
        return _coerce(name, value, cls)

    def get_opt(self, name: str, cls: Optional[Type[T]] = None) -> Optional[Any]:
        """Optional typed getter: None when absent or null (DataMap.scala:94)."""
        value = self._fields.get(name)
        if value is None:
            return None
        return _coerce(name, value, cls)

    def get_or_else(self, name: str, default: T, cls: Optional[Type[T]] = None) -> T:
        out = self.get_opt(name, cls)
        return default if out is None else out

    def get_string_list(self, name: str) -> list:
        return self.get(name, list)

    def get_double(self, name: str) -> float:
        return float(self.get(name))

    # -- combinators --------------------------------------------------------
    def merge(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        """`this ++ that` — right-hand fields win (DataMap.scala:153)."""
        merged = dict(self._fields)
        merged.update(dict(other.fields if isinstance(other, DataMap) else other))
        return DataMap(merged)

    __or__ = merge

    def without(self, keys: Iterable[str]) -> "DataMap":
        """`this -- keys` (DataMap.scala:162)."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def extract(self, cls: Type[T]) -> T:
        """Deserialize into a dataclass/pydantic-style class (DataMap.scala:192)."""
        if hasattr(cls, "model_validate"):  # pydantic v2
            return cls.model_validate(dict(self._fields))
        try:
            return cls(**self._fields)
        except TypeError as e:
            raise DataMapError(f"cannot extract {cls.__name__} from {self}: {e}") from e

    def to_json(self) -> str:
        return json.dumps(self._fields, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "DataMap":
        parsed = json.loads(s)
        if not isinstance(parsed, dict):
            raise DataMapError("DataMap JSON must be an object")
        return cls(parsed)


def _coerce(name: str, value: Any, cls: Optional[type]) -> Any:
    if cls is None:
        return value
    if cls is float and isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    if cls is int and isinstance(value, int) and not isinstance(value, bool):
        return value
    if not isinstance(value, cls) or (cls is not bool and isinstance(value, bool) and cls in (int, float)):
        raise DataMapError(
            f"field {name!r} is {type(value).__name__}, expected {cls.__name__}")
    return value


class PropertyMap(DataMap):
    """DataMap plus first/last updated times.

    The result of folding `$set/$unset/$delete` events for one entity
    (PropertyMap.scala:36-99).
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(self, fields: Optional[Mapping[str, Any]],
                 first_updated: _dt.datetime, last_updated: _dt.datetime):
        super().__init__(fields)
        object.__setattr__(self, "first_updated", first_updated)
        object.__setattr__(self, "last_updated", last_updated)

    def __repr__(self) -> str:
        return (f"PropertyMap({self.fields!r}, firstUpdated={self.first_updated}, "
                f"lastUpdated={self.last_updated})")

    def __eq__(self, other: object) -> bool:
        # Strict: a PropertyMap only equals another PropertyMap (fields AND
        # times). Comparing against a plain DataMap/dict is always False to
        # keep equality transitive; compare `.fields` explicitly instead.
        if isinstance(other, PropertyMap):
            return (self.fields == other.fields
                    and self.first_updated == other.first_updated
                    and self.last_updated == other.last_updated)
        if isinstance(other, Mapping):
            return False
        return NotImplemented

    __hash__ = DataMap.__hash__
