"""Group-commit write buffer: the durable, self-defending ingest path.

The reference delegated write-path survival to HBase/ES; the rebuild's own
backends get there with an explicit pipeline stage between the REST handler
and the EventStore (ROADMAP item 2). The shape mirrors the serving-side
MicroBatcher (server/query_server.py): many small concurrent requests are
coalesced into few large storage operations, with per-request futures so
every HTTP caller still gets its own answer.

Three mechanisms:

* **group commit** — a dedicated writer thread drains the queue and folds
  concurrent submits into single ``insert_batch`` flushes per
  (app, channel) namespace, amortizing sqlite transactions, postgres
  round-trips and parquet fragment creation. Flush triggers on size
  (``flush_max`` events) or linger (``linger_s`` after the first event of
  a batch), whichever comes first.
* **backpressure** — the queue is bounded in EVENTS (``queue_max``).
  ``submit`` never blocks and never queues unboundedly: past the bound it
  raises :class:`BufferFull` carrying a ``retry_after`` estimate, which
  the event server turns into ``429 Retry-After`` (explicit load
  shedding instead of the silent executor-queue growth it replaces).
* **fault tolerance** — every event is assigned its id at SUBMIT time, so
  a flush is idempotent: retries (exponential backoff + full jitter via
  the shared ``utils/retry`` policy, bounded attempts) go through
  ``EventStore.insert_batch_idempotent`` which skips ids already
  persisted — a fault after the backend committed cannot duplicate, a
  fault before it cannot lose (the request future fails only when every
  attempt is exhausted). A flush that HANGS is bounded by
  ``flush_timeout_s`` (the attempt runs on its own thread) and retried
  the same way.

**Commit lanes** (PR 17): with ``partitions > 1`` the buffer runs one
group-commit lane PER PARTITION — its own bounded queue, writer thread
and flush stream — routed by the same stable entity hash the
partitioned store uses (storage/partitioned.partition_of), so a lane's
flush lands in exactly one partition's commit stream and the P
partitions commit in parallel. Backpressure is per lane
(``queue_max // partitions`` events each) and the 429 ``Retry-After``
estimate comes from THAT lane's observed flush time — one slow
partition no longer inflates backoff for writers of healthy ones. A
submit whose events span lanes is split and its ids reassembled in
input order; acknowledgment still means every split part committed.

``stop(drain=True)`` flushes everything still queued before returning —
the aiohttp ``on_shutdown`` hook uses it so buffered events are never
dropped by a graceful restart.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import functools
import logging
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence

from predictionio_tpu.data.event import Event
from predictionio_tpu.obs.anatomy import (
    anatomy_enabled, anatomy_metrics, observe_ingest_batch,
)
from predictionio_tpu.obs.tracing import (
    capture_context, carried, current_trace, span,
)
from predictionio_tpu.storage.base import StorageError, generate_id
from predictionio_tpu.storage.partitioned import partition_of
from predictionio_tpu.utils.retry import RetryPolicy, start_attempt_thread

logger = logging.getLogger("pio.writebuffer")

#: flush taps: callables invoked AFTER a group commit durably lands,
#: with (events, app_id, channel_id). This is the event-write-path push
#: seam the online fold-in subsystem rides (deploy/foldin.py): an
#: in-process query server learns about fresh events the moment they
#: are acknowledged, without polling. Module-level (not per-buffer) so
#: a subscriber never has to know WHICH buffer the event server built.
#: Taps run on the writer thread — they must be cheap (mark-and-return)
#: and may never raise into the flush (failures are logged and dropped);
#: durability and the caller's ack do not depend on them.
_FLUSH_TAPS: List[Callable] = []
_TAPS_LOCK = threading.Lock()


def add_flush_tap(tap: Callable) -> None:
    """Subscribe `tap(events, app_id, channel_id)` to successful group
    commits of EVERY WriteBuffer in this process."""
    with _TAPS_LOCK:
        if tap not in _FLUSH_TAPS:
            _FLUSH_TAPS.append(tap)


def remove_flush_tap(tap: Callable) -> None:
    with _TAPS_LOCK:
        try:
            _FLUSH_TAPS.remove(tap)
        except ValueError:
            pass


def _notify_taps(events, app_id, channel_id) -> None:
    with _TAPS_LOCK:
        taps = list(_FLUSH_TAPS)
    for tap in taps:
        try:
            tap(events, app_id, channel_id)
        except Exception:
            logger.exception("flush tap failed (events stay committed)")


class BufferFull(Exception):
    """The bounded ingest queue cannot accept more events right now.

    ``retry_after`` is a seconds estimate of when capacity should free up
    (queue depth over the recently observed flush rate OF THE LANE that
    shed — a slow partition backs off only its own writers), for the
    ``Retry-After`` response header.
    """

    def __init__(self, depth: int, retry_after: int):
        super().__init__(
            f"ingest queue full ({depth} events buffered); "
            f"retry in ~{retry_after}s")
        self.depth = depth
        self.retry_after = retry_after


def _as_storage_error(e: Exception) -> StorageError:
    return e if isinstance(e, StorageError) else StorageError(repr(e))


def _with_id(e: Event) -> Event:
    """Copy of `e` with a fresh event_id. Shallow __dict__ clone instead of
    dataclasses.replace: the source event already passed __post_init__
    validation and replace() would re-run it — measurable at group-commit
    submit rates (~20us/event saved on the ingest hot path)."""
    clone = object.__new__(Event)
    clone.__dict__.update(e.__dict__)
    clone.__dict__["event_id"] = generate_id()
    return clone


class _Pending:
    """One submit: its (already id-assigned) events and the caller future.

    ``trace`` is the submitting request's captured trace context — the
    writer thread re-enters it around the flush so the group-commit span
    is linked to the request that triggered it instead of starting a
    fresh, unattributable trace (the thread boundary used to drop it).
    ``t_submit``/``req_trace`` feed the ingest anatomy: when the flush
    lands, each submitter's flush-wait and shared commit wall are
    observed into ``pio_anatomy_stage_seconds{path="ingest"}`` and onto
    the submitter's own trace as ``anatomy_*`` pseudo-spans."""

    __slots__ = ("events", "app_id", "channel_id", "future", "trace",
                 "t_submit", "req_trace")

    def __init__(self, events, app_id, channel_id, future, trace=None,
                 t_submit=0.0, req_trace=None):
        self.events = events
        self.app_id = app_id
        self.channel_id = channel_id
        self.future = future
        self.trace = trace
        self.t_submit = t_submit
        self.req_trace = req_trace


class _Lane:
    """One commit lane: bounded queue + writer thread + flush clock.

    Every field is guarded by the lane's own condition variable, so the
    P lanes never contend on a shared lock — the point of the split."""

    __slots__ = ("index", "cond", "queue", "depth", "thread",
                 "last_flush_s")

    def __init__(self, index: int):
        self.index = index
        self.cond = threading.Condition()
        self.queue: deque = deque()
        self.depth = 0          # queued + in-flush events (memory bound)
        self.thread: Optional[threading.Thread] = None
        self.last_flush_s = 0.05   # seeds the retry-after estimate


def _join_parts(parent: "concurrent.futures.Future", n_events: int,
                parts) -> None:
    """Assemble a split (multi-lane) submit's parent future from its
    per-lane children: ids land back at their input positions; the first
    failed part fails the parent (the caller must treat a failed ack as
    ambiguous and retry idempotently, exactly as for one lane)."""
    ids: List[Optional[str]] = [None] * n_events
    state = {"remaining": len(parts), "failed": False}
    lock = threading.Lock()

    def one_done(idxs, child):
        exc = child.exception()
        res = child.result() if exc is None else None
        finish = None
        with lock:
            if state["failed"]:
                return
            if exc is not None:
                state["failed"] = True
                finish = ("exc", exc)
            else:
                for i, eid in zip(idxs, res):
                    ids[i] = eid
                state["remaining"] -= 1
                if state["remaining"] == 0:
                    finish = ("ok", None)
        if finish is None:
            return
        if parent.set_running_or_notify_cancel():
            if finish[0] == "exc":
                parent.set_exception(finish[1])
            else:
                parent.set_result(ids)

    for idxs, child in parts:
        child.add_done_callback(functools.partial(one_done, idxs))


class WriteBuffer:
    """Bounded group-commit buffer in front of an EventStore."""

    def __init__(self, store_fn: Optional[Callable] = None, *,
                 queue_max: int = 8192, flush_max: int = 256,
                 linger_s: float = 0.002, retries: int = 4,
                 backoff_s: float = 0.05, backoff_cap_s: float = 1.0,
                 flush_timeout_s: float = 30.0, partitions: int = 1,
                 registry=None):
        if store_fn is None:
            from predictionio_tpu.storage.registry import Storage

            store_fn = Storage.get_events
        self._store_fn = store_fn
        self.queue_max = max(1, queue_max)
        self.flush_max = max(1, flush_max)
        self.linger_s = max(0.0, linger_s)
        self.retries = max(0, retries)
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.flush_timeout_s = flush_timeout_s
        self.partitions = max(1, partitions)
        #: per-lane event bound — total capacity stays queue_max
        self.lane_queue_max = max(1, self.queue_max // self.partitions)

        self._lanes = [_Lane(i) for i in range(self.partitions)]
        self._stopping = False

        self._shed_total = self._retry_total = None
        self._flush_size = self._flush_duration = None
        self._p_flush_size = self._p_commit = None
        self._anatomy = None
        self._registry = registry
        if registry is not None:
            self._anatomy = anatomy_metrics(registry)
            registry.gauge_callback(
                "pio_ingest_queue_depth",
                "Events buffered for group commit (queued + in flush)",
                lambda: float(self.queue_depth()))
            registry.gauge_callback(
                "pio_ingest_partition_queue_depth",
                "Events buffered per commit lane (queued + in flush)",
                lambda: [({"partition": str(lane.index)}, float(lane.depth))
                         for lane in self._lanes],
                labelnames=("partition",))
            self._shed_total = registry.counter(
                "pio_ingest_shed_total",
                "Events rejected with 429 because the ingest queue was full")
            self._retry_total = registry.counter(
                "pio_ingest_retry_total",
                "Flush attempts retried after a storage fault or timeout")
            self._flush_size = registry.histogram(
                "pio_ingest_flush_size",
                "Events per group-commit flush",
                buckets=(1., 2., 4., 8., 16., 32., 64., 128., 256., 512.,
                         1024.))
            self._p_flush_size = registry.histogram(
                "pio_ingest_partition_flush_size",
                "Events per group-commit flush, by commit lane",
                labelnames=("partition",),
                buckets=(1., 2., 4., 8., 16., 32., 64., 128., 256., 512.,
                         1024.))
            self._flush_duration = registry.histogram(
                "pio_ingest_flush_duration_seconds",
                "Wall time of one group-commit flush (including retries)")
            self._p_commit = registry.histogram(
                "pio_ingest_partition_commit_seconds",
                "Durable commit wall time of one lane flush, by commit "
                "lane (the anatomy `commit` stage, partition-resolved)",
                labelnames=("partition",))

    # -- caller side ---------------------------------------------------------
    def queue_depth(self) -> int:
        return sum(lane.depth for lane in self._lanes)

    def _retry_after(self, lane: _Lane) -> int:
        est = (lane.depth / self.flush_max) * lane.last_flush_s
        return int(min(60, max(1, est + 0.999)))

    def submit(self, events: Sequence[Event], app_id: int,
               channel_id: Optional[int] = None
               ) -> "concurrent.futures.Future[List[str]]":
        """Queue events for group commit; returns a future of their ids.

        Ids are assigned HERE (idempotency token for the retrying flush).
        Raises :class:`BufferFull` instead of queueing past the target
        lane's bound. Multi-partition buffers route each event to its
        entity's lane; a submit that spans lanes reserves capacity on
        every target lane atomically (all queued or none) and returns a
        future that resolves when every part committed."""
        events = [e if e.event_id else _with_id(e) for e in events]
        if self.partitions == 1 or len(events) == 0:
            return self._submit_lane(self._lanes[0], events, app_id,
                                     channel_id)
        groups: dict = {}
        for i, e in enumerate(events):
            p = partition_of(app_id, channel_id, e.entity_id,
                             self.partitions)
            idxs, evs = groups.setdefault(p, ([], []))
            idxs.append(i)
            evs.append(e)
        if len(groups) == 1:
            ((p, (_, evs)),) = groups.items()
            return self._submit_lane(self._lanes[p], evs, app_id,
                                     channel_id)
        parent: concurrent.futures.Future = concurrent.futures.Future()
        parts = []
        # lanes locked in index order (consistent order -> no deadlock
        # against a concurrent spanning submit)
        lane_ids = sorted(groups)
        with contextlib.ExitStack() as stack:
            for p in lane_ids:
                stack.enter_context(self._lanes[p].cond)
            if self._stopping:
                raise StorageError("write buffer is shut down")
            for p in lane_ids:
                lane = self._lanes[p]
                if lane.depth + len(groups[p][1]) > self.lane_queue_max:
                    if self._shed_total is not None:
                        self._shed_total.inc(len(events))
                    raise BufferFull(lane.depth, self._retry_after(lane))
            for p in lane_ids:
                idxs, evs = groups[p]
                child: concurrent.futures.Future = \
                    concurrent.futures.Future()
                self._enqueue_locked(self._lanes[p], evs, app_id,
                                     channel_id, child)
                parts.append((idxs, child))
        _join_parts(parent, len(events), parts)
        return parent

    def _submit_lane(self, lane: _Lane, events, app_id, channel_id
                     ) -> "concurrent.futures.Future[List[str]]":
        future: concurrent.futures.Future = concurrent.futures.Future()
        with lane.cond:
            if self._stopping:
                raise StorageError("write buffer is shut down")
            if lane.depth + len(events) > self.lane_queue_max:
                if self._shed_total is not None:
                    self._shed_total.inc(len(events))
                raise BufferFull(lane.depth, self._retry_after(lane))
            self._enqueue_locked(lane, events, app_id, channel_id, future)
        return future

    def _enqueue_locked(self, lane: _Lane, events, app_id, channel_id,
                        future) -> None:
        """Append one pending submit to a lane. Caller holds lane.cond."""
        lane.queue.append(_Pending(events, app_id, channel_id, future,
                                   trace=capture_context(),
                                   t_submit=time.perf_counter(),
                                   req_trace=current_trace()))
        lane.depth += len(events)
        if lane.thread is None:
            lane.thread = threading.Thread(
                target=self._worker, args=(lane,), daemon=True,
                name=f"pio-ingest-writer-{lane.index}")
            lane.thread.start()
        lane.cond.notify()

    # -- writer side ---------------------------------------------------------
    def _worker(self, lane: _Lane) -> None:
        while True:
            with lane.cond:
                while not lane.queue and not self._stopping:
                    lane.cond.wait()
                if not lane.queue and self._stopping:
                    return
                batch = [lane.queue.popleft()]
                total = len(batch[0].events)
                # linger: hold the first events briefly so concurrent
                # submits coalesce — but never once the flush is full.
                # During a drain only the timed WAIT is skipped: already-
                # queued items must still coalesce, or a deep queue would
                # drain as per-request flushes and blow the stop timeout.
                deadline = time.monotonic() + self.linger_s
                while total < self.flush_max:
                    if lane.queue:
                        batch.append(lane.queue.popleft())
                        total += len(batch[-1].events)
                        continue
                    if self._stopping:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not lane.cond.wait(remaining):
                        break
            try:
                self._flush(lane, batch, total)
            finally:
                with lane.cond:
                    lane.depth -= total

    def _flush(self, lane: _Lane, batch: List[_Pending],
               total: int) -> None:
        """One group commit: per-(app, channel) insert_batch with retries."""
        t0 = time.monotonic()
        if self._flush_size is not None:
            self._flush_size.observe(total)
            self._p_flush_size.observe(total, partition=str(lane.index))
        groups: dict = {}
        for p in batch:
            groups.setdefault((p.app_id, p.channel_id), []).append(p)
        for (app_id, channel_id), pendings in groups.items():
            events = [e for p in pendings for e in p.events]
            t_flush_start = time.perf_counter()
            try:
                ids = self._flush_traced(events, app_id, channel_id,
                                         pendings)
            except Exception as e:  # noqa: BLE001 — fanned out to callers
                for p in pendings:
                    if not p.future.set_running_or_notify_cancel():
                        continue
                    p.future.set_exception(
                        e if isinstance(e, StorageError)
                        else StorageError(str(e)))
                continue
            commit_s = time.perf_counter() - t_flush_start
            if self._p_commit is not None:
                self._p_commit.observe(commit_s,
                                       partition=str(lane.index))
            if self._anatomy is not None and anatomy_enabled():
                try:
                    observe_ingest_batch(
                        self._anatomy,
                        [(p.t_submit, p.req_trace) for p in pendings],
                        t_flush_start,
                        commit_s)
                except Exception:
                    logger.exception("ingest anatomy observation failed")
            pos = 0
            for p in pendings:
                n = len(p.events)
                if p.future.set_running_or_notify_cancel():
                    p.future.set_result(list(ids[pos:pos + n]))
                pos += n
            # push the committed events to the in-process subscribers
            # (online fold-in): only AFTER the durable commit, so a tap
            # can never observe an event the store might still lose
            _notify_taps(events, app_id, channel_id)
        # feed THIS lane's Retry-After estimate with its observed flush
        # time — a slow partition backs off only its own writers
        lane.last_flush_s = max(0.001, time.monotonic() - t0)
        if self._flush_duration is not None:
            self._flush_duration.observe(time.monotonic() - t0)

    def _flush_traced(self, events, app_id, channel_id,
                      pendings: List[_Pending]) -> List[str]:
        """One group flush carried under the FIRST submitter's trace
        context (when any submitter had one): the writer-thread span is
        linked to the request that opened the batch — the coalesced
        siblings ride the same flush and are represented by the batch
        size attr — instead of the pre-PR behavior of an unattributed
        thread-local span."""
        ctx = next((p.trace for p in pendings if p.trace is not None), None)
        if ctx is None:
            return self._flush_group(events, app_id, channel_id)
        with carried(ctx, "ingest_flush", registry=self._registry,
                     attrs={"events": len(events),
                            "submits": len(pendings)}):
            with span("ingest_flush"):
                return self._flush_group(events, app_id, channel_id)

    def _flush_group(self, events, app_id, channel_id) -> List[str]:
        """insert_batch with bounded retries; attempts after the first go
        through insert_batch_idempotent so an ambiguous failure (backend
        committed, then the fault fired) cannot duplicate rows.

        The backoff arithmetic is the shared utils/retry policy; the
        loop itself stays bespoke because of the hung-flush adoption
        below (a still-running attempt makes a concurrent retry unsafe
        on scan-then-write backends — retry_call's abandon-and-retry
        timeout contract would be wrong here)."""
        policy = RetryPolicy(retries=self.retries, backoff_s=self.backoff_s,
                             backoff_cap_s=self.backoff_cap_s)
        last_err: Optional[Exception] = None
        for attempt in range(policy.attempts()):
            store = self._store_fn()
            fn = (store.insert_batch if attempt == 0
                  else store.insert_batch_idempotent)
            running = start_attempt_thread(
                fn, (events, app_id, channel_id), name="pio-ingest-flush")
            try:
                return running.result(timeout=self.flush_timeout_s)
            # running.done() distinguishes "our wait timed out" from "the
            # backend RAISED a timeout" — on 3.11+ futures.TimeoutError IS
            # builtin TimeoutError, so socket/fsspec timeouts land in this
            # except clause too and must take the plain retry path
            except concurrent.futures.TimeoutError as te:
                if running.done():
                    last_err = _as_storage_error(te)
                else:
                    # the attempt is STILL running — retrying concurrently
                    # could duplicate on backends whose idempotent insert
                    # is a non-atomic scan-then-write (parquet: the hung
                    # attempt's tmp file is invisible to the retry's id
                    # scan until its rename). Give it one grace period
                    # and adopt its outcome; a write that never resolves
                    # fails the batch WITHOUT a retry — the caller gets an
                    # error (no loss: nothing was acknowledged) instead of
                    # a possible double-write.
                    try:
                        return running.result(timeout=self.flush_timeout_s)
                    except concurrent.futures.TimeoutError as te2:
                        if not running.done():
                            raise StorageError(
                                f"flush hung past {2 * self.flush_timeout_s}"
                                "s; failing without retry (a concurrent "
                                "retry could duplicate events)") from None
                        last_err = _as_storage_error(te2)
                    except Exception as e:  # resolved clean failure: retry
                        last_err = _as_storage_error(e)
            except Exception as e:
                # retry ANY failure, not just StorageError: transient
                # backend faults surface as raw driver/filesystem errors
                # too (psycopg OperationalError, fsspec OSError) — the
                # idempotent retry path makes replaying them safe either
                # way. CrashError (BaseException) still bypasses.
                last_err = _as_storage_error(e)
            if attempt == self.retries:
                break
            if self._retry_total is not None:
                self._retry_total.inc()
            # exponential backoff with full jitter, capped (utils/retry)
            time.sleep(policy.delay_s(attempt))
        raise last_err  # type: ignore[misc]

    # -- lifecycle -----------------------------------------------------------
    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop the writers. ``drain=True`` flushes everything still queued
        first (the graceful-shutdown contract: accepted events are never
        dropped); ``drain=False`` fails pending futures immediately.
        Lanes drain in parallel; the timeout bounds the whole stop."""
        threads = []
        for lane in self._lanes:
            with lane.cond:
                self._stopping = True
                if not drain:
                    dropped, lane.queue = list(lane.queue), deque()
                    for p in dropped:
                        lane.depth -= len(p.events)
                        if p.future.set_running_or_notify_cancel():
                            p.future.set_exception(StorageError(
                                "write buffer stopped before flush"))
                threads.append(lane.thread)
                lane.cond.notify_all()
        deadline = time.monotonic() + timeout_s
        for thread in threads:
            if thread is None:
                continue
            thread.join(timeout=max(0.0, deadline - time.monotonic()))
            if thread.is_alive():
                logger.warning("ingest writer did not drain within %.1fs",
                               timeout_s)
