"""segment.io webhook connector.

Behavioral parity with the reference SegmentIOConnector
(data/.../webhooks/segmentio/SegmentIOConnector.scala:24-185): payload types
identify/track/alias/page/screen/group map to a user event named after the
type, entityId = user_id else anonymous_id, eventTime = timestamp, with
type-specific fields (plus optional context) folded into properties.
"""

from __future__ import annotations

from predictionio_tpu.data.webhooks import ConnectorError, WebhookConnector

_TYPE_PROPS = {
    "identify": lambda d: {"traits": d.get("traits")},
    "track": lambda d: {"properties": d.get("properties"),
                        "event": d.get("event")},
    "alias": lambda d: {"previous_id": d.get("previousId") or d.get("previous_id")},
    "screen": lambda d: {"name": d.get("name"),
                         "properties": d.get("properties")},
    "page": lambda d: {"name": d.get("name"),
                       "properties": d.get("properties")},
    "group": lambda d: {"group_id": d.get("groupId") or d.get("group_id"),
                        "traits": d.get("traits")},
}


class SegmentIOConnector(WebhookConnector):
    name = "segmentio"
    form_based = False

    def to_event_dict(self, payload: dict) -> dict:
        if "version" not in payload:
            raise ConnectorError("Failed to get segment.io API version.")
        ptype = payload.get("type")
        if ptype not in _TYPE_PROPS:
            raise ConnectorError(
                f"Cannot convert unknown type {ptype} to event JSON.")
        user_id = payload.get("userId") or payload.get("user_id") \
            or payload.get("anonymousId") or payload.get("anonymous_id")
        if not user_id:
            raise ConnectorError(
                "there was no `userId` or `anonymousId` in the common fields.")
        props = {k: v for k, v in _TYPE_PROPS[ptype](payload).items()
                 if v is not None}
        context = payload.get("context")
        if context is not None:
            props["context"] = context
        out = {
            "event": ptype,
            "entityType": "user",
            "entityId": str(user_id),
            "properties": props,
        }
        if payload.get("timestamp"):
            out["eventTime"] = payload["timestamp"]
        return out
