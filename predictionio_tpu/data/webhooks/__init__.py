"""Webhook connectors: third-party payloads -> Events.

Parity with the reference's webhooks package
(data/.../webhooks/{JsonConnector,FormConnector,ConnectorUtil}.scala and the
registry in data/.../api/WebhooksConnectors.scala:27-37). A connector turns
one provider-specific payload (JSON body or form fields) into an Event dict;
the event server validates and stores it.
"""

from __future__ import annotations

import abc
from typing import Dict, Optional

from predictionio_tpu.data.event import Event


class ConnectorError(Exception):
    """ConnectorException parity — payload cannot be converted."""


class WebhookConnector(abc.ABC):
    """Base connector; `form_based` selects form vs JSON body parsing."""

    name: str = ""
    form_based: bool = False

    @abc.abstractmethod
    def to_event_dict(self, payload: dict) -> dict:
        """Convert provider payload to an Event wire dict (may raise
        ConnectorError)."""

    def to_event(self, payload: dict) -> Event:
        return Event.from_dict(self.to_event_dict(payload))


_REGISTRY: Dict[str, WebhookConnector] = {}


def register_connector(connector: WebhookConnector) -> None:
    _REGISTRY[connector.name] = connector


def get_connector(name: str) -> Optional[WebhookConnector]:
    _ensure_builtin()
    return _REGISTRY.get(name)


_loaded = False


def _ensure_builtin() -> None:
    """Built-in connector registry (WebhooksConnectors.scala:27-37)."""
    global _loaded
    if _loaded:
        return
    from predictionio_tpu.data.webhooks import segmentio, mailchimp, example
    register_connector(segmentio.SegmentIOConnector())
    register_connector(mailchimp.MailChimpConnector())
    register_connector(example.ExampleJsonConnector())
    register_connector(example.ExampleFormConnector())
    _loaded = True
