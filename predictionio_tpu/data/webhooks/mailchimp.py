"""MailChimp webhook connector (form-encoded).

Behavioral parity with the reference MailChimpConnector
(data/.../webhooks/mailchimp/MailChimpConnector.scala:32-360): form payloads
of type subscribe/unsubscribe/profile/upemail/cleaned/campaign map to events;
`fired_at` ("yyyy-MM-dd HH:mm:ss", UTC) becomes eventTime; `data[...]`
bracket fields are unflattened into properties.
"""

from __future__ import annotations

import datetime as _dt
import re

from predictionio_tpu.data.event import UTC, format_event_time
from predictionio_tpu.data.webhooks import ConnectorError, WebhookConnector

_BRACKETS = re.compile(r"\[([^\]]*)\]")


def parse_mailchimp_time(s: str) -> str:
    try:
        t = _dt.datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=UTC)
    except ValueError as e:
        raise ConnectorError(f"cannot parse fired_at {s!r}: {e}") from e
    return format_event_time(t)


def _unflatten(data: dict) -> dict:
    """data[merges][FNAME]=x ... -> {"merges": {"FNAME": "x"}} nesting."""
    out: dict = {}
    for key, value in data.items():
        if not key.startswith("data["):
            continue
        path = _BRACKETS.findall(key)
        node = out
        for part in path[:-1]:
            node = node.setdefault(part, {})
        node[path[-1]] = value
    return out


def _require(data: dict, key: str) -> str:
    if key not in data:
        raise ConnectorError(f"The field '{key}' is required for MailChimp data.")
    return data[key]


class MailChimpConnector(WebhookConnector):
    name = "mailchimp"
    form_based = True

    #: type -> (event name, entity id field, target list entity?)
    _SHAPES = {
        "subscribe": ("subscribe", "data[id]", True),
        "unsubscribe": ("unsubscribe", "data[id]", True),
        "profile": ("profile", "data[id]", True),
        "upemail": ("upemail", "data[new_id]", True),
        "cleaned": ("cleaned", "data[list_id]", False),
        "campaign": ("campaign", "data[id]", True),
    }

    def to_event_dict(self, payload: dict) -> dict:
        ptype = payload.get("type")
        if ptype is None:
            raise ConnectorError("The field 'type' is required for MailChimp data.")
        if ptype not in self._SHAPES:
            raise ConnectorError(
                f"Cannot convert unknown MailChimp data type {ptype} to event JSON")
        event_name, id_field, has_list_target = self._SHAPES[ptype]
        event_time = parse_mailchimp_time(_require(payload, "fired_at"))
        props = _unflatten(payload)
        entity_id = _require(payload, id_field)
        # identity fields live at the event level, not in properties
        for consumed in ("id", "new_id") if ptype == "upemail" else ("id",):
            props.pop(consumed, None)
        out = {
            "event": event_name,
            "entityType": "list" if ptype == "cleaned" else
                          ("campaign" if ptype == "campaign" else "user"),
            "entityId": entity_id,
            "properties": props,
            "eventTime": event_time,
        }
        if has_list_target and ptype != "campaign" and "data[list_id]" in payload:
            out["targetEntityType"] = "list"
            out["targetEntityId"] = payload["data[list_id]"]
            props.pop("list_id", None)
        return out
