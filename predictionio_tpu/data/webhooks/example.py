"""Example connectors (reference webhooks/examplejson, webhooks/exampleform).

Payload shapes match the reference's documented examples
(data/.../webhooks/examplejson/ExampleJsonConnector.scala:25-95,
exampleform/ExampleFormConnector.scala:58-104): `userAction` and
`userActionItem` types mapping to user events with optional context/properties.
"""

from __future__ import annotations

from predictionio_tpu.data.webhooks import ConnectorError, WebhookConnector


def _user_action(data, getter):
    props = {}
    context = getter(data, "context")
    if context is not None:
        props["context"] = context
    for k in ("anotherProperty1", "anotherProperty2"):
        v = getter(data, k)
        if v is not None:
            props[k] = v
    out = {
        "event": data["event"],
        "entityType": "user",
        "entityId": data["userId"],
        "properties": props,
    }
    if data.get("timestamp"):
        out["eventTime"] = data["timestamp"]
    return out


def _user_action_item(data, getter):
    props = {}
    context = getter(data, "context")
    if context is not None:
        props["context"] = context
    for k in ("anotherPropertyA", "anotherPropertyB"):
        v = getter(data, k)
        if v is not None:
            props[k] = v
    out = {
        "event": data["event"],
        "entityType": "user",
        "entityId": data["userId"],
        "targetEntityType": "item",
        "targetEntityId": data["itemId"],
        "properties": props,
    }
    if data.get("timestamp"):
        out["eventTime"] = data["timestamp"]
    return out


class ExampleJsonConnector(WebhookConnector):
    name = "examplejson"
    form_based = False

    def to_event_dict(self, payload: dict) -> dict:
        ptype = payload.get("type")
        try:
            if ptype == "userAction":
                return _user_action(payload, lambda d, k: d.get(k))
            if ptype == "userActionItem":
                return _user_action_item(payload, lambda d, k: d.get(k))
        except KeyError as e:
            raise ConnectorError(
                f"Cannot convert {payload} to event JSON: missing {e}") from e
        raise ConnectorError(f"Cannot convert unknown type '{ptype}' to Event JSON.")


class ExampleFormConnector(WebhookConnector):
    name = "exampleform"
    form_based = True

    def to_event_dict(self, payload: dict) -> dict:
        import json

        def getter(d, k):
            v = d.get(k)
            if v is None:
                return None
            try:  # form values for context arrive as JSON strings
                return json.loads(v)
            except (json.JSONDecodeError, TypeError):
                return v

        ptype = payload.get("type")
        try:
            if ptype == "userAction":
                return _user_action(payload, getter)
            if ptype == "userActionItem":
                return _user_action_item(payload, getter)
        except KeyError as e:
            raise ConnectorError(
                f"Cannot convert {payload} to event JSON: missing {e}") from e
        raise ConnectorError(f"Cannot convert unknown type '{ptype}' to Event JSON.")
