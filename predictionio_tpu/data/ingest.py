"""Columnar training-ingest pipeline shared by every engine DataSource.

The event→tensor hot path of `pio train` / `pio eval`: one columnar scan
(`EventStoreClient.find_columnar` → pyarrow table), vectorized column
extraction and (user, item) aggregation on flat NumPy arrays, and
`assign_indices`-based id interning — no per-`Event` Python objects
anywhere between the store and the model tensors (the RDD-scan
bottleneck the reference pays per engine, DataSource.scala's
`PEventStore.find.map` chains).

Three concerns live here so the six engines share one implementation:

* **shard/snapshot protocol** — on a multi-process runtime a sharded
  scan partitions ONE collectively-agreed `read_snapshot()` window
  exactly like the reference's per-executor JdbcRDD slices
  (JDBCPEvents.scala:89-101); engines whose algorithms re-key rows to
  their owners (recommendation's distributed ALS) opt in with
  ``sharded=True``, everything else reads replicated.
* **scan cache** — keyed by the backend's ``snapshot_digest()`` so the
  repeated folds of `pio eval` (k-fold re-reads) and back-to-back
  `pio train` runs skip the rescan when the store hasn't changed.
  Disable with ``PIO_INGEST_CACHE=0``.
* **`pio_ingest_*` metrics** — rows scanned, rows/s, cache hit/miss
  counters on the process registry, plus ``ingest_scan`` /
  ``ingest_intern`` / ``ingest_assemble`` spans through the obs span
  histogram (OBSERVABILITY.md inventory).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional, Tuple

import numpy as np

from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.tracing import span

#: scans cached per process; small — each entry is one app's filtered
#: training read (the k-fold reuse window, not a general query cache)
_CACHE_MAX = 8

_scan_cache: dict = {}
_scan_lock = threading.Lock()


def clear_scan_cache() -> None:
    with _scan_lock:
        _scan_cache.clear()


def _cache_enabled() -> bool:
    return os.environ.get("PIO_INGEST_CACHE", "1") != "0"


def _registry() -> MetricsRegistry:
    return default_registry()


def _count_rows(app_name: str, n: int, seconds: float) -> None:
    reg = _registry()
    reg.counter("pio_ingest_rows_total",
                "Event rows delivered to training reads by the columnar "
                "ingest path", labelnames=("app",)).inc(n, app=app_name)
    if seconds > 0:
        reg.gauge("pio_ingest_rows_per_second",
                  "Throughput of the most recent columnar training scan",
                  labelnames=("app",)).set(n / seconds, app=app_name)


def _count_cache(app_name: str, hit: bool) -> None:
    name = ("pio_ingest_cache_hits_total" if hit
            else "pio_ingest_cache_misses_total")
    verb = "hits" if hit else "misses"
    _registry().counter(
        name, f"Ingest scan-cache {verb} (snapshot-digest keyed)",
        labelnames=("app",)).inc(app=app_name)


def _cache_get(app_name: str, key):
    """Lookup + hit/miss accounting — shared by both cache entry points
    (training_scan tables and aggregate_scan property dicts)."""
    with _scan_lock:
        hit = _scan_cache.get(key)
    _count_cache(app_name, hit is not None)
    return hit


def _cache_put(key, value) -> None:
    """Size-capped FIFO insert — shared eviction policy."""
    with _scan_lock:
        if len(_scan_cache) >= _CACHE_MAX and key not in _scan_cache:
            _scan_cache.pop(next(iter(_scan_cache)))
        _scan_cache[key] = value


@dataclasses.dataclass
class TrainingScan:
    """One columnar training read.

    ``table`` holds the EVENT_SCHEMA columns; ``shard`` is the partition
    tuple the scan used (None = unsharded); ``replicated`` is True when a
    multi-process run wanted shards but the backend cannot partition —
    every process then holds the FULL set and the caller must keep a
    disjoint slice (`local_slice`) before feeding a distributed build.
    """

    table: "object"
    shard: Optional[tuple] = None
    replicated: bool = False

    def local_slice(self, arrays: Tuple[np.ndarray, ...]
                    ) -> Tuple[np.ndarray, ...]:
        """Strided disjoint slice for the replicated-fallback case; the
        identity otherwise (sharded or single-process reads are already
        local)."""
        if not self.replicated:
            return arrays
        import jax

        p, np_ = jax.process_index(), jax.process_count()
        return tuple(a[p::np_] for a in arrays)


def training_scan(app_name: str, channel_name: Optional[str] = None, *,
                  sharded: bool = False, cache: bool = True,
                  **filters) -> TrainingScan:
    """The shared columnar training read: filtered, optionally sharded,
    snapshot-digest cached, instrumented.

    ``filters`` go straight to ``find_columnar`` (entity_type,
    event_names, target_entity_type, ...); ``ordered=False`` is applied
    unless the caller overrides it — training math is either
    permutation-invariant or re-sorts locally.

    ``sharded=True`` opts into the multi-process shard/snapshot protocol
    (the recommendation engine's distributed-ALS read): process 0
    captures ``read_snapshot()`` once, broadcasts it, and every process
    scans only its partition of that window. Engines whose algorithms do
    NOT exchange rows by owner must keep the default replicated read.

    On a partitioned event store (`PIO_INGEST_PARTITIONS`,
    storage/partitioned.py) both paths gain partition parallelism for
    free at the store layer: the unsharded scan fans per-partition
    reads across a thread pool and merges time-ordered, and the
    sharded read maps reader shards onto store partitions
    (`shard_partitions`) under a composite snapshot — a reshard
    between capture and read fails loudly instead of skewing.
    """
    from predictionio_tpu.data.eventstore import EventStoreClient

    filters.setdefault("ordered", False)
    shard = None
    replicated = False
    if sharded:
        import jax

        if jax.process_count() > 1:
            from predictionio_tpu.parallel.shuffle import allgather_object

            # ONE process captures the snapshot; everyone partitions the
            # SAME window — independently computed bounds skew under
            # concurrent ingest and the partitions gap/overlap
            snap = allgather_object(
                EventStoreClient.read_snapshot(app_name, channel_name)
                if jax.process_index() == 0 else None)[0]
            if snap is not None:
                shard = (jax.process_index(), jax.process_count(), snap)
            else:
                # backend cannot partition: full read on every process,
                # caller keeps a disjoint strided slice (local_slice)
                replicated = True

    key = None
    if cache and _cache_enabled():
        digest = EventStoreClient.snapshot_digest(app_name, channel_name)
        if digest is not None:
            key = (app_name, channel_name, digest,
                   shard[:2] if shard else None,
                   tuple(sorted(
                       (k, tuple(v) if isinstance(v, list) else v)
                       for k, v in filters.items())))
            hit = _cache_get(app_name, key)
            if hit is not None:
                return TrainingScan(table=hit, shard=shard,
                                    replicated=replicated)

    t0 = time.perf_counter()
    with span("ingest_scan", registry=_registry()):
        table = EventStoreClient.find_columnar(
            app_name=app_name, channel_name=channel_name, shard=shard,
            **filters)
    _count_rows(app_name, table.num_rows, time.perf_counter() - t0)
    if key is not None:
        _cache_put(key, table)
    return TrainingScan(table=table, shard=shard, replicated=replicated)


def aggregate_scan(app_name: str, entity_type: str,
                   channel_name: Optional[str] = None, *,
                   required=None, cache: bool = True):
    """Entity properties for training reads: the columnar
    ``aggregate_properties`` fold behind the same snapshot-digest cache
    and ``ingest_aggregate`` span as `training_scan`. Returns
    ``{entity_id: PropertyMap}`` (a fresh dict per call; the immutable
    PropertyMaps are shared with the cache)."""
    from predictionio_tpu.data.eventstore import EventStoreClient

    key = None
    if cache and _cache_enabled():
        digest = EventStoreClient.snapshot_digest(app_name, channel_name)
        if digest is not None:
            key = ("aggregate", app_name, channel_name, entity_type,
                   tuple(required) if required else None, digest)
            hit = _cache_get(app_name, key)
            if hit is not None:
                return dict(hit)
    with span("ingest_aggregate", registry=_registry()):
        out = EventStoreClient.aggregate_properties(
            app_name, entity_type, channel_name=channel_name,
            required=required)
    if key is not None:
        _cache_put(key, out)
        return dict(out)
    return out


def event_columns(table, *names) -> Tuple[np.ndarray, ...]:
    """Named EVENT_SCHEMA columns as NumPy arrays (object for strings,
    int64 for the *_ms times) — the zero-Event handoff from Arrow.

    String columns decode through `columnar.string_column`'s dictionary
    trick — O(distinct) Python-string churn instead of O(rows), which is
    the difference on id columns whose cardinality is thousands against
    millions of rows. Nulls decode to None (absent target ids)."""
    from predictionio_tpu.data.columnar import string_column

    out = []
    for name in names:
        if name.endswith("_ms"):
            out.append(np.asarray(
                table.column(name).to_numpy(zero_copy_only=False),
                dtype=np.int64))
            continue
        out.append(string_column(table, name))
    return tuple(out)


def intern_pairs(users: np.ndarray, items: np.ndarray):
    """Vectorized id interning for an interaction table: (user_vocab,
    user_codes, item_vocab, item_codes) via `assign_indices` — the BiMap
    build without per-row dict hits, under an ``ingest_intern`` span."""
    from predictionio_tpu.data.bimap import assign_indices

    with span("ingest_intern", registry=_registry()):
        user_vocab, user_codes = assign_indices(users)
        item_vocab, item_codes = assign_indices(items)
    return user_vocab, user_codes, item_vocab, item_codes


def pair_counts(users: np.ndarray, items: np.ndarray,
                weights: Optional[np.ndarray] = None):
    """Aggregate duplicate (user, item) rows: distinct pairs plus the sum
    of ``weights`` (default 1.0 each) per pair — the vectorized analog of
    the engines' ``counts[(u, i)] += w`` fold. Returns (users', items',
    sums) with first-occurrence order of pairs NOT preserved (sorted by
    interned codes); downstream factorization is permutation-invariant.
    """
    if len(users) == 0:
        return (np.empty(0, object), np.empty(0, object),
                np.empty(0, np.float32))
    with span("ingest_assemble", registry=_registry()):
        user_vocab, ucodes, item_vocab, icodes = (
            intern_pairs(users, items))
        combined = ucodes.astype(np.int64) * len(item_vocab) + icodes
        uniq, inv = np.unique(combined, return_inverse=True)
        w = (np.ones(len(users), np.float32) if weights is None
             else np.asarray(weights, np.float32))
        sums = np.bincount(inv, weights=w,
                           minlength=len(uniq)).astype(np.float32)
        u_out = user_vocab[(uniq // len(item_vocab)).astype(np.int64)]
        i_out = item_vocab[(uniq % len(item_vocab)).astype(np.int64)]
    return u_out, i_out, sums


def latest_per_pair(users: np.ndarray, items: np.ndarray,
                    times: np.ndarray, values: np.ndarray):
    """Latest-wins per (user, item) by event time — the vectorized analog
    of the like/dislike ``if e.t > latest[key].t`` fold, including its
    tie rule (equal timestamps keep the FIRST event in scan order; the
    descending position tiebreak below reproduces the strict ``>``).
    Returns (users', items', values') for the distinct pairs."""
    if len(users) == 0:
        return users, items, values
    with span("ingest_assemble", registry=_registry()):
        user_vocab, ucodes, item_vocab, icodes = (
            intern_pairs(users, items))
        combined = ucodes.astype(np.int64) * len(item_vocab) + icodes
        order = np.lexsort((np.arange(len(users))[::-1], times, combined))
        cs = combined[order]
        is_last = np.r_[cs[1:] != cs[:-1], True]
        winners = order[is_last]
    return users[winners], items[winners], values[winners]


def sessions_by_entity(users: np.ndarray, items: np.ndarray,
                       times: np.ndarray):
    """Group an interaction scan into per-user time-ordered item
    sequences: ONE lexsort + segment split instead of a per-event dict
    append — the sessionrec DataSource assembly. Returns sessions in
    sorted-user order (the row path's ``sorted(by_user)`` contract)."""
    if len(users) == 0:
        return []
    with span("ingest_assemble", registry=_registry()):
        from predictionio_tpu.data.bimap import assign_indices

        _, codes = assign_indices(users)
        order = np.lexsort((np.arange(len(users)), times, codes))
        codes_s = codes[order]
        items_s = items[order]
        starts = np.flatnonzero(np.r_[True, codes_s[1:] != codes_s[:-1]])
        bounds = np.r_[starts, len(codes_s)]
        return [items_s[bounds[i]:bounds[i + 1]].tolist()
                for i in range(len(starts))]
