"""Materialized event views: cached columnar snapshots of the event log.

Parity with the reference's view layer:
  * DataView.create (data/.../view/DataView.scala:36-108) — a DataFrame
    materialized to parquet, cache-keyed by a hash of the time range + a
    caller-supplied schema version so stale caches self-invalidate.
  * LBatchView / PBatchView (data/.../view/{L,P}BatchView.scala) — batch
    views exposing aggregateProperties and event-window slices.

The rebuild materializes one pyarrow Table per (app, channel, time-range,
version) to a parquet file under a cache dir. Training DataSources read the
view instead of re-querying the store; the table feeds the columnar →
device-array path (SURVEY.md §2.9 P2).
"""

from __future__ import annotations

import datetime as _dt
import hashlib
import logging
import os
import tempfile
from typing import Dict, Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from predictionio_tpu.data.aggregator import AGGREGATOR_EVENT_NAMES
from predictionio_tpu.data.columnar import events_to_table, table_to_events
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import millis
from predictionio_tpu.data.eventstore import EventStoreClient

logger = logging.getLogger("pio.view")

def default_cache_dir() -> str:
    return os.environ.get(
        "PIO_VIEW_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".pio_tpu", "views"))


def _cache_key(app_name: str, channel_name: Optional[str],
               start_time: Optional[_dt.datetime],
               until_time: Optional[_dt.datetime], version: str) -> str:
    """Deterministic cache id (DataView.scala:56 uses MurmurHash of the
    time-range + schema UID; any stable digest serves the same purpose)."""
    parts = [
        app_name, channel_name or "",
        str(millis(start_time)) if start_time else "-inf",
        str(millis(until_time)) if until_time else "+inf",
        version,
    ]
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


class DataView:
    """A cached columnar snapshot of one app/channel's events."""

    def __init__(self, app_name: str, channel_name: Optional[str] = None,
                 start_time: Optional[_dt.datetime] = None,
                 until_time: Optional[_dt.datetime] = None,
                 version: str = "0",
                 cache_dir: Optional[str] = None):
        self.app_name = app_name
        self.channel_name = channel_name
        self.start_time = start_time
        self.until_time = until_time
        self.version = version
        self.cache_dir = cache_dir or default_cache_dir()
        self._table: Optional[pa.Table] = None

    @property
    def cache_path(self) -> str:
        key = _cache_key(self.app_name, self.channel_name,
                         self.start_time, self.until_time, self.version)
        return os.path.join(self.cache_dir, f"view_{key}.parquet")

    def create(self, refresh: bool = False) -> pa.Table:
        """Materialize (or load the cached) snapshot (DataView.create:56)."""
        if self._table is not None and not refresh:
            return self._table
        path = self.cache_path
        if not refresh and os.path.exists(path):
            logger.info("view cache hit: %s", path)
            self._table = pq.read_table(path)
            return self._table
        events = EventStoreClient.find(
            self.app_name, self.channel_name,
            start_time=self.start_time, until_time=self.until_time)
        table = events_to_table(events)
        os.makedirs(self.cache_dir, exist_ok=True)
        # write-then-rename: a crash or concurrent writer never leaves a
        # truncated parquet at the cache path
        fd, tmp = tempfile.mkstemp(dir=self.cache_dir, suffix=".parquet.tmp")
        os.close(fd)
        try:
            pq.write_table(table, tmp)
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        logger.info("view materialized: %s (%d rows)", path, table.num_rows)
        self._table = table
        return table

    def invalidate(self) -> None:
        self._table = None
        try:
            os.remove(self.cache_path)
        except FileNotFoundError:
            pass


class BatchView(DataView):
    """Batch view with the L/PBatchView-style derived accessors."""

    def events(self):
        return table_to_events(self.create())

    def filtered_table(self, event_names: Optional[Sequence[str]] = None,
                       entity_type: Optional[str] = None) -> pa.Table:
        table = self.create()
        mask = None
        import pyarrow.compute as pc

        if event_names is not None:
            m = pc.is_in(table.column("event"),
                         value_set=pa.array(list(event_names)))
            mask = m if mask is None else pc.and_(mask, m)
        if entity_type is not None:
            m = pc.equal(table.column("entity_type"), entity_type)
            mask = m if mask is None else pc.and_(mask, m)
        return table.filter(mask) if mask is not None else table

    def aggregate_properties(self, entity_type: str) -> Dict[str, PropertyMap]:
        """$set/$unset/$delete fold over the snapshot (PBatchView
        aggregateProperties parity) via the vectorized columnar fold —
        the view already holds the arrow table, so no per-Event
        materialization (parity with the row fold is covered by the
        randomized equivalence suite in tests/test_ingest.py)."""
        from predictionio_tpu.data.columnar import aggregate_properties_table

        rows = self.filtered_table(event_names=AGGREGATOR_EVENT_NAMES,
                                   entity_type=entity_type)
        return aggregate_properties_table(rows)
