"""The universal event datum and its validation rules.

Behavioral parity with the reference's Event model
(data/.../storage/Event.scala:42-167): an event is
(event_id?, event, entity_type, entity_id, target_entity_type?,
target_entity_id?, properties, event_time, tags, pr_id?, creation_time),
with reserved `$set/$unset/$delete` special events and `pio_` name prefixes.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
from typing import Any, Mapping, Optional, Sequence

from predictionio_tpu.data.datamap import DataMap

UTC = _dt.timezone.utc

#: Reserved single-entity event names (Event.scala:83)
SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})

#: Built-in entity types allowed to use the reserved prefix (Event.scala:144)
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})

#: Built-in property names allowed to use the reserved prefix (currently empty)
BUILTIN_PROPERTIES: frozenset = frozenset()


class EventValidationError(ValueError):
    """An event violates the validation rules (Event.scala:112-141)."""


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(tz=UTC)


def is_reserved_prefix(name: str) -> bool:
    """True if the name starts with `$` or `pio_` (Event.scala:77)."""
    return name.startswith("$") or name.startswith("pio_")


def is_special_event(name: str) -> bool:
    return name in SPECIAL_EVENTS


@dataclasses.dataclass(frozen=True)
class Event:
    """One event in the Event Store (Event.scala:42-60)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: _dt.datetime = dataclasses.field(default_factory=_utcnow)
    tags: Sequence[str] = ()
    pr_id: Optional[str] = None
    creation_time: _dt.datetime = dataclasses.field(default_factory=_utcnow)
    event_id: Optional[str] = None

    def __post_init__(self):
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        for attr in ("event_time", "creation_time"):
            t = getattr(self, attr)
            if t.tzinfo is None:  # naive timestamps are taken as UTC
                object.__setattr__(self, attr, t.replace(tzinfo=UTC))
        object.__setattr__(self, "tags", tuple(self.tags))

    # -- JSON round-trip (wire format of the Event Server REST API) ---------
    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
            "properties": self.properties.fields,
            "eventTime": format_event_time(self.event_time),
        }
        if self.event_id is not None:
            d["eventId"] = self.event_id
        if self.target_entity_type is not None:
            d["targetEntityType"] = self.target_entity_type
        if self.target_entity_id is not None:
            d["targetEntityId"] = self.target_entity_id
        if self.tags:
            d["tags"] = list(self.tags)
        if self.pr_id is not None:
            d["prId"] = self.pr_id
        d["creationTime"] = format_event_time(self.creation_time)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Event":
        if not isinstance(d, Mapping):
            raise EventValidationError("event must be a JSON object")
        if "event" not in d:
            raise EventValidationError("field event is required")
        if "entityType" not in d:
            raise EventValidationError("field entityType is required")
        if "entityId" not in d:
            raise EventValidationError("field entityId is required")
        props = d.get("properties") or {}
        if not isinstance(props, Mapping):
            raise EventValidationError("properties must be a JSON object")
        return cls(
            event=_req_str(d, "event"),
            entity_type=_req_str(d, "entityType"),
            entity_id=_req_str(d, "entityId"),
            target_entity_type=_opt_str(d, "targetEntityType"),
            target_entity_id=_opt_str(d, "targetEntityId"),
            properties=DataMap(props),
            event_time=(parse_event_time(d["eventTime"])
                        if d.get("eventTime") is not None else _utcnow()),
            tags=tuple(d.get("tags") or ()),
            pr_id=_opt_str(d, "prId"),
            creation_time=(parse_event_time(d["creationTime"])
                           if d.get("creationTime") is not None else _utcnow()),
            event_id=_opt_str(d, "eventId"),
        )

    @classmethod
    def from_json(cls, s: str) -> "Event":
        return cls.from_dict(json.loads(s))


def _req_str(d: Mapping[str, Any], key: str) -> str:
    v = d[key]
    if not isinstance(v, str):
        raise EventValidationError(f"field {key} must be a string")
    return v


def _opt_str(d: Mapping[str, Any], key: str) -> Optional[str]:
    v = d.get(key)
    if v is None:
        return None
    if not isinstance(v, str):
        raise EventValidationError(f"field {key} must be a string")
    return v


def parse_event_time(s: str) -> _dt.datetime:
    """Parse ISO-8601 with timezone; naive times are UTC (Event.scala:73)."""
    if not isinstance(s, str):
        raise EventValidationError(f"eventTime must be an ISO-8601 string, got {s!r}")
    try:
        t = _dt.datetime.fromisoformat(s.replace("Z", "+00:00"))
    except ValueError as e:
        raise EventValidationError(f"cannot parse time {s!r}: {e}") from e
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    return t


def format_event_time(t: _dt.datetime) -> str:
    if t.tzinfo is None:
        t = t.replace(tzinfo=UTC)
    return t.isoformat(timespec="milliseconds")


def millis(t: _dt.datetime) -> int:
    """Epoch milliseconds — the aggregation/order key (joda getMillis parity)."""
    return int(t.timestamp() * 1000)


def validate_event(e: Event) -> None:
    """Validate an event, raising EventValidationError on any violation.

    Rule-for-rule parity with EventValidation.validate (Event.scala:112-141).
    """
    if not e.event:
        raise EventValidationError("event must not be empty.")
    if not e.entity_type:
        raise EventValidationError("entityType must not be empty string.")
    if not e.entity_id:
        raise EventValidationError("entityId must not be empty string.")
    if e.target_entity_type == "":
        raise EventValidationError("targetEntityType must not be empty string")
    if e.target_entity_id == "":
        raise EventValidationError("targetEntityId must not be empty string.")
    if (e.target_entity_type is None) != (e.target_entity_id is None):
        raise EventValidationError(
            "targetEntityType and targetEntityId must be specified together.")
    if e.event == "$unset" and e.properties.is_empty:
        raise EventValidationError("properties cannot be empty for $unset event")
    if is_reserved_prefix(e.event) and not is_special_event(e.event):
        raise EventValidationError(
            f"{e.event} is not a supported reserved event name.")
    if is_special_event(e.event) and e.target_entity_type is not None:
        raise EventValidationError(
            f"Reserved event {e.event} cannot have targetEntity")
    if is_reserved_prefix(e.entity_type) and e.entity_type not in BUILTIN_ENTITY_TYPES:
        raise EventValidationError(
            f"The entityType {e.entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.")
    if (e.target_entity_type is not None
            and is_reserved_prefix(e.target_entity_type)
            and e.target_entity_type not in BUILTIN_ENTITY_TYPES):
        raise EventValidationError(
            f"The targetEntityType {e.target_entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.")
    for k in e.properties.key_set():
        if is_reserved_prefix(k) and k not in BUILTIN_PROPERTIES:
            raise EventValidationError(
                f"The property {k} is not allowed. "
                "'pio_' is a reserved name prefix.")
