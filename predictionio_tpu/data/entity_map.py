"""EntityMap: entity data keyed through a BiMap id space.

Behavioral parity with the reference's EntityMap
(data/.../storage/EntityMap.scala): entity string ids get contiguous integer
indices (via BiMap) and each entity carries a data payload. The rebuild keeps
the payloads in insertion-order lists aligned with the index space so they can
be stacked into static-shape device arrays for the training path.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, Iterator, Mapping, Tuple, TypeVar

from predictionio_tpu.data.bimap import BiMap

T = TypeVar("T")
U = TypeVar("U")


class EntityMap(Generic[T]):
    """Immutable map entityId -> data with a contiguous int id space."""

    __slots__ = ("_data", "_id_map")

    def __init__(self, data: Mapping[str, T], id_map: "BiMap[str, int] | None" = None):
        self._data: Dict[str, T] = dict(data)
        if id_map is None:
            id_map = BiMap.string_int(self._data.keys())
        elif set(id_map) != set(self._data):
            raise ValueError(
                "id_map keys must exactly match data keys "
                f"({len(set(self._data) - set(id_map))} data-only, "
                f"{len(set(id_map) - set(self._data))} map-only)")
        self._id_map = id_map

    # -- entity data access -------------------------------------------------
    def __getitem__(self, entity_id: str) -> T:
        return self._data[entity_id]

    def get(self, entity_id: str, default=None):
        return self._data.get(entity_id, default)

    def __contains__(self, entity_id: str) -> bool:
        return entity_id in self._data

    def __len__(self) -> int:
        return len(self._data)

    def __iter__(self) -> Iterator[str]:
        return iter(self._data)

    def items(self):
        return self._data.items()

    # -- id space -----------------------------------------------------------
    @property
    def id_map(self) -> "BiMap[str, int]":
        return self._id_map

    def entity_int_id(self, entity_id: str) -> int:
        return self._id_map[entity_id]

    def entity_id_of(self, int_id: int) -> str:
        return self._id_map.inverse()[int_id]

    def data_by_int_id(self, int_id: int) -> T:
        return self._data[self.entity_id_of(int_id)]

    # -- columnar construction ----------------------------------------------
    @classmethod
    def from_columnar(cls, entity_ids, payloads) -> "EntityMap[T]":
        """Build from parallel (entity_id, payload) columns — the shape a
        columnar scan hands over. Later rows win on duplicate ids
        (dict-update semantics); the id space is the usual sorted
        `BiMap.string_int` assignment over the distinct ids."""
        return cls({str(e): p for e, p in zip(entity_ids, payloads)})

    # -- transforms ---------------------------------------------------------
    def map_values(self, fn: Callable[[T], U]) -> "EntityMap[U]":
        return EntityMap({k: fn(v) for k, v in self._data.items()},
                         self._id_map)

    def to_rows(self) -> Iterator[Tuple[str, int, T]]:
        """(entity_id, int_id, data) rows in int-id order — the stackable
        layout for building [n_entities, ...] device arrays."""
        inv = self._id_map.inverse()
        for i in range(len(self._id_map)):
            eid = inv[i]
            yield eid, i, self._data[eid]

    def __repr__(self) -> str:
        return f"EntityMap({len(self._data)} entities)"
