"""Folding `$set/$unset/$delete` events into per-entity PropertyMaps.

Behavioral parity with the reference's LEventAggregator
(data/.../storage/LEventAggregator.scala:32-148) and the monoid-based
PEventAggregator (PEventAggregator.scala:28-210). The semantics, per entity,
over events sorted by event time:

  * `$set`    — merge properties into the current map (later values win);
                (re)creates the entity if currently deleted/absent
  * `$unset`  — remove the named keys (no-op if entity currently absent)
  * `$delete` — drop the entity entirely (subsequent `$set` recreates it)
  * any other event — ignored for aggregation
  * first_updated / last_updated — min/max event time over the special events

Entities whose fold ends with no live map (never `$set`, or deleted last) are
excluded from the result.

This module provides the row-at-a-time fold used by the serving path; the
training path reaches the same semantics through the columnar event log
(predictionio_tpu.data.columnar).
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, Iterable, Optional

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event, millis

#: Event names that drive aggregation (LEventAggregator.scala:91)
AGGREGATOR_EVENT_NAMES = ("$set", "$unset", "$delete")


class _Fold:
    __slots__ = ("fields", "first", "last")

    def __init__(self):
        # fields is None <=> entity absent/deleted; {} is a live empty entity
        self.fields: Optional[dict] = None
        self.first: Optional[_dt.datetime] = None
        self.last: Optional[_dt.datetime] = None

    def step(self, e: Event) -> None:
        name = e.event
        if name not in ("$set", "$unset", "$delete"):
            return
        t = e.event_time
        self.first = t if self.first is None or t < self.first else self.first
        self.last = t if self.last is None or t > self.last else self.last
        if name == "$set":
            if self.fields is None:
                self.fields = dict(e.properties.fields)
            else:
                self.fields.update(e.properties.fields)
        elif name == "$unset":
            if self.fields is not None:
                for k in e.properties.key_set():
                    self.fields.pop(k, None)
        else:  # $delete
            self.fields = None

    def result(self) -> Optional[PropertyMap]:
        if self.fields is None:
            return None
        return PropertyMap(self.fields, self.first, self.last)


def aggregate_properties_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Fold one entity's events (sorted by time here) into a PropertyMap.

    Parity with LEventAggregator.aggregatePropertiesSingle
    (LEventAggregator.scala:66-89).
    """
    fold = _Fold()
    for e in sorted(events, key=lambda ev: millis(ev.event_time)):
        fold.step(e)
    return fold.result()


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Group events by entity_id and fold each group, keeping live entities.

    Parity with LEventAggregator.aggregateProperties
    (LEventAggregator.scala:42-62).
    """
    by_entity: Dict[str, list] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: Dict[str, PropertyMap] = {}
    for entity_id, evs in by_entity.items():
        pm = aggregate_properties_single(evs)
        if pm is not None:
            out[entity_id] = pm
    return out
