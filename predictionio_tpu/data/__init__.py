"""Event model and data-access layer (L2).

Rebuilds the reference's `data/` module (SURVEY.md section 2.2): the universal
`Event` datum, the schemaless `DataMap` property bag, `$set/$unset/$delete`
property aggregation, and bidirectional id maps for string->index assignment.
"""

from predictionio_tpu.data.datamap import DataMap, DataMapError, PropertyMap
from predictionio_tpu.data.event import Event, EventValidationError, validate_event
from predictionio_tpu.data.aggregator import aggregate_properties, aggregate_properties_single
from predictionio_tpu.data.columnar import aggregate_properties_table
from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.entity_map import EntityMap

__all__ = [
    "EntityMap",
    "DataMap",
    "DataMapError",
    "PropertyMap",
    "Event",
    "EventValidationError",
    "validate_event",
    "aggregate_properties",
    "aggregate_properties_single",
    "aggregate_properties_table",
    "BiMap",
]
