"""Bidirectional maps and vectorized string->index assignment.

Behavioral parity with the reference's BiMap
(data/.../storage/BiMap.scala:28-167). Where the reference builds id maps by
collecting an RDD to the driver (BiMap.scala:126-128), the rebuild assigns
contiguous indices with `np.unique` over columnar arrays — a vectorized,
deterministic (sorted-key) assignment that feeds static-shape device arrays.
"""

from __future__ import annotations

from typing import Dict, Generic, Iterable, Mapping, Sequence, Tuple, TypeVar

import numpy as np

K = TypeVar("K")
V = TypeVar("V")


class BiMap(Generic[K, V]):
    """Immutable bidirectional map; values must be unique (BiMap.scala:28)."""

    __slots__ = ("_forward", "_inverse")

    def __init__(self, forward: Mapping[K, V], _inverse: "BiMap | None" = None):
        self._forward = dict(forward)
        if _inverse is None:
            inv = {}
            for k, v in self._forward.items():
                if v in inv:
                    raise ValueError(f"BiMap values must be unique: duplicate {v!r}")
                inv[v] = k
            self._inverse = inv
        else:
            self._inverse = _inverse

    @property
    def forward(self) -> Dict[K, V]:
        return dict(self._forward)

    def inverse(self) -> "BiMap[V, K]":
        out = BiMap.__new__(BiMap)
        out._forward = self._inverse
        out._inverse = self._forward
        return out

    def __getitem__(self, key: K) -> V:
        return self._forward[key]

    def get(self, key: K, default=None):
        return self._forward.get(key, default)

    def get_opt(self, key: K):
        return self._forward.get(key)

    def contains(self, key: K) -> bool:
        return key in self._forward

    __contains__ = contains

    def __len__(self) -> int:
        return len(self._forward)

    def __iter__(self):
        return iter(self._forward)

    def items(self):
        return self._forward.items()

    def take(self, n: int) -> "BiMap[K, V]":
        sub = dict(list(self._forward.items())[:n])
        return BiMap(sub)

    def to_map(self) -> Dict[K, V]:
        return dict(self._forward)

    def __eq__(self, other) -> bool:
        return isinstance(other, BiMap) and self._forward == other._forward

    def __repr__(self) -> str:
        return f"BiMap({self._forward!r})"

    # -- id assignment (BiMap.stringInt/stringLong parity, vectorized) ------
    @classmethod
    def string_int(cls, keys: Iterable[str]) -> "BiMap[str, int]":
        """Assign contiguous ints [0, n) to distinct keys, sorted for determinism."""
        uniq = np.unique(np.asarray(list(keys), dtype=object))
        return cls({str(k): i for i, k in enumerate(uniq)})

    string_long = string_int  # Python ints are unbounded

    @classmethod
    def string_double(cls, keys: Iterable[str]) -> "BiMap[str, float]":
        uniq = np.unique(np.asarray(list(keys), dtype=object))
        return cls({str(k): float(i) for i, k in enumerate(uniq)})


def batch_lookup(vocab: np.ndarray, values) -> np.ndarray:
    """Vectorized `vocab_index` for whole columns: int32 codes into the
    sorted `vocab`, with -1 for values not present.

    One searchsorted over the batch replaces a per-row dict hit (or a
    per-row `vocab_index` binary search) — the intern step of the
    columnar training path, used wherever a DataSource joins event
    columns against an id space (known-user filters, item-metadata
    joins).
    """
    arr = np.asarray(values, dtype=object)
    if arr.size == 0 or len(vocab) == 0:
        return np.full(arr.size, -1, np.int32)
    idx = np.searchsorted(vocab, arr)
    idx_c = np.minimum(idx, len(vocab) - 1)
    hit = vocab[idx_c] == arr
    return np.where(hit, idx_c, -1).astype(np.int32)


def vocab_index(vocab: np.ndarray, key: str) -> "int | None":
    """Index of `key` in a sorted vocab array (binary search), else None.

    The shared lookup for every model's user/item id maps (the inverse
    direction of assign_indices).
    """
    i = int(np.searchsorted(vocab, key))
    if i < len(vocab) and vocab[i] == key:
        return i
    return None


def _assign_indices_u64(arr: np.ndarray):
    """Fast path for short ASCII ids (<= 8 chars, the ML-20M shape):
    null-padded bytes viewed as BIG-endian uint64 compare exactly like
    the strings (lexicographic bytes == unicode order for ASCII, and the
    null padding ranks shorter prefixes first), so the whole distinct +
    sort pipeline runs on machine integers — ~5x faster than string
    factorize at 20M ids. Returns None when the precondition fails
    (long or non-ASCII ids) and the caller falls through."""
    if arr.dtype.kind != "U" or arr.dtype.itemsize > 32 or arr.size == 0:
        return None
    n_chars = arr.dtype.itemsize // 4
    # numpy unicode is UTF-32: view the raw codepoints with zero copies
    cps = np.ascontiguousarray(arr).view(np.uint32).reshape(-1, n_chars)
    if cps.max(initial=0) > 127:
        return None                     # non-ASCII: byte order != str order
    # pack the (null-padded) codepoint bytes big-endian so integer
    # comparison == lexicographic string comparison
    packed = np.zeros((len(arr), 8), np.uint8)
    packed[:, :n_chars] = cps.astype(np.uint8)
    ints = packed.view(">u8").reshape(-1).astype(np.uint64)  # zero-copy view
    try:
        import pandas as pd

        raw, uniq = pd.factorize(ints.view(np.int64), sort=False)
        uniq = uniq.view(np.uint64)
        order = np.argsort(uniq)        # sort only the DISTINCT ints
        rank = np.empty(len(order), np.int32)
        rank[order] = np.arange(len(order), dtype=np.int32)
        codes, uniq_int = rank[raw], uniq[order]
    except ImportError:
        uniq_int, codes = np.unique(ints, return_inverse=True)
        codes = codes.astype(np.int32)
    # rebuild the vocab strings from the sorted distinct ints (small)
    ub = uniq_int.astype(">u8").view(np.uint8).reshape(-1, 8)[:, :n_chars]
    vocab = np.ascontiguousarray(
        ub.astype(np.uint32)).view(arr.dtype).reshape(-1)
    return vocab, codes


def assign_indices(values: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized distinct-id assignment for the training path.

    Returns (vocab, codes): `vocab` is the sorted array of distinct strings
    and `codes[i]` the index of `values[i]` in `vocab`. This replaces the
    reference's collect-to-driver BiMap build (BiMap.scala:126-128) and is
    the scalable path for 20M-rating id spaces (SURVEY.md section 7 hard
    parts): hash-based pandas.factorize over the big array (O(n), no 20M
    string sort) + a sort of only the DISTINCT values to keep the sorted-
    vocab contract `vocab_index` relies on; numpy fallback otherwise.
    """
    arr = np.asarray(values)
    fast = _assign_indices_u64(arr)
    if fast is not None:
        return fast
    try:
        import pandas as pd
    except ImportError:
        vocab, codes = np.unique(arr, return_inverse=True)
        return vocab, codes.astype(np.int32)
    raw_codes, uniques = pd.factorize(arr, sort=False)
    if len(raw_codes) and raw_codes.min() < 0:
        # factorize's NA sentinel is -1; rank[-1] would silently alias a
        # null id onto a REAL vocab entry (the numpy path raises too)
        raise ValueError("null/NaN id in values — every entity id must "
                         "be a concrete string")
    uniques = np.asarray(uniques)
    order = np.argsort(uniques, kind="stable")   # distinct values only
    rank = np.empty(len(order), np.int32)
    rank[order] = np.arange(len(order), dtype=np.int32)
    return uniques[order], rank[raw_codes]
