"""Columnar event representation — the training-path data format.

The reference's parallel read path returns RDD[Event]
(data/.../storage/PEvents.scala:38-189). The TPU-native equivalent is a
pyarrow Table: one columnar batch the host can filter/aggregate vectorized and
convert to static-shape numpy/jax arrays feeding the device loader
(SURVEY.md section 2.9 P2).
"""

from __future__ import annotations

import json
from typing import Iterable, Iterator

import numpy as np
import pyarrow as pa

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import UTC, Event, millis

EVENT_SCHEMA = pa.schema([
    ("event_id", pa.string()),
    ("event", pa.string()),
    ("entity_type", pa.string()),
    ("entity_id", pa.string()),
    ("target_entity_type", pa.string()),
    ("target_entity_id", pa.string()),
    ("properties", pa.string()),   # JSON; parsed lazily
    ("event_time_ms", pa.int64()),
    ("creation_time_ms", pa.int64()),
])


def rows_to_event_table(rows) -> pa.Table:
    """SQL result rows (9 columns in EVENT_SCHEMA order: id, event,
    entityType, entityId, targetEntityType, targetEntityId, properties,
    eventTime, creationTime) -> the shared columnar layout. One builder
    for every SQL backend's `find_columnar` so the schema can never
    drift between them."""
    if not rows:
        return pa.table({n: [] for n in EVENT_SCHEMA.names},
                        schema=EVENT_SCHEMA)
    c = list(zip(*rows))
    return pa.table({
        "event_id": c[0], "event": c[1], "entity_type": c[2],
        "entity_id": c[3], "target_entity_type": c[4],
        "target_entity_id": c[5],
        "properties": [p if p else None for p in c[6]],
        "event_time_ms": c[7], "creation_time_ms": c[8],
    }, schema=EVENT_SCHEMA)


def events_to_table(events: Iterable[Event]) -> pa.Table:
    cols = {name: [] for name in EVENT_SCHEMA.names}
    for e in events:
        cols["event_id"].append(e.event_id)
        cols["event"].append(e.event)
        cols["entity_type"].append(e.entity_type)
        cols["entity_id"].append(e.entity_id)
        cols["target_entity_type"].append(e.target_entity_type)
        cols["target_entity_id"].append(e.target_entity_id)
        cols["properties"].append(
            None if e.properties.is_empty else e.properties.to_json())
        cols["event_time_ms"].append(millis(e.event_time))
        cols["creation_time_ms"].append(millis(e.creation_time))
    return pa.table(cols, schema=EVENT_SCHEMA)


def table_to_events(table: pa.Table) -> Iterator[Event]:
    import datetime as dt

    for row in table.to_pylist():
        yield Event(
            event_id=row["event_id"],
            event=row["event"],
            entity_type=row["entity_type"],
            entity_id=row["entity_id"],
            target_entity_type=row["target_entity_type"],
            target_entity_id=row["target_entity_id"],
            properties=(DataMap(json.loads(row["properties"]))
                        if row["properties"] else DataMap()),
            event_time=dt.datetime.fromtimestamp(row["event_time_ms"] / 1000, tz=UTC),
            creation_time=dt.datetime.fromtimestamp(
                row["creation_time_ms"] / 1000, tz=UTC),
        )


def property_column(table: pa.Table, key: str, dtype=np.float32) -> np.ndarray:
    """Extract one numeric property from the JSON properties column."""
    out = np.empty(table.num_rows, dtype=dtype)
    props = table.column("properties").to_pylist()
    for i, p in enumerate(props):
        if p is None:
            out[i] = np.nan
        else:
            out[i] = json.loads(p).get(key, np.nan)
    return out


def ratings_arrays(table: pa.Table, rating_key: str = "rating",
                   default_rating: float = 1.0):
    """(user_ids, item_ids, ratings) numpy views of an interaction table.

    user = entity_id, item = target_entity_id; rows without a target are
    dropped. Missing rating properties get `default_rating` (implicit
    feedback events like view/like/buy).
    """
    targets = np.asarray(table.column("target_entity_id").to_pylist(), dtype=object)
    mask = np.array([t is not None for t in targets], dtype=bool)
    users = np.asarray(table.column("entity_id").to_pylist(), dtype=object)[mask]
    items = targets[mask]
    ratings = property_column(table, rating_key)[mask]
    ratings = np.where(np.isnan(ratings), default_rating, ratings)
    return users, items, ratings.astype(np.float32)
