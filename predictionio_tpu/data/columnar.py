"""Columnar event representation — the training-path data format.

The reference's parallel read path returns RDD[Event]
(data/.../storage/PEvents.scala:38-189). The TPU-native equivalent is a
pyarrow Table: one columnar batch the host can filter/aggregate vectorized and
convert to static-shape numpy/jax arrays feeding the device loader
(SURVEY.md section 2.9 P2).
"""

from __future__ import annotations

import json
from typing import Iterable, Iterator

import numpy as np
import pyarrow as pa

from predictionio_tpu.data.aggregator import AGGREGATOR_EVENT_NAMES
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import UTC, Event, millis

EVENT_SCHEMA = pa.schema([
    ("event_id", pa.string()),
    ("event", pa.string()),
    ("entity_type", pa.string()),
    ("entity_id", pa.string()),
    ("target_entity_type", pa.string()),
    ("target_entity_id", pa.string()),
    ("properties", pa.string()),   # JSON; parsed lazily
    ("event_time_ms", pa.int64()),
    ("creation_time_ms", pa.int64()),
])


#: EVENT_SCHEMA name -> the SQL backends' physical column (shared by
#: sqlite/postgres `find_columnar` so projections cannot drift)
SQL_COLUMN_OF = {
    "event_id": "id", "event": "event", "entity_type": "entityType",
    "entity_id": "entityId", "target_entity_type": "targetEntityType",
    "target_entity_id": "targetEntityId", "properties": "properties",
    "event_time_ms": "eventTime", "creation_time_ms": "creationTime",
}


def projected_schema(names=None) -> pa.Schema:
    """EVENT_SCHEMA restricted to `names` (order preserved); the full
    schema when None. Unknown names raise KeyError early."""
    if names is None:
        return EVENT_SCHEMA
    return pa.schema([EVENT_SCHEMA.field(n) for n in names])


def rows_to_event_table(rows, names=None) -> pa.Table:
    """SQL result rows -> the shared columnar layout. `names` is the
    projection the rows were SELECTed with (EVENT_SCHEMA order: id,
    event, entityType, entityId, targetEntityType, targetEntityId,
    properties, eventTime, creationTime), defaulting to all nine. One
    builder for every SQL backend's `find_columnar` so the schema can
    never drift between them."""
    schema = projected_schema(names)
    if not rows:
        return pa.table({n: [] for n in schema.names}, schema=schema)
    c = list(zip(*rows))
    data = {n: c[i] for i, n in enumerate(schema.names)}
    if "properties" in data:
        data["properties"] = [p if p else None for p in data["properties"]]
    return pa.table(data, schema=schema)


def events_to_table(events: Iterable[Event]) -> pa.Table:
    cols = {name: [] for name in EVENT_SCHEMA.names}
    for e in events:
        cols["event_id"].append(e.event_id)
        cols["event"].append(e.event)
        cols["entity_type"].append(e.entity_type)
        cols["entity_id"].append(e.entity_id)
        cols["target_entity_type"].append(e.target_entity_type)
        cols["target_entity_id"].append(e.target_entity_id)
        cols["properties"].append(
            None if e.properties.is_empty else e.properties.to_json())
        cols["event_time_ms"].append(millis(e.event_time))
        cols["creation_time_ms"].append(millis(e.creation_time))
    return pa.table(cols, schema=EVENT_SCHEMA)


def table_to_events(table: pa.Table) -> Iterator[Event]:
    import datetime as dt

    for row in table.to_pylist():
        yield Event(
            event_id=row["event_id"],
            event=row["event"],
            entity_type=row["entity_type"],
            entity_id=row["entity_id"],
            target_entity_type=row["target_entity_type"],
            target_entity_id=row["target_entity_id"],
            properties=(DataMap(json.loads(row["properties"]))
                        if row["properties"] else DataMap()),
            event_time=dt.datetime.fromtimestamp(row["event_time_ms"] / 1000, tz=UTC),
            creation_time=dt.datetime.fromtimestamp(
                row["creation_time_ms"] / 1000, tz=UTC),
        )


def string_column(table: pa.Table, name: str) -> np.ndarray:
    """One string column as a NumPy object array, decoded through Arrow's
    hash-based dictionary encode: one Python string per DISTINCT value,
    then a vectorized ``vocab[codes]`` gather of shared references —
    O(distinct) object churn instead of O(rows). Nulls decode to None."""
    import pyarrow.compute as pc

    if table.num_rows == 0:
        return np.empty(0, dtype=object)
    enc = table.column(name).combine_chunks().dictionary_encode()
    vocab = np.asarray(enc.dictionary.to_pylist() + [None], dtype=object)
    idx = np.asarray(
        pc.fill_null(enc.indices, len(vocab) - 1)
        .to_numpy(zero_copy_only=False), dtype=np.int64)
    return vocab[idx]


def aggregate_properties_table(table: pa.Table, required=None):
    """Vectorized `$set/$unset/$delete` fold over a columnar event scan.

    Same semantics as the per-event fold (data/aggregator.py, the
    LEventAggregator parity contract) but computed with sort + last-wins
    segment ops on flat arrays instead of materializing an Event object
    per row:

      1. one stable lexsort puts every entity's special events in time
         order (ties keep scan order, like the row fold's stable sort);
      2. `$delete` precedence is a per-entity max-scan: rows at or before
         the segment's LAST delete can never contribute fields;
      3. field resolution is last-wins per (entity, key): flatten the
         surviving rows' parsed keys, lexsort by (entity, key, position),
         keep each group's final op, and keep the key iff that op is a
         `$set`;
      4. first/last updated are the segment's time extrema over ALL
         special rows (pre-delete rows still advance the clock, matching
         `_Fold.step`).

    Only `json.loads` per surviving row and the final per-entity dict
    assembly stay on the Python side; everything positional is NumPy.
    Returns ``{entity_id: PropertyMap}`` with UTC times (datetime
    equality is instant-based, so this matches the row path's
    zone-restoring reads).

    `required` filters the result to entities carrying every named field
    (PEventStore.aggregateProperties `required` parity).
    """
    import datetime as dt

    from predictionio_tpu.data.bimap import assign_indices
    from predictionio_tpu.data.datamap import PropertyMap

    if table.num_rows == 0:
        return {}
    events = string_column(table, "event")
    special = np.isin(events, np.asarray(AGGREGATOR_EVENT_NAMES, dtype=object))
    if not special.all():
        table = table.filter(pa.array(special))
        if table.num_rows == 0:
            return {}
        events = events[special]
    entity_ids = string_column(table, "entity_id")
    times = np.asarray(
        table.column("event_time_ms").to_numpy(zero_copy_only=False),
        dtype=np.int64)
    props = table.column("properties").to_pylist()

    vocab, codes = assign_indices(entity_ids)
    n = len(codes)
    # stable (entity, time) order; the trailing arange keeps scan order
    # for equal timestamps (sorted() stability in the row fold)
    order = np.lexsort((np.arange(n), times, codes))
    codes_s, times_s = codes[order], times[order]
    events_s = events[order]

    starts = np.flatnonzero(np.r_[True, codes_s[1:] != codes_s[:-1]])
    seg_of = np.repeat(np.arange(len(starts)),
                       np.diff(np.r_[starts, n]))
    seg_entity = vocab[codes_s[starts]]

    # time extrema per segment (sorted by time -> first/last element)
    first_ms = times_s[starts]
    last_ms = times_s[np.r_[starts[1:] - 1, n - 1]]

    # rows at or before each segment's last $delete are dead
    pos = np.arange(n)
    is_delete = events_s == "$delete"
    last_delete = np.maximum.reduceat(
        np.where(is_delete, pos, -1), starts)
    alive = pos > last_delete[seg_of]

    is_set = events_s == "$set"
    live_seg = np.zeros(len(starts), dtype=bool)
    live_seg[seg_of[alive & is_set]] = True

    # flatten surviving rows into (segment, key, position, is_set, value)
    surv = np.flatnonzero(alive & (is_set | (events_s == "$unset")))
    f_seg, f_key, f_pos, f_set, f_val = [], [], [], [], []
    props_s_idx = order[surv]          # original row ids of survivors
    for p_i, s_i in zip(props_s_idx, surv):
        raw = props[p_i]
        fields = json.loads(raw) if raw else {}
        seg = seg_of[s_i]
        setop = bool(is_set[s_i])
        for k, v in fields.items():
            f_seg.append(seg)
            f_key.append(k)
            f_pos.append(s_i)
            f_set.append(setop)
            f_val.append(v)

    out_fields = {int(s): {} for s in np.flatnonzero(live_seg)}
    if f_seg:
        f_seg = np.asarray(f_seg, dtype=np.int64)
        f_pos = np.asarray(f_pos, dtype=np.int64)
        f_set = np.asarray(f_set, dtype=bool)
        _, key_codes = assign_indices(np.asarray(f_key, dtype=object))
        # last-wins per (segment, key): sort and keep each group's tail
        forder = np.lexsort((f_pos, key_codes, f_seg))
        gs, gk = f_seg[forder], key_codes[forder]
        is_last = np.r_[(gs[1:] != gs[:-1]) | (gk[1:] != gk[:-1]), True]
        winners = forder[is_last]
        for w in winners[f_set[winners]]:
            seg = int(f_seg[w])
            if seg in out_fields:
                out_fields[seg][f_key[w]] = f_val[w]

    def _dt(ms: int) -> dt.datetime:
        return dt.datetime.fromtimestamp(ms / 1000, tz=UTC)

    req = list(required) if required else None
    out = {}
    for seg, fields in out_fields.items():
        if req and not all(r in fields for r in req):
            continue
        out[str(seg_entity[seg])] = PropertyMap(
            fields, _dt(int(first_ms[seg])), _dt(int(last_ms[seg])))
    return out


def property_column(table: pa.Table, key: str, dtype=np.float32) -> np.ndarray:
    """Extract one numeric property from the JSON properties column.

    Dictionary-encodes the column first and parses each DISTINCT JSON
    string once: property payloads on interaction events are drawn from a
    tiny value set (ratings 1-5, weights), so a million-row scan costs a
    handful of `json.loads` plus one vectorized gather."""
    import pyarrow.compute as pc

    n = table.num_rows
    if n == 0:
        return np.empty(0, dtype=dtype)
    enc = table.column("properties").combine_chunks().dictionary_encode()
    vocab = enc.dictionary.to_pylist()
    parsed = np.asarray(
        [np.nan if p is None else json.loads(p).get(key, np.nan)
         for p in vocab], dtype=dtype)
    codes = enc.indices
    null_mask = np.asarray(pc.is_null(codes).to_numpy(zero_copy_only=False))
    idx = np.asarray(pc.fill_null(codes, 0).to_numpy(zero_copy_only=False),
                     dtype=np.int64)
    out = parsed[idx]
    out[null_mask] = np.nan
    return out


#: columnar batch-scoring I/O (workflow/batch_predict.py): queries in, one
#: row per query. Two accepted input layouts — a single ``query`` column of
#: JSON-encoded objects (the JSON-lines file, columnized), or one column
#: per query FIELD (the natural parquet idiom; null cells are absent keys).
QUERIES_SCHEMA = pa.schema([("query", pa.string())])

#: batch-predict columnar output: the same self-descriptive
#: {query, prediction} pair as the JSON-lines format, one row per query,
#: both sides canonical JSON (sort_keys) so outputs diff cleanly
PREDICTIONS_SCHEMA = pa.schema([
    ("query", pa.string()),
    ("prediction", pa.string()),
])


def predictions_schema(prediction_type: "pa.DataType" = None) -> pa.Schema:
    """The batch-predict parquet output schema. With a `prediction_type`
    (an engine's ``Algorithm.columnar_wire_type()``) the prediction
    column is STRUCTURED — real arrow columns downstream can project,
    not JSON strings they must re-parse; without one it falls back to
    the generic JSON-string layout (PREDICTIONS_SCHEMA)."""
    if prediction_type is None:
        return PREDICTIONS_SCHEMA
    return pa.schema([("query", pa.string()),
                      ("prediction", prediction_type)])


def query_table_rows(table: pa.Table):
    """Decode a columnar query table into per-row raw values for the
    batch-predict reader: a list whose entries are JSON strings (the
    ``query``-column layout — parsed downstream so a malformed cell
    becomes a per-row error record, not an abort) or plain dicts (the
    field-per-column layout, nulls dropped)."""
    if "query" in table.column_names:
        return table.column("query").to_pylist()
    rows = table.to_pylist()
    return [{k: v for k, v in row.items() if v is not None} for row in rows]


def queries_to_table(queries) -> pa.Table:
    """JSON-encodable query objects -> the ``query``-column layout
    (canonical sort_keys encoding)."""
    return pa.table(
        {"query": [json.dumps(q, sort_keys=True) for q in queries]},
        schema=QUERIES_SCHEMA)


def ratings_arrays(table: pa.Table, rating_key: str = "rating",
                   default_rating: float = 1.0):
    """(user_ids, item_ids, ratings) numpy views of an interaction table.

    user = entity_id, item = target_entity_id; rows without a target are
    dropped. Missing rating properties get `default_rating` (implicit
    feedback events like view/like/buy).
    """
    targets = np.asarray(table.column("target_entity_id").to_pylist(), dtype=object)
    mask = np.array([t is not None for t in targets], dtype=bool)
    users = np.asarray(table.column("entity_id").to_pylist(), dtype=object)[mask]
    items = targets[mask]
    ratings = property_column(table, rating_key)[mask]
    ratings = np.where(np.isnan(ratings), default_rating, ratings)
    return users, items, ratings.astype(np.float32)
