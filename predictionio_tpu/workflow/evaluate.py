"""The evaluation workflow: run a sweep, record EvaluationInstance.

Parity with CoreWorkflow.runEvaluation (core/.../workflow/CoreWorkflow.scala:104-165)
and EvaluationWorkflow.scala:32-45: insert EvaluationInstance, run the
evaluation (MetricEvaluator over the params list), store results in oneliner /
HTML / JSON forms, mark EVALCOMPLETED.
"""

from __future__ import annotations

import datetime as _dt
import logging
from typing import Optional, Sequence

from predictionio_tpu.core.evaluation import Evaluation, MetricEvaluatorResult
from predictionio_tpu.core.params import EngineParams
from predictionio_tpu.data.event import UTC
from predictionio_tpu.storage.base import EvaluationInstance
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.workflow.context import WorkflowContext, WorkflowParams
from predictionio_tpu.workflow.instrument import workflow_run_metrics

logger = logging.getLogger("pio.workflow")


def run_evaluation(evaluation: Evaluation,
                   engine_params_list: Sequence[EngineParams],
                   evaluation_class: str = "",
                   params_generator_class: str = "",
                   workflow_params: Optional[WorkflowParams] = None,
                   ctx: Optional[WorkflowContext] = None
                   ) -> MetricEvaluatorResult:
    wp = workflow_params or WorkflowParams()
    ctx = ctx or WorkflowContext.create(
        mode="Evaluation", batch=wp.batch, workflow_params=wp)

    instances = Storage.get_meta_data_evaluation_instances()
    instance = EvaluationInstance(
        status="INIT",
        start_time=_dt.datetime.now(tz=UTC),
        evaluation_class=evaluation_class or type(evaluation).__name__,
        engine_params_generator_class=params_generator_class,
        batch=wp.batch,
        runtime_conf={k: str(v) for k, v in wp.runtime_conf.items()},
    )
    instance_id = instances.insert(instance)
    instance.id = instance_id
    logger.info("EvaluationInstance %s created (INIT)", instance_id)

    # one trace per sweep; a recurring-pipeline parent hands its context
    # via PIO_TRACE_CONTEXT so the eval joins the pipeline's trace id
    from predictionio_tpu.obs.trace_context import record_event
    from predictionio_tpu.obs.tracing import adopt

    try:
        with adopt("evaluate", attrs={"instance": instance_id}):
            with workflow_run_metrics("evaluate", "pio_eval"):
                result = evaluation.run(ctx, engine_params_list)
            # recorded INSIDE the adopted trace so the completion event
            # carries the sweep's trace id (the train.py discipline)
            record_event("eval_completed", {"instance": instance_id})
    except BaseException as e:
        # a failed sweep must not leave the instance stuck at INIT — the
        # dashboard/admin listings would show it as forever-starting.
        # BaseException on purpose: an injected kill (storage.faults
        # CrashError) or a KeyboardInterrupt mid-sweep is exactly the
        # crash the orchestrator's chaos suite drives through here, and
        # it used to leave the partial INIT row behind. The terminal
        # write is best-effort (the store may be the thing that died);
        # the original failure always re-raises.
        try:
            instance.status = "EVALFAILED"
            instance.end_time = _dt.datetime.now(tz=UTC)
            instance.evaluator_results = f"{type(e).__name__}: {e}"
            instances.update(instance)
        except Exception:
            logger.exception("could not mark instance %s EVALFAILED",
                             instance_id)
        logger.exception("evaluation failed: instance %s", instance_id)
        raise

    instance.status = "EVALCOMPLETED"
    instance.end_time = _dt.datetime.now(tz=UTC)
    instance.evaluator_results = result.to_one_liner()
    instance.evaluator_results_html = result.to_html()
    instance.evaluator_results_json = result.to_json()
    instances.update(instance)
    logger.info("evaluation completed: instance %s — %s",
                instance_id, result.to_one_liner())
    return result
