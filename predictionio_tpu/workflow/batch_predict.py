"""Offline batch scoring: pipelined, sharded, columnar `pio batchpredict`.

Parity with the reference BatchPredict (core/.../workflow/BatchPredict.scala
:37-235): input file of queries -> restore an engine instance -> supplement/
predict/serve per query -> self-descriptive ``{"query": ..., "prediction":
...}`` output. The reference maps the full pipeline per query over an RDD
(P8 in SURVEY.md); the first port here was a single loop interleaving
line-by-line JSON parsing, device dispatch and synchronous writes.

This is the throughput complement of the serving hot path — the
"parallel-and-stream" shape (arXiv:2111.00032): a heavy offline sweep
at maximal batch sizes behind the same shape discipline serving uses.

  * **pipelined** — a reader thread streams and decodes queries into
    bounded chunks, the scorer (caller's thread) drives the engines'
    bucketed ``batch_predict`` path, and a writer thread serializes and
    drains completed chunks, so file I/O and JSON churn never block the
    device. Bounded queues cap buffered rows; ``pipelined=False`` runs
    the identical stages inline (the measurement baseline).
  * **maximal buckets** — chunks pad up the ops/bucketing power-of-two
    ladder to ``chunk_size`` with sentinel indices, exactly as the
    serving micro-batcher pads its drains: the XLA compile ledger of a
    run is bounded by ``bucket_count(chunk_size)`` per scorer family,
    and the padding waste is charged to throughput
    (``pio_batchpredict_pad_waste_rows_total``) where serving charges
    its padding to latency. There is no linger — offline chunks are
    always full except the last.
  * **columnar** — queries may arrive as JSON-lines OR a parquet table
    (data/columnar.py layouts), and results may leave as JSON-lines OR
    parquet; engines whose single algorithm + passthrough FirstServing
    allow it score through ``Algorithm.batch_predict_columnar`` — the
    JSON-ready wire dicts directly, skipping the per-row dataclass
    churn that dominates CPU profiles at batch-scoring rates (output
    stays byte-identical; parity-tested).
  * **sharded** — the ``PIO_PROCESS_ID`` / ``PIO_NUM_PROCESSES``
    contract of parallel/distributed.py assigns each process one
    contiguous row range (the JdbcRDD partition layout, ALX-style
    offline work division). Each shard writes an output fragment via
    temp-write + atomic rename (the storage/parquet_events.py
    discipline); the last shard to finish claims a merge manifest
    (O_EXCL) and concatenates fragments in rank order into the final
    path — so the merged output is identical to a single-process run,
    and a kill at ANY point leaves nothing partial visible at the
    final path. Query rows shard here; each shard's *event* reads go
    through ``training_scan``'s shard/snapshot protocol, which a
    partitioned event store (``PIO_INGEST_PARTITIONS``,
    storage/partitioned.py) maps onto its partitions — whole
    partitions per shard when shards <= partitions, sub-sharded
    within one partition when shards exceed them.

Malformed input rows (unparseable JSON, queries that don't fit the
engine's query class, rows an engine fails on) never abort the run:
each becomes a record in a ``<output>.errors.jsonl`` sidecar and an
increment of ``pio_batchpredict_invalid_queries_total``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import threading
import time
import uuid
from typing import Any, List, Optional, Tuple

from predictionio_tpu.core.base import FirstServing, Serving
from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.params import params_from_json
from predictionio_tpu.obs import batch_stats
from predictionio_tpu.obs import fleet as obs_fleet
from predictionio_tpu.obs.registry import MetricsRegistry, default_registry
from predictionio_tpu.obs.trace_context import from_env, recorder
from predictionio_tpu.obs.tracing import capture_context, carried, span
from predictionio_tpu.ops.bucketing import bucket_size, padding_waste
from predictionio_tpu.parallel.distributed import (
    contiguous_range, resolve_worker,
)
from predictionio_tpu.server.query_server import _query_class
from predictionio_tpu.storage.base import EngineInstance
from predictionio_tpu.storage.faults import maybe_kill
from predictionio_tpu.utils.server_config import (
    BatchPredictConfig, batchpredict_config,
)

logger = logging.getLogger("pio.batchpredict")

_EOF = object()


@dataclasses.dataclass
class BatchPredictReport:
    """What one batch-predict worker did (and, when it performed the
    shard merge or ran unsharded, the run totals)."""

    written: int = 0             # predictions THIS worker wrote
    invalid: int = 0             # sidecar error records THIS worker wrote
    chunks: int = 0
    pad_waste: int = 0
    seconds: float = 0.0
    rows_per_second: float = 0.0
    output_path: str = ""        # final path when merged, else fragment
    errors_path: Optional[str] = None
    worker: Tuple[int, int] = (0, 1)
    merged: bool = True          # False = this shard left a fragment only
    total_written: Optional[int] = None   # across shards (merger only)
    total_invalid: Optional[int] = None
    #: the run's trace id (PIO_TRACE_CONTEXT parent, else a fresh root);
    #: one id spans the parent and every shard of a fleet run
    trace_id: Optional[str] = None
    #: merged fleet observability (merger only): per-process metrics with
    #: a `process` label, exact counter totals, the fleet's trace records
    fleet: Optional[dict] = None


# ---------------------------------------------------------------------------
# serialization
# ---------------------------------------------------------------------------

_FIELD_NAMES: dict = {}


def fast_jsonable(obj: Any) -> Any:
    """`_to_jsonable` semantics without `dataclasses.asdict`: asdict
    deep-copies every leaf it visits, which at batch-scoring rates costs
    more than the scoring matmul. This walk builds the same JSON value
    (to_dict when offered, dataclass fields by name, containers
    recursively, leaves by reference) — byte-identical once dumped with
    sort_keys, which the parity tests assert."""
    if type(obj) in (str, int, float, bool, type(None)):
        return obj
    to_dict = getattr(obj, "to_dict", None)
    if callable(to_dict):
        return to_dict()
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        t = type(obj)
        names = _FIELD_NAMES.get(t)
        if names is None:
            names = _FIELD_NAMES.setdefault(
                t, tuple(f.name for f in dataclasses.fields(t)))
        return {n: fast_jsonable(getattr(obj, n)) for n in names}
    if isinstance(obj, (list, tuple)):
        return [fast_jsonable(v) for v in obj]
    if isinstance(obj, dict):
        return {k: fast_jsonable(v) for k, v in obj.items()}
    return obj


# ---------------------------------------------------------------------------
# input: JSON-lines or columnar parquet -> decoded row stream
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Row:
    row: int                     # absolute input row number (0-based)
    raw: Any                     # original query value for the echo/sidecar
    query: Any = None            # decoded query object (None when error)
    error: Optional[str] = None


def _format_of(path: str, override: Optional[str] = None,
               default: Optional[str] = None) -> str:
    """Resolve a file format: an explicit per-invocation override wins,
    then a recognized extension, then the configured default — the host
    knob only names formats for extension-less paths, so a server.json
    ``outputFormat`` can never turn ``preds.parquet`` into JSON-lines."""
    if override:
        return override
    low = path.lower()
    if low.endswith((".parquet", ".pq")):
        return "parquet"
    if low.endswith((".jsonl", ".json", ".ndjson")):
        return "jsonl"
    return default or "jsonl"


def _count_input_rows(path: str, fmt: str) -> int:
    """Total query rows — the shard-range denominator. JSON-lines rows
    are the non-blank lines (a fast byte scan); parquet reads metadata."""
    if fmt == "parquet":
        import pyarrow.parquet as pq

        return pq.ParquetFile(path).metadata.num_rows
    n = 0
    with open(path, "rb") as f:
        for line in f:
            if line.strip():
                n += 1
    return n


def _decode_obj(row: int, obj: Any, qc: Optional[type]) -> _Row:
    if qc is None:
        return _Row(row, obj, query=obj)
    try:
        return _Row(row, obj, query=params_from_json(obj, qc))
    except Exception as e:
        return _Row(row, obj,
                    error=f"query does not fit {qc.__name__}: {e}")


def _decode_text(row: int, text: str, qc: Optional[type]) -> _Row:
    try:
        obj = json.loads(text)
    except ValueError as e:
        return _Row(row, text, error=f"invalid JSON: {e}")
    return _decode_obj(row, obj, qc)


def _iter_rows(input_path: str, fmt: str, qc: Optional[type],
               lo: Optional[int] = None, hi: Optional[int] = None):
    """Decoded `_Row` stream for input rows [lo, hi) (everything when
    unbounded). Decoding runs here — i.e. on the READER thread of a
    pipelined run — so JSON parsing overlaps device scoring."""
    if fmt == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq

        from predictionio_tpu.data.columnar import query_table_rows

        pf = pq.ParquetFile(input_path)
        # prune to the row groups overlapping [lo, hi): a shard must not
        # decode the whole file to reach its range (the groups a [lo, hi)
        # window selects over cumulative counts are contiguous, so `row`
        # resumes at the first selected group's absolute start)
        md = pf.metadata
        groups: List[int] = []
        row = start = 0
        for g in range(md.num_row_groups):
            g_lo, g_hi = row, row + md.row_group(g).num_rows
            row = g_hi
            if (hi is None or g_lo < hi) and (lo is None or g_hi > lo):
                if not groups:
                    start = g_lo
                groups.append(g)
        row = start
        for batch in (pf.iter_batches(row_groups=groups) if groups
                      else ()):
            if hi is not None and row >= hi:
                break
            cells = query_table_rows(pa.Table.from_batches([batch]))
            for cell in cells:
                r = row
                row += 1
                if lo is not None and r < lo:
                    continue
                if hi is not None and r >= hi:
                    break
                if isinstance(cell, str):
                    yield _decode_text(r, cell, qc)
                elif cell is None:
                    yield _Row(r, cell, error="null query row")
                else:
                    yield _decode_obj(r, cell, qc)
        return
    row = 0
    with open(input_path) as f:
        for line in f:
            text = line.strip()
            if not text:
                continue
            r = row
            row += 1
            if lo is not None and r < lo:
                continue
            if hi is not None and r >= hi:
                break
            yield _decode_text(r, text, qc)


def _iter_chunks(rows_iter, chunk_size: int, registry: MetricsRegistry):
    chunk: List[_Row] = []
    while True:
        with span("batchpredict_read", registry=registry):
            for r in rows_iter:
                chunk.append(r)
                if len(chunk) >= chunk_size:
                    break
            else:
                break
        yield chunk
        chunk = []
    if chunk:
        yield chunk


# ---------------------------------------------------------------------------
# scorer: the bucketed batch path at the maximal bucket
# ---------------------------------------------------------------------------

class _ChunkScorer:
    """Score one decoded chunk through the engine's batch path.

    Mirrors the query server's `_predict_batch` discipline — supplement,
    pad to the power-of-two bucket under sentinel indices, per-algorithm
    `batch_predict`, serve, with per-query error isolation — at the
    MAXIMAL bucket (`chunk_size`), no linger. Output entries are
    ``("json", wire_dict)`` from the columnar lane, ``("obj", served)``
    from the generic lane, or ``("err", message)``.
    """

    def __init__(self, result, max_bucket: int,
                 registry: MetricsRegistry):
        self.result = result
        self.max_bucket = max(1, max_bucket)
        self.registry = registry
        self.fast = self._lane_hook("batch_predict_columnar")
        self.arrow = None       # activated by enable_arrow() (parquet out)
        self.pad_waste = 0
        self._queries = batch_stats.batch_queries_counter(registry)
        self._pad = batch_stats.batch_pad_waste(registry)
        self._chunk_hist = batch_stats.batch_chunk_seconds(registry)

    def _lane_hook(self, name: str):
        """A dataclass-free scorer hook, eligible only when it provably
        changes nothing: ONE algorithm offering the hook, behind a
        passthrough supplement and stock FirstServing (any override could
        transform what the generic lane would have produced, so those
        engines keep the generic path)."""
        r = self.result
        if len(r.algorithms) != 1:
            return None
        hook = getattr(r.algorithms[0], name, None)
        if not callable(hook):
            return None
        s = type(r.serving)
        if s.supplement is not Serving.supplement:
            return None
        if s.serve is not FirstServing.serve:
            return None
        return hook

    def enable_arrow(self):
        """Turn on the fully columnar lane (scores leave as ONE arrow
        column per chunk, no per-row Python objects) for a parquet run.
        Returns the arrow type of the prediction column, or None when the
        engine doesn't support the lane — the caller falls back to the
        dict lanes + JSON-string parquet layout."""
        hook = self._lane_hook("batch_predict_arrow")
        if hook is None:
            return None
        wire_type = getattr(self.result.algorithms[0],
                            "columnar_wire_type", None)
        if not callable(wire_type):
            return None
        self.arrow = hook
        return wire_type()

    def _padded(self, entries: List[Tuple[int, Any]], n_real: int):
        """Pad an indexed batch up its bucket with clones of the last
        real query under sentinel indices >= n_real; their predictions
        are computed and discarded (the bounded price of the bounded
        compile-shape set). Returns (padded entries, waste rows) — the
        caller charges the waste, ONCE per chunk, for whichever lane
        produced the chunk's final result (a failed lane's padding is
        not double-billed by its generic retry)."""
        bucket = bucket_size(len(entries), self.max_bucket)
        waste = padding_waste(len(entries), bucket)
        if waste:
            pad_q = entries[-1][1]
            entries = entries + [(n_real + j, pad_q) for j in range(waste)]
        return entries, waste

    def score(self, rows: List[_Row]):
        """-> (outs, col): per-row ``("json"|"obj"|"err"|"arrow", payload)``
        entries, plus — on the arrow lane — the chunk's prediction column
        (one arrow array over the non-error rows, in order)."""
        out: List[Optional[Tuple[str, Any]]] = [None] * len(rows)
        valid = []
        for i, r in enumerate(rows):
            if r.error is not None:
                out[i] = ("err", r.error)
            else:
                valid.append((i, r.query))
        if not valid:
            return out, None
        col = None
        waste = 0
        t0 = time.perf_counter()
        with span("batchpredict_score", registry=self.registry):
            if self.arrow is not None:
                try:
                    col, waste = self._score_arrow(valid, len(rows), out)
                except Exception:
                    logger.exception(
                        "arrow scoring lane failed; retrying the chunk "
                        "on the generic path")
                    col = None
                    waste = self._score_generic(valid, len(rows), out)
            elif self.fast is not None:
                try:
                    waste = self._score_fast(valid, len(rows), out)
                except Exception:
                    logger.exception(
                        "columnar scoring lane failed; retrying the "
                        "chunk on the generic path")
                    waste = self._score_generic(valid, len(rows), out)
            else:
                waste = self._score_generic(valid, len(rows), out)
        if waste:
            self._pad.inc(waste)
            self.pad_waste += waste
        self._chunk_hist.observe(time.perf_counter() - t0)
        self._queries.inc(len(valid))
        return out, col

    def _score_fast(self, valid, n_rows, out) -> int:
        batch, waste = self._padded(valid, n_rows)
        per = dict(self.fast(self.result.models[0], batch))
        for i, _ in valid:
            out[i] = ("json", per[i])
        return waste

    def _score_arrow(self, valid, n_rows, out):
        """Chunk scores as ONE arrow column: the hook returns an array
        parallel to the padded batch; pads ride the tail, so the real
        rows are a zero-copy prefix slice."""
        batch, waste = self._padded(valid, n_rows)
        col = self.arrow(self.result.models[0], batch)
        for i, _ in valid:
            out[i] = ("arrow", None)
        return col.slice(0, len(valid)), waste

    def _score_generic(self, valid, n_rows, out) -> int:
        result = self.result
        qmap = dict(valid)
        sup = []
        for i, q in valid:
            if out[i] is not None:     # columnar fallback may have partials
                out[i] = None
            try:
                sup.append((i, result.serving.supplement(q)))
            except Exception as e:
                out[i] = ("err", f"supplement failed: {e!r}")
        if not sup:
            return 0
        batch, waste = self._padded(sup, n_rows)
        try:
            per = {i: [] for i, _ in sup}
            for algo, model in zip(result.algorithms, result.models):
                for i, p in algo.batch_predict(model, batch):
                    if i in per:            # pad rows sliced off
                        per[i].append(p)
            for i, _ in sup:
                try:
                    out[i] = ("obj", result.serving.serve(qmap[i], per[i]))
                except Exception as e:
                    out[i] = ("err", f"serve failed: {e!r}")
        except Exception:
            # poison query inside a vectorized batch_predict — isolate it
            # by falling back to per-query predict (the server rule)
            for i, sq in sup:
                if out[i] is not None:
                    continue
                try:
                    preds = [a.predict(m, sq) for a, m in
                             zip(result.algorithms, result.models)]
                    out[i] = ("obj", result.serving.serve(qmap[i], preds))
                except Exception as e:
                    out[i] = ("err", f"predict failed: {e!r}")
        return waste


# ---------------------------------------------------------------------------
# output: crash-safe JSON-lines / parquet sinks
# ---------------------------------------------------------------------------

class _Sink:
    """Crash-safe output file: all bytes land in a same-directory temp
    file; `commit()` atomically renames it into place (so a kill at any
    moment leaves nothing partial visible at the target); `abort()`
    removes the temp."""

    def __init__(self, target: str):
        self.target = target
        self.tmp = f"{target}.tmp-{uuid.uuid4().hex}"
        self.rows = 0

    def _close(self) -> None:
        raise NotImplementedError

    def commit(self) -> None:
        self._close()
        os.replace(self.tmp, self.target)

    def abort(self) -> None:
        try:
            self._close()
        except Exception:
            pass
        try:
            if os.path.exists(self.tmp):
                os.unlink(self.tmp)
        except OSError:
            pass


class _JsonlSink(_Sink):
    def __init__(self, target: str):
        super().__init__(target)
        self._f = open(self.tmp, "w")

    def write_chunk(self, lines: List[str]) -> None:
        if lines:
            self._f.write("\n".join(lines) + "\n")
            self.rows += len(lines)

    def _close(self) -> None:
        if not self._f.closed:
            self._f.close()


class _ParquetSink(_Sink):
    """One row group per scored chunk. With a `prediction_type` (the
    engine's columnar wire type) predictions land as a STRUCTURED arrow
    column via one C-level `pa.array(dicts, type)` conversion per chunk
    — roughly an order of magnitude cheaper than a json.dumps per row,
    and downstream readers get real columns. Without one, the generic
    JSON-string layout."""

    def __init__(self, target: str, prediction_type=None):
        super().__init__(target)
        import pyarrow.parquet as pq

        from predictionio_tpu.data.columnar import predictions_schema

        self.prediction_type = prediction_type
        self.schema = predictions_schema(prediction_type)
        self._writer = pq.ParquetWriter(self.tmp, self.schema)

    def write_chunk(self, query_jsons: List[str], predictions) -> None:
        if query_jsons:
            import pyarrow as pa

            if isinstance(predictions, pa.Array):
                # arrow lane: the scorer already assembled the column
                pred = (predictions if
                        predictions.type == self.prediction_type
                        else predictions.cast(self.prediction_type))
            elif self.prediction_type is not None:
                pred = pa.array(predictions, type=self.prediction_type)
            else:
                pred = pa.array(predictions, type=pa.string())
            self._writer.write_table(pa.table(
                {"query": pa.array(query_jsons, type=pa.string()),
                 "prediction": pred}, schema=self.schema))
            self.rows += len(query_jsons)

    def _close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class _Sidecar:
    """Lazy error sidecar: no invalid rows -> no file at all."""

    def __init__(self, target: str):
        self.target = target
        self._sink: Optional[_JsonlSink] = None
        self.rows = 0

    def record(self, row: _Row, message: str) -> None:
        if self._sink is None:
            self._sink = _JsonlSink(self.target)
        self._sink.write_chunk([json.dumps(
            {"row": row.row, "error": message, "query": row.raw},
            sort_keys=True, default=str)])
        self.rows += 1

    def commit(self) -> None:
        if self._sink is not None:
            self._sink.commit()
        else:
            # an error-free run must not leave a previous run's sidecar
            # at the target masquerading as this run's errors
            try:
                os.unlink(self.target)
            except OSError:
                pass

    def abort(self) -> None:
        if self._sink is not None:
            self._sink.abort()


class _Writer:
    """The serialize-and-drain stage (the writer thread's work)."""

    def __init__(self, fmt: str, target: str, sidecar: _Sidecar,
                 registry: MetricsRegistry, prediction_type=None):
        self.fmt = fmt
        self.sidecar = sidecar
        self.registry = registry
        self.structured = fmt == "parquet" and prediction_type is not None
        self.sink = (_ParquetSink(target, prediction_type)
                     if fmt == "parquet" else _JsonlSink(target))
        self.invalid_counter = batch_stats.batch_invalid_counter(registry)

    def write_chunk(self, rows: List[_Row], scored) -> None:
        outs, col = scored
        with span("batchpredict_write", registry=self.registry):
            if self.fmt == "parquet":
                qjs, preds = [], []
                for r, entry in zip(rows, outs):
                    kind, payload = entry
                    if kind == "err":
                        self._invalid(r, payload)
                        continue
                    # canonical sort_keys echo — identical bytes to the
                    # jsonl lane's query field regardless of how the
                    # input spelled the object
                    qjs.append(json.dumps(r.raw, sort_keys=True))
                    if kind == "arrow":
                        continue        # the whole column rides `col`
                    pj = payload if kind == "json" else fast_jsonable(payload)
                    preds.append(pj if self.structured
                                 else json.dumps(pj, sort_keys=True))
                self.sink.write_chunk(qjs, col if col is not None else preds)
            else:
                lines = []
                for r, entry in zip(rows, outs):
                    kind, payload = entry
                    if kind == "err":
                        self._invalid(r, payload)
                        continue
                    pj = payload if kind == "json" else fast_jsonable(payload)
                    lines.append(json.dumps(
                        {"query": r.raw, "prediction": pj}, sort_keys=True))
                self.sink.write_chunk(lines)
        maybe_kill("batchpredict:chunk")

    def _invalid(self, row: _Row, message: str) -> None:
        self.sidecar.record(row, message)
        self.invalid_counter.inc()

    def commit(self) -> None:
        self.sink.commit()
        self.sidecar.commit()

    def abort(self) -> None:
        self.sink.abort()
        self.sidecar.abort()


# ---------------------------------------------------------------------------
# shard fragments + manifest merge
# ---------------------------------------------------------------------------

def _part_path(output: str, rank: int, size: int) -> str:
    return f"{output}.part-{rank:05d}-of-{size:05d}"


def _obs_path(output: str, rank: int, size: int) -> str:
    return f"{output}.obs-{rank:05d}-of-{size:05d}.json"


def _fleet_path(output: str) -> str:
    return f"{output}.fleet.json"


def _err_part_path(output: str, rank: int, size: int) -> str:
    return f"{output}.errors.part-{rank:05d}-of-{size:05d}"


def _meta_path(output: str, rank: int, size: int) -> str:
    return f"{output}.meta-{rank:05d}-of-{size:05d}.json"


def _manifest_path(output: str) -> str:
    return f"{output}.manifest.json"


def _input_fingerprint(input_path: str,
                       instance: Optional[EngineInstance]) -> List[Any]:
    """Identity of (input file, scored instance) for a fleet — recorded
    in every shard meta so completion markers from a DIFFERENT fleet
    generation (crash leftovers next to a since-rewritten input, or
    fragments scored with an older release) are never merged with fresh
    fragments. `loaded=` runs without an instance record "" — callers
    wiring their own models to a shared sharded output path must keep
    the model fixed across the fleet."""
    st = os.stat(input_path)
    return [st.st_mtime_ns, st.st_size,
            instance.id if instance is not None else ""]


def _write_meta(output: str, rank: int, size: int, written: int,
                invalid: int, fingerprint: List[Any]) -> None:
    """Commit this shard's completion record (temp-write + rename, AFTER
    its fragments are in place — the meta appearing atomically IS the
    shard's done marker)."""
    meta = _meta_path(output, rank, size)
    tmp = f"{meta}.tmp-{uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        json.dump({"rank": rank, "size": size, "rows": written,
                   "invalid": invalid, "input": fingerprint},
                  f, sort_keys=True)
    os.replace(tmp, meta)


def _read_meta(path: str, fingerprint: List[Any]) -> Optional[dict]:
    """A shard's meta, or None when it is missing, torn, or recorded
    against a different input file (a stale marker from a previous
    fleet — NOT done as far as this fleet is concerned)."""
    try:
        with open(path) as f:
            entry = json.load(f)
    except (OSError, ValueError):
        return None
    if entry.get("input") != fingerprint:
        return None
    return entry


def _clear_stale_rank_markers(output: str, rank: int, size: int) -> None:
    """A re-run must not let a PREVIOUS run's completion markers for
    this rank survive into its own fleet: remove the meta first (it is
    the done-marker, so there is no window where a stale fragment looks
    complete), then the fragments. Each shard clears only its OWN rank —
    a sibling's live markers from the same fleet stay usable."""
    for path in (_meta_path(output, rank, size),
                 _part_path(output, rank, size),
                 _err_part_path(output, rank, size),
                 _obs_path(output, rank, size)):
        try:
            os.unlink(path)
        except OSError:
            pass


def _maybe_merge(output: str, size: int, fmt: str,
                 fingerprint: List[Any]) -> Optional[dict]:
    """Merge shard fragments into the final output if every shard is
    done (a meta counts only when it matches THIS fleet's input
    fingerprint). The LAST shard to finish performs the merge; election
    is an O_EXCL create of the manifest, so exactly one merger claims
    it even when shards finish simultaneously. A pre-existing manifest
    is NOT a dead end: as long as every fragment + meta is present the
    merge is simply re-run (same fragments -> same bytes, committed by
    atomic rename), so a merger that crashed at ANY point — before or
    after the commit — is healed by the next run over the same path.
    Returns the run totals when this call merged, else None."""
    metas = [_meta_path(output, r, size) for r in range(size)]
    entries = [_read_meta(m, fingerprint) for m in metas]
    if any(e is None for e in entries):
        return None
    manifest = _manifest_path(output)
    try:
        fd = os.open(manifest, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return _roll_forward_merge(output, size, fmt, manifest, entries)
    with os.fdopen(fd, "w") as f:
        json.dump({"format": fmt, "shards": entries}, f, sort_keys=True)
    maybe_kill("batchpredict:merge")
    return _do_merge(output, size, fmt, entries)


def _roll_forward_merge(output: str, size: int, fmt: str, manifest: str,
                        entries: List[dict]) -> Optional[dict]:
    """A manifest already exists: a previous merger crashed mid-merge
    (no output yet) or right after its commit (output present but the
    stale claim survived — which would otherwise wedge every future
    fleet on this path), or a concurrent merger is mid-flight right
    now. Every meta already matched this fleet's fingerprint, so if the
    fragments are present too, re-run the merge — idempotent, so racing
    a live merger is harmless (_do_merge treats losing that race as
    success). Fragments missing with the output present is the normal
    already-merged-and-GC'd state: nothing to do."""
    parts = [_part_path(output, r, size) for r in range(size)]
    if not all(os.path.exists(p) for p in parts):
        if not os.path.exists(output):
            logger.warning(
                "merge manifest %s exists, the merged output is missing, "
                "and the shard fragments are incomplete — cannot roll the "
                "crashed merge forward; remove the manifest and re-run "
                "the shards", manifest)
        return None
    try:
        logger.info("re-running the merge claimed by existing manifest %s",
                    manifest)
        return _do_merge(output, size, fmt, entries)
    except OSError:
        if os.path.exists(output) and not os.path.exists(manifest):
            return None       # a concurrent merger committed and GC'd
        raise


def _do_merge(output: str, size: int, fmt: str, entries: List[dict]) -> dict:
    """Concatenate the shard fragments in rank order into the final path
    (temp-write + atomic rename), merge the error sidecars, then GC the
    manifest and fragments. Concurrent mergers (an O_EXCL winner racing
    a roll-forward, or two roll-forwards) build byte-identical content,
    so losing the race — our fragment reads failing because the winner
    committed and GC'd first — counts as success."""
    manifest = _manifest_path(output)
    metas = [_meta_path(output, r, size) for r in range(size)]
    parts = [_part_path(output, r, size) for r in range(size)]
    totals = {"written": sum(e["rows"] for e in entries),
              "invalid": sum(e["invalid"] for e in entries)}
    tmp = f"{output}.tmp-{uuid.uuid4().hex}"
    try:
        if fmt == "parquet":
            import pyarrow as pa
            import pyarrow.parquet as pq

            # the fragments carry the schema (structured wire columns or
            # the generic JSON-string layout) — the merge preserves it
            schema = pq.ParquetFile(parts[0]).schema_arrow
            writer = pq.ParquetWriter(tmp, schema)
            try:
                for part in parts:
                    pf = pq.ParquetFile(part)
                    for batch in pf.iter_batches():
                        writer.write_table(pa.Table.from_batches(
                            [batch], schema=schema))
            finally:
                writer.close()
        else:
            with open(tmp, "wb") as out_f:
                for part in parts:
                    with open(part, "rb") as in_f:
                        while True:
                            buf = in_f.read(1 << 20)
                            if not buf:
                                break
                            out_f.write(buf)
        os.replace(tmp, output)                  # COMMIT
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        # the manifest is GC'd first below, so its absence alongside a
        # present output proves a concurrent merge committed — a real IO
        # failure leaves the claim in place and re-raises
        if os.path.exists(output) and not os.path.exists(manifest):
            logger.info("concurrent merger already committed %s", output)
            return totals
        raise

    # fleet observability merge: fold every shard's obs snapshot into
    # ONE view (per-process labels, exact counter sums, the union of
    # trace records) committed as <output>.fleet.json, and import the
    # fleet's traces into THIS process's flight recorder so one trace id
    # spans parent + shards at /debug/traces.json. Snapshots already
    # GC'd by a previous merge leave the committed fleet.json in place.
    obs_paths = [p for p in (_obs_path(output, r, size)
                             for r in range(size)) if os.path.exists(p)]
    try:
        # best-effort by contract: the predictions are already committed,
        # and a bad shard snapshot (mixed code versions skewing histogram
        # buckets, a malformed series) must never fail the data path —
        # or leave the manifest claim wedged for every future fleet
        view = obs_fleet.merge_snapshot_files(obs_paths)
        if view.processes:
            fleet_doc = view.to_json()
            ftmp = f"{_fleet_path(output)}.tmp-{uuid.uuid4().hex}"
            try:
                with open(ftmp, "w") as f:
                    json.dump(fleet_doc, f, sort_keys=True)
                os.replace(ftmp, _fleet_path(output))
            except OSError:
                try:
                    os.unlink(ftmp)
                except OSError:
                    pass
            obs_fleet.import_into_recorder(view)
            totals["fleet"] = fleet_doc
    except Exception:
        logger.exception("fleet observability merge failed "
                         "(predictions are committed and unaffected)")

    err_parts = [p for p in
                 (_err_part_path(output, r, size) for r in range(size))
                 if os.path.exists(p)]
    try:
        if err_parts:
            etmp = f"{output}.errors.tmp-{uuid.uuid4().hex}"
            try:
                with open(etmp, "wb") as out_f:
                    for part in err_parts:
                        with open(part, "rb") as in_f:
                            out_f.write(in_f.read())
                os.replace(etmp, f"{output}.errors.jsonl")
            except OSError:
                try:
                    os.unlink(etmp)
                except OSError:
                    pass
                raise
        else:
            # an error-free merge must not leave a previous run's sidecar
            # next to the fresh output
            os.unlink(f"{output}.errors.jsonl")
    except OSError:
        # either the sidecar never existed, or a concurrent merger is
        # GC'ing the error fragments after committing the identical
        # merged sidecar
        pass

    # post-commit GC: the manifest FIRST — it is the merge claim, and a
    # surviving claim would outlive the fragments; everything behind it
    # is harmlessly redundant if we crash mid-loop
    for path in [manifest] + parts + metas + err_parts + obs_paths:
        try:
            os.unlink(path)
        except OSError:
            pass
    return totals


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

class _StageFailed(Exception):
    """Internal: another pipeline stage died; unwind quietly."""


def _run_pipeline(chunks, scorer: _ChunkScorer, writer: _Writer,
                  queue_chunks: int, pipelined: bool) -> int:
    """Drive reader -> scorer -> writer; returns chunks scored. The
    scorer runs on the CALLING thread (it owns device dispatch order);
    reading+decoding and serializing+writing ride two daemon threads
    behind bounded queues so neither ever blocks the device. Any stage
    failure stops the others promptly and re-raises here — including
    BaseException kill points, so a crash test dies exactly where it was
    injected."""
    if not pipelined:
        n = 0
        for rows in chunks:
            writer.write_chunk(rows, scorer.score(rows))
            n += 1
        return n

    in_q: "queue.Queue" = queue.Queue(maxsize=queue_chunks)
    out_q: "queue.Queue" = queue.Queue(maxsize=queue_chunks)
    stop = threading.Event()
    reader_exc: List[BaseException] = []
    writer_exc: List[BaseException] = []

    def _put(q, item) -> None:
        while True:
            if stop.is_set():
                raise _StageFailed()
            try:
                q.put(item, timeout=0.05)
                return
            except queue.Full:
                continue

    def _get(q):
        while True:
            if stop.is_set():
                raise _StageFailed()
            try:
                return q.get(timeout=0.05)
            except queue.Empty:
                continue

    # both stage threads re-enter the run's trace (the shard runs under
    # tracing.adopt) so decode/commit I/O attributes to the batchpredict
    # trace id; record=False — the run-level span already records
    ctx = capture_context()

    def read_loop() -> None:
        try:
            with carried(ctx, "bp_reader", record=False):
                for rows in chunks:
                    _put(in_q, rows)
                _put(in_q, _EOF)
        except _StageFailed:
            pass
        except BaseException as e:       # noqa: BLE001 — incl. CrashError
            reader_exc.append(e)
            stop.set()

    def write_loop() -> None:
        try:
            with carried(ctx, "bp_writer", record=False):
                while True:
                    item = _get(out_q)
                    if item is _EOF:
                        return
                    writer.write_chunk(*item)
        except _StageFailed:
            pass
        except BaseException as e:       # noqa: BLE001 — incl. CrashError
            writer_exc.append(e)
            stop.set()

    rt = threading.Thread(target=read_loop, name="pio-bp-reader",
                          daemon=True)
    wt = threading.Thread(target=write_loop, name="pio-bp-writer",
                          daemon=True)
    rt.start()
    wt.start()
    n = 0
    try:
        while True:
            item = _get(in_q)
            if item is _EOF:
                _put(out_q, _EOF)
                break
            _put(out_q, (item, scorer.score(item)))
            n += 1
    except _StageFailed:
        pass
    except BaseException:
        stop.set()
        raise
    finally:
        # settle both stages before inspecting their fate: a failed run
        # gets bounded joins after stop (a hung stage must not wedge the
        # unwind), a healthy one joins unbounded — the writer may
        # legitimately need longer than any timeout to drain the queue
        # tail, and committing before it finishes would truncate the
        # output
        if reader_exc or writer_exc or stop.is_set():
            stop.set()
            rt.join(timeout=30)
            wt.join(timeout=30)
        else:
            rt.join()
            wt.join()
    if writer_exc:
        raise writer_exc[0]
    if reader_exc:
        raise reader_exc[0]
    if rt.is_alive() or wt.is_alive():
        raise RuntimeError(
            "batch-predict pipeline stage did not settle after failure; "
            "aborting instead of committing a possibly-truncated output")
    return n


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def run_batch_predict(engine: Optional[Engine],
                      instance: Optional[EngineInstance],
                      input_path: str, output_path: str,
                      chunk_size: Optional[int] = None, *,
                      output_format: Optional[str] = None,
                      input_format: Optional[str] = None,
                      variant_conf: Optional[dict] = None,
                      config: Optional[BatchPredictConfig] = None,
                      loaded: Optional[tuple] = None,
                      pipelined: Optional[bool] = None,
                      worker: Optional[Tuple[int, int]] = None,
                      registry: Optional[MetricsRegistry] = None
                      ) -> BatchPredictReport:
    """Score a file of queries offline; returns a BatchPredictReport.

    Explicit arguments beat the resolved config (env >
    engine.json ``batchpredict`` section (``variant_conf``) >
    server.json). ``loaded=(result, ctx)`` skips the model-store restore
    (benches/tests with synthetic models); ``worker=(rank, size)`` pins
    the shard identity instead of reading the PIO_* process env.
    """
    cfg = config or batchpredict_config(variant_conf)
    chunk = max(1, chunk_size if chunk_size is not None else cfg.chunk_size)
    pipe = cfg.pipelined if pipelined is None else pipelined
    out_fmt = _format_of(output_path, output_format, cfg.output_format)
    in_fmt = _format_of(input_path, input_format)
    rank, size = resolve_worker(*(worker or (None, None)))
    registry = registry or default_registry()

    if loaded is not None:
        result = loaded[0]
    else:
        from predictionio_tpu.workflow.train import load_for_deploy

        result, _ctx = load_for_deploy(engine, instance)
    qc = _query_class(result)

    lo = hi = None
    if size > 1:
        n_rows = _count_input_rows(input_path, in_fmt)
        lo, hi = contiguous_range(n_rows, rank, size)
        target = _part_path(output_path, rank, size)
        err_target = _err_part_path(output_path, rank, size)
        _clear_stale_rank_markers(output_path, rank, size)
    else:
        target = output_path
        err_target = f"{output_path}.errors.jsonl"

    scorer = _ChunkScorer(result, chunk, registry)
    prediction_type = None
    if out_fmt == "parquet":
        # arrow lane: scores leave the engine as ONE structured arrow
        # column per chunk (no per-row Python objects at all) and the
        # parquet output gets REAL wire-typed columns
        prediction_type = scorer.enable_arrow()
        if prediction_type is None and scorer.fast is not None:
            # dict lane + declared wire type still gets structured
            # columns (one pa.array conversion per chunk)
            wire_type = getattr(result.algorithms[0],
                                "columnar_wire_type", None)
            if callable(wire_type):
                prediction_type = wire_type()
    writer = _Writer(out_fmt, target, _Sidecar(err_target), registry,
                     prediction_type=prediction_type)
    t0 = time.perf_counter()
    # the whole shard run is ONE trace: a parent that spawned this
    # process hands its context via PIO_TRACE_CONTEXT (obs/trace_context)
    # and every shard of the fleet then shares the parent's trace id; a
    # standalone run roots a fresh one. The completed-run record (with
    # the read/score/write span totals) lands in the flight recorder and
    # rides the shard's obs snapshot to the merger.
    parent_ctx = from_env()
    run_name = (f"batchpredict shard {rank}/{size}" if size > 1
                else "batchpredict")
    try:
        with carried(parent_ctx, run_name, registry=registry,
                     attrs={"input": os.path.basename(input_path),
                            "output": os.path.basename(output_path),
                            "rank": rank, "size": size}) as run_trace:
            trace_id = run_trace.trace_id
            chunks = _iter_chunks(
                _iter_rows(input_path, in_fmt, qc, lo, hi), chunk, registry)
            n_chunks = _run_pipeline(chunks, scorer, writer,
                                     cfg.queue_chunks, pipe)
            writer.commit()
    except BaseException:
        writer.abort()
        raise
    seconds = time.perf_counter() - t0

    written = writer.sink.rows
    invalid = writer.sidecar.rows
    rps = written / seconds if seconds > 0 else 0.0
    batch_stats.batch_rows_per_second(registry).set(rps)
    report = BatchPredictReport(
        written=written, invalid=invalid, chunks=n_chunks,
        pad_waste=scorer.pad_waste, seconds=seconds, rows_per_second=rps,
        output_path=target,
        errors_path=(writer.sidecar.target if invalid else None),
        worker=(rank, size), merged=(size == 1),
        total_written=written if size == 1 else None,
        total_invalid=invalid if size == 1 else None,
        trace_id=trace_id)

    if size > 1:
        fp = _input_fingerprint(input_path, instance)
        # push this shard's observability to the merger: registry
        # snapshot + this run's trace records, committed BEFORE the meta
        # done-marker so the merging shard always finds it
        doc = obs_fleet.snapshot(registry, process=f"{rank}/{size}",
                                 include_traces=False,
                                 extra={"worker": [rank, size],
                                        "traceId": trace_id})
        doc["traces"] = recorder().traces(trace_id=trace_id)
        doc["events"] = [e for e in recorder().events()
                         if e.get("traceId") == trace_id]
        obs_fleet.write_snapshot(_obs_path(output_path, rank, size), doc)
        _write_meta(output_path, rank, size, written, invalid, fp)
        totals = _maybe_merge(output_path, size, out_fmt, fp)
        if totals is not None:
            report.merged = True
            report.output_path = output_path
            report.total_written = totals["written"]
            report.total_invalid = totals["invalid"]
            report.fleet = totals.get("fleet")
            report.errors_path = (f"{output_path}.errors.jsonl"
                                  if totals["invalid"] else None)
    logger.info(
        "batch predict%s: %d predictions (%d invalid, %d pad rows, "
        "%.0f rows/s%s) -> %s",
        f" shard {rank}/{size}" if size > 1 else "",
        report.written, report.invalid, report.pad_waste, rps,
        (", arrow lane" if scorer.arrow is not None
         else ", columnar lane" if scorer.fast is not None else ""),
        report.output_path)
    return report
