"""Batch prediction: score a file of queries through a trained engine.

Parity with the reference BatchPredict (core/.../workflow/BatchPredict.scala:37-235):
input file of one JSON query per line -> restore the latest COMPLETED
instance -> supplement/predict/serve per query -> output file of
self-descriptive {"query": ..., "prediction": ...} lines (:196-228).

The reference maps the full pipeline per query over an RDD (P8 in SURVEY.md);
here queries are processed in chunks so algorithms with vectorized
batch_predict implementations amortize device dispatch.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.params import params_from_json
from predictionio_tpu.server.query_server import _query_class, _to_jsonable
from predictionio_tpu.storage.base import EngineInstance

logger = logging.getLogger("pio.batchpredict")


def run_batch_predict(engine: Engine, instance: EngineInstance,
                      input_path: str, output_path: str,
                      chunk_size: int = 1024) -> int:
    """Returns the number of predictions written."""
    from predictionio_tpu.workflow.train import load_for_deploy

    result, ctx = load_for_deploy(engine, instance)
    qc = _query_class(result)

    n = 0
    with open(input_path) as fin, open(output_path, "w") as fout:
        chunk = []
        for line in fin:
            line = line.strip()
            if not line:
                continue
            chunk.append(json.loads(line))
            if len(chunk) >= chunk_size:
                n += _process_chunk(result, qc, chunk, fout)
                chunk = []
        if chunk:
            n += _process_chunk(result, qc, chunk, fout)
    logger.info("batch predict: %d predictions -> %s", n, output_path)
    return n


def _process_chunk(result, qc, chunk, fout) -> int:
    queries = [params_from_json(q, qc) if qc else q for q in chunk]
    supplemented = [(i, result.serving.supplement(q))
                    for i, q in enumerate(queries)]
    per_algo = []
    for algo, model in zip(result.algorithms, result.models):
        per_algo.append(dict(algo.batch_predict(model, supplemented)))
    for i, (raw, q) in enumerate(zip(chunk, queries)):
        predictions = [preds[i] for preds in per_algo]
        served = result.serving.serve(q, predictions)
        fout.write(json.dumps(
            {"query": raw, "prediction": _to_jsonable(served)},
            sort_keys=True) + "\n")
    return len(chunk)
