"""The train workflow: run an engine's pipeline, checkpoint, record metadata.

Parity with CoreWorkflow.runTrain (core/.../workflow/CoreWorkflow.scala:45-102)
and the CreateWorkflow entry (CreateWorkflow.scala:136-281): an EngineInstance
row is inserted with status INIT, the engine trains on the workflow context's
mesh, models are serialized into the Models store keyed by the instance id,
and the instance is marked COMPLETED. Failed runs leave the instance INIT so
it can never be deployed (SURVEY.md section 5 failure semantics).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
from typing import Optional

from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.params import EngineParams, params_to_json
from predictionio_tpu.data.event import UTC
from predictionio_tpu.storage.base import EngineInstance, Model
from predictionio_tpu.storage.registry import Storage
from predictionio_tpu.workflow.context import WorkflowContext, WorkflowParams
from predictionio_tpu.workflow.instrument import workflow_run_metrics
from predictionio_tpu.workflow.serialization import serialize_models

logger = logging.getLogger("pio.workflow")


def run_train(engine: Engine,
              engine_params: EngineParams,
              engine_factory: str = "",
              engine_variant: str = "default",
              workflow_params: Optional[WorkflowParams] = None,
              ctx: Optional[WorkflowContext] = None) -> EngineInstance:
    """Returns the COMPLETED EngineInstance (raises on failure)."""
    wp = workflow_params or WorkflowParams()
    ctx = ctx or WorkflowContext.create(
        mode="Training", batch=wp.batch, workflow_params=wp)

    instances = Storage.get_meta_data_engine_instances()
    instance = EngineInstance(
        status="INIT",
        start_time=_dt.datetime.now(tz=UTC),
        engine_id=engine_factory or type(engine).__name__,
        engine_version="1",
        engine_variant=engine_variant,
        engine_factory=engine_factory,
        batch=wp.batch,
        runtime_conf={k: str(v) for k, v in wp.runtime_conf.items()},
        data_source_params=json.dumps(
            params_to_json(engine_params.data_source_params), sort_keys=True),
        preparator_params=json.dumps(
            params_to_json(engine_params.preparator_params), sort_keys=True),
        algorithms_params=json.dumps(
            [{"name": n, "params": params_to_json(p)}
             for n, p in engine_params.algorithm_params_list], sort_keys=True),
        serving_params=json.dumps(
            params_to_json(engine_params.serving_params), sort_keys=True),
    )
    instance_id = instances.insert(instance)
    instance.id = instance_id  # insert returns the generated id; don't rely
    # on the backend mutating the record in place
    logger.info("EngineInstance %s created (INIT)", instance_id)

    blob = None
    # the whole run is one trace: a parent pipeline (or a multi-process
    # launcher) hands its context via PIO_TRACE_CONTEXT so this train's
    # record joins the parent's trace id in the flight recorder
    from predictionio_tpu.obs.trace_context import record_event
    from predictionio_tpu.obs.tracing import adopt

    with adopt("train", attrs={"instance": instance_id,
                               "variant": engine_variant}):
        with workflow_run_metrics("train", "pio_train"):
            # CoreWorkflow.runTrain:45 — train, persist, mark COMPLETED
            result = engine.train(
                ctx, engine_params,
                skip_sanity_check=wp.skip_sanity_check,
                stop_after_read=wp.stop_after_read,
                stop_after_prepare=wp.stop_after_prepare)

            if wp.save_model:
                persisted = engine.persist_models(ctx, instance_id, result)
                blob = serialize_models(persisted)
                Storage.get_model_data_models().insert(
                    Model(id=instance_id, models=blob))
                logger.info("models saved (%d bytes) for instance %s",
                            len(blob), instance_id)

            instance.status = "COMPLETED"
            instance.end_time = _dt.datetime.now(tz=UTC)
            instances.update(instance)
        record_event("train_completed", {
            "instance": instance_id, "variant": engine_variant})

    # register the completed instance as the variant's next release
    # (deploy/ subsystem: `pio releases` listing, warm deploys, rollback
    # lineage). Best-effort by contract — the train already succeeded.
    from predictionio_tpu.deploy.releases import record_release

    record_release(
        instance,
        train_seconds=(instance.end_time - instance.start_time
                       ).total_seconds(),
        blob=blob)
    if getattr(ctx, "checkpointer", None) is not None:
        # resume is for crashed/preempted runs only: a completed run clears
        # its snapshots so the next train never resumes from stale factors
        ctx.checkpointer.clear()
    logger.info("training completed: instance %s", instance_id)
    return instance


def load_for_deploy(engine: Engine, instance: EngineInstance,
                    ctx: Optional[WorkflowContext] = None):
    """Restore a TrainResult for serving from a COMPLETED instance
    (CreateServer.scala:204-206 + Engine.prepareDeploy:198)."""
    from predictionio_tpu.workflow.serialization import deserialize_models

    ctx = ctx or WorkflowContext.create(mode="Serving", batch=instance.batch)
    engine_params = engine_params_of_instance(engine, instance)
    model = Storage.get_model_data_models().get(instance.id)
    persisted = deserialize_models(model.models) if model else \
        [None] * len(engine_params.algorithm_params_list)
    return engine.prepare_deploy(ctx, engine_params, instance.id, persisted), ctx


def engine_params_of_instance(engine: Engine,
                              instance: EngineInstance) -> EngineParams:
    """EngineInstance params JSON -> EngineParams
    (Engine.engineInstanceToEngineParams:420 parity)."""
    data = {
        "datasource": {"params": json.loads(instance.data_source_params or "{}")},
        "preparator": {"params": json.loads(instance.preparator_params or "{}")},
        "algorithms": json.loads(instance.algorithms_params or "[]"),
        "serving": {"params": json.loads(instance.serving_params or "{}")},
    }
    return engine.engine_params_from_json(data)
