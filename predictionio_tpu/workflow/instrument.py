"""Shared run instrumentation for the train/evaluate workflows.

One context manager owns the whole harness: run counter by outcome,
end-to-end duration histogram, a ``collect_phases`` sink bridged into
``pio_phase_duration_seconds`` (published on success AND failure — a
failed run's partial phase breakdown is exactly what you debug with),
and the JAX device gauges registered on the process registry.
"""

from __future__ import annotations

import contextlib
import time

from predictionio_tpu.obs.jax_stats import register_jax_metrics
from predictionio_tpu.obs.registry import default_registry, exponential_buckets
from predictionio_tpu.utils.profiling import collect_phases

#: 100 ms .. ~27 min doubling — training runs, not request latencies
WORKFLOW_DURATION_BUCKETS = exponential_buckets(0.1, 2.0, 15)


def publish_phase_timings(sink: dict, workflow: str) -> None:
    """Bridge a ``collect_phases`` sink into the process registry so
    per-phase breakdowns (build/transfer/...) surface at /metrics."""
    if not sink:
        return
    hist = default_registry().histogram(
        "pio_phase_duration_seconds",
        "Host-phase wall time bridged from utils.profiling.collect_phases",
        labelnames=("workflow", "phase"), buckets=WORKFLOW_DURATION_BUCKETS)
    for name, seconds in sink.items():
        hist.observe(seconds, workflow=workflow, phase=name)


@contextlib.contextmanager
def workflow_run_metrics(workflow: str, metric_prefix: str):
    """Instrument one workflow run; yields the phase sink.

    ``workflow`` labels the phase timings ("train"/"evaluate");
    ``metric_prefix`` names the run metrics ("pio_train" ->
    pio_train_runs_total + pio_train_duration_seconds).
    """
    registry = register_jax_metrics(default_registry())
    runs = registry.counter(f"{metric_prefix}_runs_total",
                            f"{workflow} workflow runs by outcome",
                            labelnames=("status",))
    duration = registry.histogram(
        f"{metric_prefix}_duration_seconds",
        f"End-to-end {workflow} workflow wall time by outcome",
        labelnames=("status",), buckets=WORKFLOW_DURATION_BUCKETS)
    t0 = time.perf_counter()
    phases: dict = {}
    try:
        with collect_phases(phases):
            yield phases
    except BaseException:
        runs.inc(status="failed")
        duration.observe(time.perf_counter() - t0, status="failed")
        publish_phase_timings(phases, workflow)
        raise
    runs.inc(status="completed")
    duration.observe(time.perf_counter() - t0, status="completed")
    publish_phase_timings(phases, workflow)
