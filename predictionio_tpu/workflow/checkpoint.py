"""Mid-training checkpoint/resume.

The reference's only mid-training checkpoint is MLlib ALS's internal
`setCheckpointInterval(10)` (examples/.../ALSAlgorithm.scala:84); workflow-
level resume does not exist there (SURVEY.md §5 "Checkpoint / resume").
Here both are first-class: algorithms that accept a ``Checkpointer`` save
their training state (a pytree of numpy arrays) every N iterations/epochs
and resume from the latest snapshot after a crash or preemption — the
elastic-recovery story TPU preemptible slices need.

Format: one pickle per snapshot, written atomically (tmp file + rename) so
a crash mid-save never corrupts the latest good snapshot; `latest()` picks
the highest step. Snapshots hold host numpy pytrees (device arrays are
pulled to host), so they are mesh-shape independent: a run checkpointed on
8 chips can resume on 1 and vice versa.

Two safety properties:

* **Fingerprinted resume.** A snapshot can carry a `fingerprint` (hash of
  hyperparams + dataset identity, computed by the algorithm). `latest()`
  called with a fingerprint ignores snapshots whose fingerprint differs —
  so a crashed run restarted with different reg/seed/alpha, or against
  different data of the same shape, retrains from scratch instead of
  silently resuming from incompatible factors.
* **Restricted deserialization.** Snapshots are loaded with an unpickler
  that only resolves numpy array machinery and builtin containers —
  a writable checkpoint directory does not grant code execution in the
  training process (checkpoint dirs on shared/preemptible fleets have a
  weaker trust boundary than the model store). Algorithms therefore save
  plain pytrees of dict/list/tuple/ndarray/scalars only.
"""

from __future__ import annotations

import logging
import os
import pickle
import re
from typing import Any, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

#: step_<N>.pkl (no lineage tag) or step_<N>.<fp8>.pkl — the tag is the
#: first 8 hex chars of the run fingerprint, letting GC and resume treat
#: each run lineage independently without opening the files
_SNAP_RE = re.compile(r"^step_(\d+)(?:\.([0-9a-f]{8}))?\.pkl$")

#: exact (module, name) pairs the snapshot unpickler may resolve — the
#: ndarray reconstruction machinery only. Deliberately NOT whole modules:
#: e.g. `numpy.load` with allow_pickle would reopen the door to arbitrary
#: code execution via a second attacker-written file.
_SAFE_SYMBOLS = {
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if (module, name) in _SAFE_SYMBOLS or \
                (module == "numpy.dtypes" and name.endswith("DType")):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"snapshot references forbidden symbol {module}.{name}; "
            "checkpoints may only contain numpy pytrees")


def _safe_load(f) -> Any:
    return _RestrictedUnpickler(f).load()


def _tag(fingerprint: Optional[str]) -> Optional[str]:
    """8-hex-char filename tag for a run fingerprint (hashed, so any
    string works, not just hexdigests)."""
    if fingerprint is None:
        return None
    import hashlib

    return hashlib.blake2b(fingerprint.encode(),
                           digest_size=4).hexdigest()


class Checkpointer:
    """Directory of step-numbered snapshots with atomic writes."""

    def __init__(self, directory: str, interval: int = 10,
                 keep: int = 2):
        self.directory = directory
        self.interval = max(int(interval), 1)
        self.keep = max(int(keep), 1)
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int, fingerprint: Optional[str] = None) -> str:
        t = _tag(fingerprint)
        return os.path.join(self.directory,
                            f"step_{step}{'.' + t if t else ''}.pkl")

    def _scan(self):
        """[(step, tag_or_None, filename)] for every snapshot present."""
        out = []
        for name in os.listdir(self.directory):
            m = _SNAP_RE.match(name)
            if m:
                out.append((int(m.group(1)), m.group(2), name))
        return out

    def due(self, step: int) -> bool:
        return step > 0 and step % self.interval == 0

    def scoped(self, name: str) -> "Checkpointer":
        """A sub-checkpointer under `<dir>/<name>` — one namespace per
        algorithm, so a multi-algorithm engine never resumes one
        algorithm's training from another's snapshots."""
        return Checkpointer(os.path.join(self.directory, name),
                            interval=self.interval, keep=self.keep)

    def save(self, step: int, state: Any,
             fingerprint: Optional[str] = None) -> None:
        """state: a pytree of dict/list/tuple/ndarray/scalars; device
        arrays are host-copied. `fingerprint` ties the snapshot to the
        (hyperparams, dataset) that produced it — see `latest`."""
        import jax

        host = jax.tree.map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, state)
        path = self._path(step, fingerprint)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"step": step, "state": host,
                         "fingerprint": fingerprint}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._gc(fingerprint)

    def latest(self, fingerprint: Optional[str] = None
               ) -> Optional[Tuple[int, Any]]:
        """(step, state) of the newest readable, compatible snapshot.

        Scans steps newest-first. Unreadable, malformed, or forbidden
        snapshots are skipped with a warning; so are snapshots of a
        DIFFERENT lineage: with a fingerprint given, only snapshots
        carrying that exact fingerprint match; with fingerprint=None only
        untagged snapshots match — a fingerprint-less caller never
        resumes from some other run's tagged state (and vice versa).
        A restarted run whose params or data changed retrains from
        scratch rather than resuming from incompatible state. Reads
        never delete: stale lineages are left for their own run (or
        `clear`) — per-lineage `_gc` means they cannot starve this run's
        snapshots either."""
        entries = sorted(self._scan(), reverse=True,
                         key=lambda e: (e[0], e[1] or "", e[2]))
        want_tag = _tag(fingerprint)
        for step, tag, name in entries:
            path = os.path.join(self.directory, name)
            if tag != want_tag:
                continue          # other lineage, by filename alone
            try:
                with open(path, "rb") as f:
                    snap = _safe_load(f)
                if not isinstance(snap, dict):
                    raise ValueError(f"snapshot is {type(snap).__name__}, "
                                     "expected dict")
                step_v, state = snap["step"], snap["state"]
                # algorithms index into the state dict; a loadable file
                # with a non-dict state must also degrade to skip, not
                # crash the caller
                if not isinstance(state, dict):
                    raise ValueError(
                        f"snapshot state is {type(state).__name__}, "
                        "expected dict")
            except Exception as e:
                # the writable-dir threat model again: ANY malformed file
                # must degrade to "skip + warn", never crash the training
                # process at resume
                logger.warning("checkpoint %s unreadable (%s) — skipping",
                               path, e)
                continue
            if snap.get("fingerprint") != fingerprint:
                logger.warning(
                    "checkpoint %s fingerprint mismatch (snapshot %s, "
                    "run %s) — ignoring, training from scratch",
                    path, snap.get("fingerprint"), fingerprint)
                continue
            return step_v, state
        return None

    def clear(self) -> None:
        """Remove all snapshots, including per-algorithm scoped subdirs."""
        for root, _dirs, files in os.walk(self.directory):
            for name in files:
                if _SNAP_RE.match(name) or name.endswith(".tmp"):
                    os.unlink(os.path.join(root, name))

    def _gc(self, fingerprint: Optional[str] = None) -> None:
        """Keep the newest `keep` snapshots OF THIS LINEAGE (same filename
        tag); other lineages' files are never touched, so a concurrent or
        restarted run with different params cannot destroy this run's
        resume state (nor vice versa)."""
        tag = _tag(fingerprint)
        mine = sorted((step, name) for step, t, name in self._scan()
                      if t == tag)
        for _step, name in mine[:-self.keep]:
            try:
                os.unlink(os.path.join(self.directory, name))
            except OSError:
                pass


def checkpointer_of(ctx) -> Optional[Checkpointer]:
    """Pull the workflow-configured checkpointer out of a WorkflowContext
    (None when checkpointing is off or ctx is a bare object)."""
    return getattr(ctx, "checkpointer", None)
