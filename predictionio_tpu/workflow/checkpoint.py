"""Mid-training checkpoint/resume.

The reference's only mid-training checkpoint is MLlib ALS's internal
`setCheckpointInterval(10)` (examples/.../ALSAlgorithm.scala:84); workflow-
level resume does not exist there (SURVEY.md §5 "Checkpoint / resume").
Here both are first-class: algorithms that accept a ``Checkpointer`` save
their training state (a pytree of numpy arrays) every N iterations/epochs
and resume from the latest snapshot after a crash or preemption — the
elastic-recovery story TPU preemptible slices need.

Format: one pickle per snapshot, written atomically (tmp file + rename) so
a crash mid-save never corrupts the latest good snapshot; `latest()` picks
the highest step. Snapshots hold host numpy pytrees (device arrays are
pulled to host), so they are mesh-shape independent: a run checkpointed on
8 chips can resume on 1 and vice versa.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Optional, Tuple

import numpy as np

_SNAP_RE = re.compile(r"^step_(\d+)\.pkl$")


class Checkpointer:
    """Directory of step-numbered snapshots with atomic writes."""

    def __init__(self, directory: str, interval: int = 10,
                 keep: int = 2):
        self.directory = directory
        self.interval = max(int(interval), 1)
        self.keep = max(int(keep), 1)
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}.pkl")

    def due(self, step: int) -> bool:
        return step > 0 and step % self.interval == 0

    def scoped(self, name: str) -> "Checkpointer":
        """A sub-checkpointer under `<dir>/<name>` — one namespace per
        algorithm, so a multi-algorithm engine never resumes one
        algorithm's training from another's snapshots."""
        return Checkpointer(os.path.join(self.directory, name),
                            interval=self.interval, keep=self.keep)

    def save(self, step: int, state: Any) -> None:
        """state: any picklable pytree; device arrays are host-copied."""
        import jax

        host = jax.tree.map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, state)
        tmp = self._path(step) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"step": step, "state": host}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(step))
        self._gc()

    def latest(self) -> Optional[Tuple[int, Any]]:
        """(step, state) of the newest snapshot, or None."""
        best = -1
        for name in os.listdir(self.directory):
            m = _SNAP_RE.match(name)
            if m:
                best = max(best, int(m.group(1)))
        if best < 0:
            return None
        with open(self._path(best), "rb") as f:
            snap = pickle.load(f)
        return snap["step"], snap["state"]

    def clear(self) -> None:
        """Remove all snapshots, including per-algorithm scoped subdirs."""
        for root, _dirs, files in os.walk(self.directory):
            for name in files:
                if _SNAP_RE.match(name) or name.endswith(".tmp"):
                    os.unlink(os.path.join(root, name))

    def _gc(self) -> None:
        steps = sorted(
            int(m.group(1)) for name in os.listdir(self.directory)
            if (m := _SNAP_RE.match(name)))
        for s in steps[:-self.keep]:
            try:
                os.unlink(self._path(s))
            except OSError:
                pass


def checkpointer_of(ctx) -> Optional[Checkpointer]:
    """Pull the workflow-configured checkpointer out of a WorkflowContext
    (None when checkpointing is off or ctx is a bare object)."""
    return getattr(ctx, "checkpointer", None)
