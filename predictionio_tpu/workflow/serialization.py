"""Model blob (de)serialization.

Replaces the reference's Kryo/chill model blob machinery
(core/.../workflow/CoreWorkflow.scala:76-81, CreateServer.scala:62-76): every
model is a picklable Python object; pytrees of jax Arrays are converted to
numpy first so blobs are host-portable and loadable without devices.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List


class _RetrainSentinel:
    """Marks an algorithm slot whose model is retrained at deploy
    (the reference's Unit model, PAlgorithm.scala:112)."""

    def __repr__(self):
        return "RETRAIN_ON_DEPLOY"


RETRAIN_ON_DEPLOY = _RetrainSentinel()


def _to_host(obj: Any) -> Any:
    """Pull any jax arrays in a pytree down to numpy."""
    try:
        import jax

        leaves, treedef = jax.tree.flatten(obj)
        if any(isinstance(x, jax.Array) for x in leaves):
            return jax.tree.unflatten(
                treedef, [jax.device_get(x) if isinstance(x, jax.Array) else x
                          for x in leaves])
    except (ImportError, TypeError):
        pass
    return obj


def serialize_models(models: List[Any]) -> bytes:
    payload = [RETRAIN_ON_DEPLOY if m is None else _to_host(m) for m in models]
    buf = io.BytesIO()
    pickle.dump(payload, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def deserialize_models(blob: bytes) -> List[Any]:
    models = pickle.loads(blob)
    return [None if isinstance(m, _RetrainSentinel) else m for m in models]
