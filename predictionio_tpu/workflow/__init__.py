"""Workflow engine (L4): train / eval / deploy drivers over a device mesh.

Rebuilds core/workflow (SURVEY.md section 2.6). The reference's
WorkflowContext creates the one SparkContext; here it creates the one
`jax.sharding.Mesh` (single-controller JAX replaces the Spark driver).
"""

from predictionio_tpu.workflow.context import WorkflowContext, WorkflowParams
from predictionio_tpu.workflow.train import run_train
from predictionio_tpu.workflow.evaluate import run_evaluation

__all__ = ["WorkflowContext", "WorkflowParams", "run_train", "run_evaluation"]
