"""WorkflowContext: the single factory for device meshes.

Parity with the reference WorkflowContext (core/.../workflow/WorkflowContext.scala:28-47)
— the only place a SparkContext is created becomes the only place a
`jax.sharding.Mesh` is built. Everything downstream (DataSource reads,
Algorithm.train, serving) receives this context.

TPU-first design notes:
  * mesh axes default to a single "data" axis over all local devices; engine
    variants may request e.g. {"mesh_shape": [4, 2], "mesh_axes":
    ["data", "model"]} through runtime_conf (the sparkConf analog)
  * jax is imported lazily so storage/CLI paths never pay jax import cost
  * `local_mesh()` (mesh of 1) is the analog of the reference's L-components
    running on the driver (LAlgorithm.scala:48)
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, Optional, Sequence, Tuple

logger = logging.getLogger("pio.workflow")


@dataclasses.dataclass
class WorkflowParams:
    """WorkflowParams.scala:32 — workflow-level flags."""

    batch: str = ""
    verbose: int = 2
    save_model: bool = True
    skip_sanity_check: bool = False
    stop_after_read: bool = False
    stop_after_prepare: bool = False
    #: jax/XLA settings overlay (the sparkEnv/sparkConf analog)
    runtime_conf: Dict[str, str] = dataclasses.field(default_factory=dict)


def mesh_of(ctx):
    """The mesh of a workflow context, or a fresh default mesh when the
    caller passed a bare context (tests, embedded use). Shared by every
    algorithm that trains on the mesh."""
    mesh = getattr(ctx, "mesh", None)
    if mesh is None:
        mesh = WorkflowContext.create(mode="Training").mesh
    return mesh


class WorkflowContext:
    """Holds the device mesh + app metadata for one workflow run."""

    def __init__(self, mode: str = "", batch: str = "",
                 mesh_shape: Optional[Sequence[int]] = None,
                 mesh_axes: Optional[Sequence[str]] = None,
                 devices=None):
        self.mode = mode
        self.batch = batch
        self._mesh = None
        self._mesh_shape = tuple(mesh_shape) if mesh_shape else None
        self._mesh_axes = tuple(mesh_axes) if mesh_axes else None
        self._devices = devices
        #: mid-training Checkpointer (workflow/checkpoint.py), set from
        #: runtime_conf checkpoint_dir/checkpoint_interval; None = off
        self.checkpointer = None
        logger.info("WorkflowContext: mode=%s batch=%s", mode, batch)

    # -- mesh ---------------------------------------------------------------
    @property
    def mesh(self):
        """The mesh, built lazily on first use (WorkflowContext.scala:45)."""
        if self._mesh is None:
            self._mesh = self._build_mesh()
        return self._mesh

    def _build_mesh(self):
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devices = self._devices if self._devices is not None else jax.devices()
        if self._mesh_shape is None:
            shape: Tuple[int, ...] = (len(devices),)
            axes: Tuple[str, ...] = ("data",)
        else:
            shape = self._mesh_shape
            axes = self._mesh_axes or tuple(
                f"axis{i}" for i in range(len(shape)))
        n = 1
        for s in shape:
            n *= s
        arr = np.asarray(devices[:n]).reshape(shape)
        logger.info("mesh: shape=%s axes=%s over %d device(s)", shape, axes, n)
        return Mesh(arr, axis_names=axes)

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    def local_mesh(self):
        """Mesh of one device — the L-component path (SURVEY.md P6).
        Honors the context's device override like _build_mesh does."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        devices = self._devices if self._devices is not None else jax.devices()
        return Mesh(np.asarray(devices[:1]), axis_names=("data",))

    # -- factory (WorkflowContext.apply parity) ------------------------------
    @classmethod
    def create(cls, mode: str = "", batch: str = "",
               workflow_params: Optional[WorkflowParams] = None,
               devices=None) -> "WorkflowContext":
        conf = dict(workflow_params.runtime_conf) if workflow_params else {}
        mesh_shape = conf.get("mesh_shape")
        if isinstance(mesh_shape, str):
            mesh_shape = [int(x) for x in mesh_shape.split(",") if x]
        mesh_axes = conf.get("mesh_axes")
        if isinstance(mesh_axes, str):
            mesh_axes = [x for x in mesh_axes.split(",") if x]
        ctx = cls(mode=mode, batch=batch, mesh_shape=mesh_shape,
                  mesh_axes=mesh_axes, devices=devices)
        ckpt_dir = conf.get("checkpoint_dir")
        if ckpt_dir:
            from predictionio_tpu.workflow.checkpoint import Checkpointer

            ctx.checkpointer = Checkpointer(
                ckpt_dir, interval=int(conf.get("checkpoint_interval", 10)))
        return ctx
