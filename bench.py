"""Benchmark: the judged configs (BASELINE.md) as one unkillable suite.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Design (round-2 rebuild after BENCH_r01 died in backend init):

* The orchestrator process NEVER imports jax. Every config — and the
  backend probe itself — runs in a subprocess with a hard timeout, so a
  wedged TPU tunnel or a crashing config costs that one subprocess, not
  the suite: partial results always beat rc=1.
* Platform resolution: BENCH_PLATFORM env override, else probe the
  JAX_PLATFORMS platform (the real chip) with retry+backoff, else fall
  back to CPU. Workers force the platform through jax.config because
  device plugins override the env var (utils/config.honor_jax_platforms).
* Baselines are MEASURED single-process numpy runs of the same math (the
  stand-in for stock Spark-local; the reference publishes no numbers).
  Only the 20M config extrapolates — linearly from a measured >=4M-rating
  numpy run, flagged in its JSON.
* MFU: an analytic FLOP model of the ALS sweep (gram nnz*K^2 + solve
  segs*K^3 MACs) against the chip's bf16 peak — an estimate (the math
  runs in f32), reported per config next to wall-clock.

Configs:
  pipeline_ml100k   the judged path: 100k rate events -> sqlite event
                    store -> run_train workflow (`pio train` wall-clock)
                    -> deploy -> 1k HTTP /queries.json, p50/p99
  als_ml100k        recommendation ALS kernel @ MovieLens-100K shape
  cooccurrence_ml1m similarproduct cooccurrence @ ML-1M shape
  naive_bayes_spam  classification NB, spam/ham scale
  ecommerce_implicit_als  implicit ALS (view+buy confidence) + top-N
  eval_sweep_3fold_3rank  cross-validated ALS hyperparameter sweep
  als_ml20m         MovieLens-20M-shape ALS on one chip: 20M ratings,
                    138k x 27k, string-id assignment + data build +
                    train + RMSE all timed (north star, BASELINE.md)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

RANK, ITERS, REG = 10, 20, 0.01


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Synthetic data + measured numpy baselines (no jax)
# ---------------------------------------------------------------------------

def synthetic_ratings(n_users, n_items, nnz, seed=0, implicit=False):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, nnz).astype(np.int32)
    items = rng.integers(0, n_items, nnz).astype(np.int32)
    latent_u = rng.normal(size=(n_users, 4))
    latent_v = rng.normal(size=(n_items, 4))
    raw = np.einsum("nk,nk->n", latent_u[users], latent_v[items])
    if implicit:
        ratings = (raw > 0).astype(np.float32) + 1.0
    else:
        ratings = np.clip(np.round(2.5 + raw), 1, 5).astype(np.float32)
    return users, items, ratings


def _np_half_sweep(F, seg, tgt, val, n_seg, rank, reg, implicit=False,
                   alpha=1.0, chunk=1_000_000):
    """One numpy half-sweep (same math as the device kernel), chunked so
    the [n, K, K] outer-product buffer stays bounded at 20M nnz."""
    gram = np.zeros((n_seg, rank, rank), np.float32)
    rhs = np.zeros((n_seg, rank), np.float32)
    cnt = np.zeros(n_seg, np.float32)
    for lo in range(0, len(seg), chunk):
        s, t, v = seg[lo:lo + chunk], tgt[lo:lo + chunk], val[lo:lo + chunk]
        f = F[t]
        if implicit:
            w = alpha * np.abs(v)                     # c - 1
            p = (v > 0).astype(np.float32)
            outer = np.einsum("nk,nl->nkl", f, f) * w[:, None, None]
            np.add.at(gram, s, outer)
            np.add.at(rhs, s, f * ((1.0 + w) * p)[:, None])
            np.add.at(cnt, s, w)
        else:
            outer = np.einsum("nk,nl->nkl", f, f)
            np.add.at(gram, s, outer)
            np.add.at(rhs, s, f * v[:, None])
            np.add.at(cnt, s, 1.0)
    if implicit:
        gram = gram + (F.T @ F)[None, :, :]
    A = gram + (reg * np.maximum(cnt, 1.0))[:, None, None] * \
        np.eye(rank, dtype=np.float32)
    return np.linalg.solve(A, rhs[..., None])[..., 0]


def numpy_als_baseline(users, items, ratings, nu, ni, rank, iters, reg=REG,
                       implicit=False, alpha=1.0, measure_iters=None,
                       seed=1):
    """MEASURED full numpy ALS run (both sides per iteration). When
    `measure_iters` < iters, the measured iterations are extrapolated
    linearly (flagged by the caller in its JSON)."""
    rng = np.random.default_rng(seed)
    V = rng.normal(size=(ni, rank)).astype(np.float32) / np.sqrt(rank)
    run = min(measure_iters or iters, iters)
    t0 = time.perf_counter()
    for _ in range(run):
        U = _np_half_sweep(V, users, items, ratings, nu, rank, reg,
                           implicit, alpha)
        V = _np_half_sweep(U, items, users, ratings, ni, rank, reg,
                           implicit, alpha)
    dt = time.perf_counter() - t0
    return dt * (iters / run), run


# ---------------------------------------------------------------------------
# FLOP model / MFU
# ---------------------------------------------------------------------------

def als_model_flops(nnz, nu, ni, rank, iters):
    """Analytic FLOPs of `iters` full ALS iterations: Gramian assembly
    (one K x K outer-accumulate per rating, both sides) + rhs + batched
    Cholesky solves (K^3/3 factor + 2 K^2 triangular solves/segment)."""
    gram = 2 * nnz * rank * rank * 2          # both sides, 2 flops/MAC
    rhs = 2 * nnz * rank * 2
    solve = (nu + ni) * (rank ** 3 / 3 + 2 * rank * rank) * 2
    return iters * (gram + rhs + solve)


_PEAK_BF16 = (  # (device_kind substring, peak bf16 FLOP/s per chip)
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
)


def peak_flops(device_kind: str):
    kind = (device_kind or "").lower()
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None     # unknown chip / CPU: no MFU claim


# ---------------------------------------------------------------------------
# Worker-side backend setup
# ---------------------------------------------------------------------------

def setup_backend(platform: str):
    """Import jax pinned to `platform`. jax.config is authoritative —
    device plugins (the tunneled TPU) override JAX_PLATFORMS alone and
    can hang the process when the remote chip is unreachable."""
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)
    devices = jax.devices()
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(devices)[:1], axis_names=("data",))
    return jax, devices, mesh


# ---------------------------------------------------------------------------
# Configs — each returns a detail dict
# ---------------------------------------------------------------------------

def cfg_pipeline_ml100k(jax, mesh, platform):
    """The judged workload boundary (BASELINE.md target metrics): events
    in the store -> `pio train` equivalent -> deploy -> HTTP query
    latency. Mirrors the reference quickstart
    (tests/pio_tests/scenarios/quickstart_test.py:33-95,
    CreateServer.scala:597-604)."""
    import asyncio
    import tempfile

    from predictionio_tpu.data import DataMap, Event
    from predictionio_tpu.engines.recommendation import (
        default_engine_params, engine as engine_factory)
    from predictionio_tpu.storage import App, Storage
    from predictionio_tpu.workflow import run_train
    from predictionio_tpu.workflow.train import load_for_deploy

    nu, ni, nnz = 943, 1682, 100_000
    users, items, ratings = synthetic_ratings(nu, ni, nnz, seed=11)

    with tempfile.TemporaryDirectory() as tmp:
        Storage.configure({
            "sources": {"DB": {"TYPE": "sqlite",
                               "PATH": os.path.join(tmp, "bench.db")}},
            "repositories": {
                "METADATA": {"NAME": "pio", "SOURCE": "DB"},
                "EVENTDATA": {"NAME": "pio", "SOURCE": "DB"},
                "MODELDATA": {"NAME": "pio", "SOURCE": "DB"},
            },
        })
        from predictionio_tpu.data.eventstore import clear_cache
        clear_cache()
        apps = Storage.get_meta_data_apps()
        app_id = apps.insert(App(id=0, name="BenchApp"))
        store = Storage.get_events()
        store.init_channel(app_id)

        t0 = time.perf_counter()
        batch = []
        for u, i, r in zip(users, items, ratings):
            batch.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap({"rating": float(r)})))
            if len(batch) >= 10_000:
                store.insert_batch(batch, app_id)
                batch = []
        if batch:
            store.insert_batch(batch, app_id)
        import_s = time.perf_counter() - t0

        engine = engine_factory()
        ep = default_engine_params("BenchApp", rank=RANK,
                                   num_iterations=ITERS)
        t0 = time.perf_counter()
        instance = run_train(
            engine, ep,
            engine_factory="predictionio_tpu.engines.recommendation:engine")
        train_s = time.perf_counter() - t0   # the `pio train` wall-clock

        t0 = time.perf_counter()
        result, ctx = load_for_deploy(engine, instance)
        deploy_s = time.perf_counter() - t0

        from aiohttp.test_utils import TestClient, TestServer

        from predictionio_tpu.server.query_server import create_query_server

        server = create_query_server(engine, result, instance, ctx)
        lat = []

        async def drive():
            c = TestClient(TestServer(server.app))
            await c.start_server()
            try:
                for q in range(20):        # warm-up (compile + caches)
                    await c.post("/queries.json",
                                 json={"user": f"u{q % nu}", "num": 10})
                for q in range(1000):
                    t = time.perf_counter()
                    resp = await c.post(
                        "/queries.json",
                        json={"user": f"u{q % nu}", "num": 10})
                    assert resp.status == 200, await resp.text()
                    body = await resp.json()
                    assert len(body["itemScores"]) == 10
                    lat.append(time.perf_counter() - t)
            finally:
                await c.close()

        asyncio.run(drive())
        Storage.reset()
        clear_cache()

    lat_ms = np.asarray(lat) * 1e3
    p50, p99 = float(np.percentile(lat_ms, 50)), float(np.percentile(lat_ms, 99))
    return {
        "elapsed_s": round(train_s, 3),
        "baseline_s": None,
        "note": (f"import {import_s:.1f}s, pio-train {train_s:.2f}s, "
                 f"deploy {deploy_s:.2f}s, query p50 {p50:.2f}ms "
                 f"p99 {p99:.2f}ms over 1000 HTTP queries"),
        "import_s": round(import_s, 2),
        "train_s": round(train_s, 3),
        "deploy_s": round(deploy_s, 3),
        "query_p50_ms": round(p50, 3),
        "query_p99_ms": round(p99, 3),
    }


def cfg_als_ml100k(jax, mesh, platform):
    """Config 1 kernel: recommendation ALS @ ML-100K shape; measured
    numpy baseline is a FULL run of the same math (not extrapolated)."""
    from predictionio_tpu.models.als import ALSData, ALSParams, train_als
    from predictionio_tpu.models.als import rmse as als_rmse

    nu, ni, nnz = 943, 1682, 100_000
    users, items, ratings = synthetic_ratings(nu, ni, nnz)
    base, measured = numpy_als_baseline(users, items, ratings, nu, ni,
                                        RANK, ITERS)
    params = ALSParams(rank=RANK, num_iterations=ITERS, reg=REG,
                       chunk_size=16384)
    data = ALSData.build(users, items, ratings, nu, ni, n_shards=1)
    train_als(mesh, data, params)          # warm-up compile
    t0 = time.perf_counter()
    data = ALSData.build(users, items, ratings, nu, ni, n_shards=1)
    U, V = train_als(mesh, data, params)
    elapsed = time.perf_counter() - t0
    err = als_rmse(U, V, users, items, ratings)
    assert np.isfinite(err), "ALS diverged"
    flops = als_model_flops(nnz, nu, ni, RANK, ITERS)
    return {"elapsed_s": round(elapsed, 4), "baseline_s": round(base, 3),
            "baseline_measured_iters": measured,
            "model_flops": flops,
            "note": f"train-RMSE {err:.3f}"}


def cfg_als_ml20m(jax, mesh, platform):
    """North-star shape (BASELINE.md): 20M ratings, 138k users x 27k
    items, trained end-to-end on one chip — string-id assignment, data
    build, train, RMSE all timed. On the CPU fallback the shape scales
    down (flagged) so partial results still arrive."""
    from predictionio_tpu.data.bimap import assign_indices
    from predictionio_tpu.models.als import ALSData, ALSParams, train_als
    from predictionio_tpu.models.als import rmse as als_rmse

    if platform == "cpu":
        nu, ni, nnz, iters, scaled = 30_000, 10_000, 2_000_000, 5, True
    else:
        nu, ni, nnz, iters, scaled = 138_000, 27_000, 20_000_000, ITERS, False
    users, items, ratings = synthetic_ratings(nu, ni, nnz, seed=20)

    # the BiMap.scala:126-128 hard part: string ids -> contiguous indices
    user_ids = users.astype("U8")
    item_ids = items.astype("U8")
    t0 = time.perf_counter()
    user_vocab, user_codes = assign_indices(user_ids)
    item_vocab, item_codes = assign_indices(item_ids)
    id_assign_s = time.perf_counter() - t0
    del user_ids, item_ids
    nu_r, ni_r = len(user_vocab), len(item_vocab)

    t0 = time.perf_counter()
    data = ALSData.build(user_codes, item_codes, ratings, nu_r, ni_r,
                         n_shards=1)
    build_s = time.perf_counter() - t0

    params = ALSParams(rank=RANK, num_iterations=iters, reg=REG,
                       chunk_size=16384)
    train_als(mesh, data, params)               # warm-up compile
    t0 = time.perf_counter()
    U, V = train_als(mesh, data, params)
    train_s = time.perf_counter() - t0
    err = als_rmse(U, V, user_codes[:1_000_000], item_codes[:1_000_000],
                   ratings[:1_000_000])
    assert np.isfinite(err), "ALS diverged"

    # numpy baseline measured on a >=4M-rating run, extrapolated linearly
    cap = min(nnz, 4_000_000)
    bi = max(1, min(2, iters))
    base_cap, measured = numpy_als_baseline(
        user_codes[:cap], item_codes[:cap], ratings[:cap], nu_r, ni_r,
        RANK, iters, measure_iters=bi)
    base = base_cap * (nnz / cap)
    flops = als_model_flops(nnz, nu_r, ni_r, RANK, iters)
    return {"elapsed_s": round(train_s, 3), "baseline_s": round(base, 2),
            "baseline_measured_iters": measured,
            "baseline_extrapolated_from_nnz": cap,
            "model_flops": flops, "scaled_for_cpu": scaled,
            "nnz": nnz,
            "note": (f"{nnz / 1e6:.0f}M ratings {nu_r}x{ni_r}: id-assign "
                     f"{id_assign_s:.1f}s, build {build_s:.1f}s, train "
                     f"{train_s:.2f}s ({iters} iters), RMSE {err:.3f}"),
            "id_assign_s": round(id_assign_s, 2),
            "build_s": round(build_s, 2)}


def cfg_cooccurrence(jax, mesh, platform):
    """Config 2: similarproduct cooccurrence @ ML-1M shape."""
    import jax.numpy as jnp

    from predictionio_tpu.models.cooccurrence import distinct_pairs

    nu, ni, nnz = 6040, 3706, 1_000_000
    users, items, _ = synthetic_ratings(nu, ni, nnz, seed=2)
    users, items = distinct_pairs(users, items)
    n_top = 20

    # numpy baseline: same math — dense A^T A + per-row top-N
    t0 = time.perf_counter()
    a = np.zeros((nu, ni), np.float32)
    a[users, items] = 1.0
    c_np = a.T @ a
    np.fill_diagonal(c_np, 0.0)
    np.argpartition(-c_np, kth=n_top, axis=1)[:, :n_top]
    base = time.perf_counter() - t0

    @jax.jit
    def count_topn(u, i):
        am = jnp.zeros((nu, ni), jnp.float32).at[u, i].set(1.0)
        c = am.T @ am
        c = c * (1.0 - jnp.eye(ni, dtype=jnp.float32))
        return jax.lax.top_k(c, n_top)

    count_topn(jnp.asarray(users), jnp.asarray(items))   # warm-up
    t0 = time.perf_counter()
    scores, idx = count_topn(jnp.asarray(users), jnp.asarray(items))
    jax.block_until_ready((scores, idx))
    elapsed = time.perf_counter() - t0
    # matmul-dominated: A^T A is 2 * nu * ni^2 flops
    flops = 2.0 * nu * ni * ni
    return {"elapsed_s": round(elapsed, 4), "baseline_s": round(base, 3),
            "model_flops": flops,
            "note": f"{len(users)} distinct pairs"}


def cfg_naive_bayes(jax, mesh, platform):
    """Config 3: classification NaiveBayes, spam/ham-scale."""
    from predictionio_tpu.models.naive_bayes import train_multinomial_nb

    n_docs, vocab = 20_000, 2_000
    rng = np.random.default_rng(3)
    labels = np.where(rng.random(n_docs) < 0.4, "spam", "ham")
    X = rng.poisson(
        np.where((labels == "spam")[:, None],
                 rng.random(vocab) * 2.0, rng.random(vocab) * 1.2)
    ).astype(np.float32)

    # numpy baseline: same math (count, smooth, log, score matmul)
    t0 = time.perf_counter()
    lv, codes = np.unique(labels, return_inverse=True)
    counts = np.zeros((len(lv), vocab), np.float64)
    np.add.at(counts, codes, X)
    prior = np.log(np.bincount(codes) / n_docs)
    prob = np.log((counts + 1.0) / (counts + 1.0).sum(1, keepdims=True))
    (X @ prob.T.astype(np.float32) + prior[None, :]).argmax(1)
    base = time.perf_counter() - t0

    model = train_multinomial_nb(X, labels)              # warm-up
    t0 = time.perf_counter()
    model = train_multinomial_nb(X, labels)
    pred = model.predict(X)
    elapsed = time.perf_counter() - t0
    acc = float((pred == labels).mean())
    assert acc > 0.9, f"NB accuracy {acc}"
    return {"elapsed_s": round(elapsed, 4), "baseline_s": round(base, 3),
            "note": f"accuracy {acc:.3f}"}


def cfg_ecommerce(jax, mesh, platform):
    """Config 4: ecommerce implicit ALS (view+buy confidence) + top-N;
    measured numpy baseline runs the same implicit math in full."""
    import jax.numpy as jnp

    from predictionio_tpu.models.als import ALSData, ALSParams, train_als

    nu, ni, nnz = 2000, 1500, 200_000
    users, items, ratings = synthetic_ratings(nu, ni, nnz, seed=4,
                                              implicit=True)
    iters = 10
    base, measured = numpy_als_baseline(users, items, ratings, nu, ni,
                                        RANK, iters, implicit=True)
    params = ALSParams(rank=RANK, num_iterations=iters, reg=REG,
                       implicit_prefs=True, alpha=1.0, chunk_size=16384)

    @jax.jit
    def topn(u_all, v):
        return jax.lax.top_k(u_all @ v.T, 10)

    data = ALSData.build(users, items, ratings, nu, ni, n_shards=1)
    U, V = train_als(mesh, data, params)   # warm-up train ...
    jax.block_until_ready(topn(jnp.asarray(U), jnp.asarray(V)))
    t0 = time.perf_counter()
    data = ALSData.build(users, items, ratings, nu, ni, n_shards=1)
    U, V = train_als(mesh, data, params)
    scores, idx = topn(jnp.asarray(U), jnp.asarray(V))
    jax.block_until_ready((scores, idx))
    elapsed = time.perf_counter() - t0
    flops = als_model_flops(nnz, nu, ni, RANK, iters)
    return {"elapsed_s": round(elapsed, 4), "baseline_s": round(base, 3),
            "baseline_measured_iters": measured, "model_flops": flops,
            "note": "implicit ALS + batch top-10"}


def cfg_eval_sweep(jax, mesh, platform):
    """Config 5: 3-fold x 3-rank cross-validated ALS sweep; the numpy
    baseline runs the IDENTICAL sweep in full."""
    from predictionio_tpu.models.als import ALSData, ALSParams, train_als
    from predictionio_tpu.models.als import rmse as als_rmse

    nu, ni, nnz = 943, 1682, 100_000
    users, items, ratings = synthetic_ratings(nu, ni, nnz, seed=5)
    k_fold, ranks, iters = 3, (8, 10, 12), 5
    fold_of = np.arange(nnz) % k_fold

    t0 = time.perf_counter()
    for rank in ranks:
        for f in range(k_fold):
            tr = fold_of != f
            numpy_als_baseline(users[tr], items[tr], ratings[tr], nu, ni,
                               rank, iters)
    base = time.perf_counter() - t0

    def sweep():
        best = (None, np.inf)
        for rank in ranks:
            params = ALSParams(rank=rank, num_iterations=iters, reg=REG,
                               chunk_size=16384)
            errs = []
            for f in range(k_fold):
                tr = fold_of != f
                te = ~tr
                data = ALSData.build(users[tr], items[tr], ratings[tr],
                                     nu, ni, n_shards=1)
                U, V = train_als(mesh, data, params)
                errs.append(als_rmse(U, V, users[te], items[te],
                                     ratings[te]))
            mean_err = float(np.mean(errs))
            if mean_err < best[1]:
                best = (rank, mean_err)
        return best

    sweep()                                 # warm-up (compile per rank)
    t0 = time.perf_counter()
    best_rank, best_err = sweep()
    elapsed = time.perf_counter() - t0
    flops = sum(als_model_flops(nnz * (k_fold - 1) // k_fold, nu, ni, r,
                                iters) * k_fold for r in ranks)
    return {"elapsed_s": round(elapsed, 4), "baseline_s": round(base, 3),
            "model_flops": flops,
            "note": f"best rank {best_rank}, test-RMSE {best_err:.3f}"}


CONFIGS = {
    "pipeline_ml100k": (cfg_pipeline_ml100k, 1200),
    "als_ml100k": (cfg_als_ml100k, 900),
    "cooccurrence_ml1m": (cfg_cooccurrence, 600),
    "naive_bayes_spam": (cfg_naive_bayes, 600),
    "ecommerce_implicit_als": (cfg_ecommerce, 900),
    "eval_sweep_3fold_3rank": (cfg_eval_sweep, 1200),
    "als_ml20m": (cfg_als_ml20m, 2700),
}


# ---------------------------------------------------------------------------
# Worker entry points
# ---------------------------------------------------------------------------

def worker_probe(platform: str) -> None:
    jax, devices, _mesh = setup_backend(platform)
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    print(json.dumps({"ok": True, "platform": platform,
                      "n_devices": len(devices),
                      "device_kind": devices[0].device_kind}), flush=True)


def worker_config(name: str, platform: str) -> None:
    fn, _budget = CONFIGS[name]
    jax, devices, mesh = setup_backend(platform)
    t0 = time.perf_counter()
    detail = fn(jax, mesh, platform)
    detail.update({
        "name": name, "platform": platform,
        "device_kind": devices[0].device_kind,
        "total_s": round(time.perf_counter() - t0, 2),
    })
    base, elapsed = detail.get("baseline_s"), detail.get("elapsed_s")
    if base and elapsed:
        detail["speedup"] = round(base / elapsed, 2)
    peak = peak_flops(devices[0].device_kind)
    if peak and detail.get("model_flops") and elapsed:
        detail["mfu"] = round(detail["model_flops"] / elapsed / peak, 5)
    detail.pop("model_flops", None)
    print("BENCH_DETAIL " + json.dumps(detail), flush=True)


# ---------------------------------------------------------------------------
# Orchestrator (no jax in this process)
# ---------------------------------------------------------------------------

def _last_json(out: str):
    """Parse the last JSON-looking line of worker stdout; None on any
    malformed/truncated output (a killed worker must never crash the
    orchestrator's collection loop)."""
    for line in reversed((out or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def _run_sub(args, timeout):
    """Run a worker subprocess; (rc, stdout, stderr_tail). rc=124 on
    timeout — the subprocess is killed, the suite lives on."""
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + args,
            capture_output=True, text=True, timeout=timeout)
        return p.returncode, p.stdout, p.stderr[-2000:]
    except subprocess.TimeoutExpired as e:
        out = e.stdout or b""
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return 124, out, f"timeout after {timeout}s"


def resolve_platform():
    """BENCH_PLATFORM override, else probe the env-configured platform
    (the real chip) with retries + backoff, else CPU."""
    override = os.environ.get("BENCH_PLATFORM")
    if override:
        log(f"[bench] platform forced to {override} via BENCH_PLATFORM")
        rc, out, err = _run_sub(["--probe", override], timeout=420)
        if rc == 0:
            return override, _last_json(out)
        log(f"[bench] forced platform {override} probe FAILED (rc={rc}) — "
            "falling back to CPU")
        return "cpu", None

    plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip() or "tpu"
    plat = None if plat == "cpu" else plat

    if plat:
        for attempt, budget in enumerate((240, 240, 360)):
            rc, out, err = _run_sub(["--probe", plat], timeout=budget)
            info = _last_json(out) if rc == 0 else None
            if info:
                log(f"[bench] platform {plat} up: "
                    f"{info['n_devices']} x {info['device_kind']}")
                return plat, info
            log(f"[bench] probe {plat} attempt {attempt + 1} failed "
                f"(rc={rc}): {err.strip().splitlines()[-1] if err.strip() else 'no output'}")
            time.sleep(10 * (attempt + 1))
    log("[bench] no accelerator reachable — falling back to CPU")
    rc, out, err = _run_sub(["--probe", "cpu"], timeout=240)
    return "cpu", (_last_json(out) if rc == 0 else None)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe")
    ap.add_argument("--config")
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--only", help="comma-separated config subset")
    args = ap.parse_args()

    if args.probe:
        worker_probe(args.probe)
        return
    if args.config:
        worker_config(args.config, args.platform)
        return

    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE_S",
                                                       5400))
    platform, _info = resolve_platform()

    names = list(CONFIGS)
    if args.only:
        names = args.only.split(",")
        unknown = [n for n in names if n not in CONFIGS]
        if unknown:
            log(f"[bench] unknown config(s) {unknown}; "
                f"known: {list(CONFIGS)}")
            sys.exit(2)

    details, failures = [], []
    for name in names:
        _fn, budget = CONFIGS[name]
        remain = deadline - time.monotonic()
        if remain < 60:
            failures.append({"name": name, "error": "suite deadline hit"})
            log(f"[bench] {name}: SKIPPED (deadline)")
            continue
        rc, out, err = _run_sub(
            ["--config", name, "--platform", platform],
            timeout=min(budget, remain))
        detail = None
        for line in out.splitlines():
            if line.startswith("BENCH_DETAIL "):
                try:
                    detail = json.loads(line[len("BENCH_DETAIL "):])
                except json.JSONDecodeError:
                    pass          # truncated line from a killed worker
        if rc == 0 and detail:
            details.append(detail)
            log(f"[bench] {name}: {json.dumps(detail)}")
        else:
            tail = (err or out).strip().splitlines()
            failures.append({"name": name, "rc": rc,
                             "error": tail[-1] if tail else "no output"})
            log(f"[bench] {name}: FAILED rc={rc} "
                f"({tail[-1] if tail else 'no output'})")

    total = sum(d.get("elapsed_s") or 0.0 for d in details)
    speedups = [d["speedup"] for d in details if d.get("speedup")]
    geomean = (float(np.exp(np.mean(np.log(speedups))))
               if speedups else 0.0)
    mfus = {d["name"]: d["mfu"] for d in details if d.get("mfu")}
    pipeline = next((d for d in details if d["name"] == "pipeline_ml100k"),
                    None)

    per_cfg = ", ".join(
        f"{d['name']} {d.get('speedup', '-')}x"
        + (f"/mfu {d['mfu']:.1%}" if d.get("mfu") else "")
        for d in details)
    unit = (f"seconds total across {len(details)}/{len(names)} configs on "
            f"{platform}; speedups [{per_cfg}]")
    if pipeline:
        unit += (f"; pio-train {pipeline['train_s']}s, query p50 "
                 f"{pipeline['query_p50_ms']}ms p99 "
                 f"{pipeline['query_p99_ms']}ms")

    # full per-config artifact for the judge
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_DETAILS.json"), "w") as f:
            json.dump({"platform": platform, "details": details,
                       "failures": failures, "mfu": mfus}, f, indent=1)
    except OSError:
        pass

    print(json.dumps({
        "metric": "judged_suite_wallclock",
        "value": round(total, 3),
        "unit": unit,
        "vs_baseline": round(geomean, 2),
    }))


if __name__ == "__main__":
    main()
