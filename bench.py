"""Benchmark: ALS training throughput on MovieLens-100K-scale data.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The judged config is `pio train` of the recommendation template on
MovieLens-100K (BASELINE.md config 1). The reference publishes no numbers
(BASELINE.md), so vs_baseline is measured in-process against a single-thread
numpy implementation of the same ALS math — the stand-in for the stock
CPU-bound Spark-local run until a real Spark baseline is recorded.
vs_baseline > 1 means the TPU path is faster.

MovieLens-100K shape: 943 users, 1682 items, 100k ratings; template defaults
rank=10, numIterations=20 (quickstart engine.json), ALS-WR regularization.
"""

from __future__ import annotations

import json
import time

import numpy as np

N_USERS, N_ITEMS, NNZ = 943, 1682, 100_000
RANK, ITERS, REG = 10, 20, 0.01


def synthetic_ml100k(seed=0):
    rng = np.random.default_rng(seed)
    users = rng.integers(0, N_USERS, NNZ).astype(np.int32)
    items = rng.integers(0, N_ITEMS, NNZ).astype(np.int32)
    latent_u = rng.normal(size=(N_USERS, 4))
    latent_v = rng.normal(size=(N_ITEMS, 4))
    raw = np.einsum("nk,nk->n", latent_u[users], latent_v[items])
    ratings = np.clip(np.round(2.5 + raw), 1, 5).astype(np.float32)
    return users, items, ratings


def numpy_als_sweep_time(users, items, ratings) -> float:
    """One user-side half-sweep in vectorized numpy (the CPU baseline)."""
    rng = np.random.default_rng(1)
    V = rng.normal(size=(N_ITEMS, RANK)).astype(np.float32) / np.sqrt(RANK)
    order = np.argsort(users, kind="stable")
    u_s, i_s, r_s = users[order], items[order], ratings[order]
    t0 = time.perf_counter()
    f = V[i_s]                                        # [nnz, K]
    outer = np.einsum("nk,nl->nkl", f, f)             # [nnz, K, K]
    gram = np.zeros((N_USERS, RANK, RANK), np.float32)
    np.add.at(gram, u_s, outer)
    rhs = np.zeros((N_USERS, RANK), np.float32)
    np.add.at(rhs, u_s, f * r_s[:, None])
    cnt = np.bincount(u_s, minlength=N_USERS).astype(np.float32)
    A = gram + (REG * np.maximum(cnt, 1.0))[:, None, None] * np.eye(RANK, dtype=np.float32)
    np.linalg.solve(A, rhs[..., None])
    return time.perf_counter() - t0


def main():
    import jax

    from jax.sharding import Mesh
    from predictionio_tpu.models.als import ALSData, ALSParams, train_als
    from predictionio_tpu.models.als import rmse as als_rmse

    users, items, ratings = synthetic_ml100k()

    # CPU numpy baseline: 1 half-sweep x 2 sides x ITERS, measured once
    base_sweep = numpy_als_sweep_time(users, items, ratings)
    baseline_total = base_sweep * 2 * ITERS

    devices = np.asarray(jax.devices())
    mesh = Mesh(devices.reshape(-1)[:1], axis_names=("data",))
    params = ALSParams(rank=RANK, num_iterations=ITERS, reg=REG,
                       chunk_size=16384)

    # warm-up (compile) then timed end-to-end train step: host data layout
    # (sort/shard, the DataSource->device path) + device training
    data = ALSData.build(users, items, ratings, N_USERS, N_ITEMS, n_shards=1)
    train_als(mesh, data, params)
    t0 = time.perf_counter()
    data = ALSData.build(users, items, ratings, N_USERS, N_ITEMS, n_shards=1)
    U, V = train_als(mesh, data, params)
    elapsed = time.perf_counter() - t0

    err = als_rmse(U, V, users, items, ratings)
    assert np.isfinite(err), "training diverged"

    print(json.dumps({
        "metric": "als_ml100k_train_wallclock",
        "value": round(elapsed, 4),
        "unit": f"seconds ({ITERS} iters, rank {RANK}, {NNZ} ratings, "
                f"train-RMSE {err:.3f}, {devices.size} device(s))",
        "vs_baseline": round(baseline_total / elapsed, 2),
    }))


if __name__ == "__main__":
    main()
